"""LM token serving: continuous batching over a paged KV cache.

The PR 9 serving engine batches INDEPENDENT one-shot forwards; an
autoregressive LM breaks that shape — one request is a prompt prefill
followed by a variable-length chain of single-token decode steps, and a
naive server runs each request's chain to completion while everyone
else queues.  This module serves tokens the Orca/vLLM way instead:

- **Iteration-level (continuous) batching.**  The scheduler owns
  ``maxBatch`` decode slots.  Every iteration dispatches ONE fused
  decode step over all occupied slots; sequences that finish (EOS,
  token budget, deadline) vacate their slot and free their KV blocks
  *that same iteration*, and waiting prompts prefill into the vacancy —
  no head-of-line blocking behind the longest generation.
- **Paged KV cache** (:class:`~bigdl_tpu.serving.kv_cache.PagedKVCache`):
  one fixed device pool of ``(layer, block, block_size, head,
  head_dim)`` K/V blocks sized once at construction (gated by the HBM
  preflight budget), a host free-list, and per-sequence block tables.
  Exhaustion is a structured retriable ``Overloaded`` at admission —
  never a device OOM mid-decode.
- **One decode shape.**  The decode step always runs at ``(maxBatch,
  1)`` with inactive slots masked (their scatters land in the reserved
  dump block); prefill pads to a small bucket ladder.  Both compile
  through ``compile_cache.tracked_jit`` under the strict retrace
  sentinel — zero post-warmup retraces is test- and bench-asserted,
  exactly the PR 7 contract extended to decode.
- **Streaming output.**  ``submit()`` returns a :class:`TokenStream`
  whose iterator yields tokens as the scheduler emits them; TTFT and
  inter-token latency land in exact windowed percentile histograms
  (``LM/ttft_ms``, ``LM/itl_ms``).
- **int8 weight tier** (``bigdl.lm.quantize=int8``): decode matmuls run
  against per-output-channel symmetric int8 weights dequantized in the
  contraction.  The tier only serves after passing a two-part gate at
  construction — the HLO auditor's precision-drift pass over the
  quantized program AND an fp-vs-int8 logits ``allclose`` on identical
  KV-pool inputs (:class:`QuantizationGateError` otherwise).

Failure taxonomy, admission control (queue bound, cooldown, projected
wait), deadline shedding, poison quarantine, the hung-dispatch
watchdog, drain-on-preemption, and the accounting identity
``completed + shed + rejected + quarantined == submitted`` are all the
PR 9 machinery reused verbatim — a token stream that failed after
emitting some tokens keeps them and terminates with the structured
error saying why.
"""

from __future__ import annotations

import logging
import math
import queue
import threading
import time
from collections import deque
from contextlib import nullcontext
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import analysis, telemetry
from bigdl_tpu.resources import GOVERNOR as _resource_governor
from bigdl_tpu.resources import item_nbytes as _item_nbytes
from bigdl_tpu.telemetry import incident, request_trace
from bigdl_tpu.serving.engine import (OUTCOMES, DeadlineExceeded,
                                      HungDispatchError,
                                      HungDispatchWatchdog, Overloaded,
                                      ServingDataError, ServingInfraError,
                                      _service_ema)
from bigdl_tpu.serving.kv_cache import DUMP_BLOCK, PagedKVCache
from bigdl_tpu.utils import elastic

logger = logging.getLogger("bigdl_tpu")


class UnsupportedModelError(ValueError):
    """The served model is not the decoder-only transformer shape this
    engine knows how to dissect (``models.transformer.transformer_lm``).
    Structured — names the exact structural mismatch — because the
    silent alternative is a decode path that reads the wrong weights."""

    def __init__(self, what: str):
        super().__init__(
            f"LMServingEngine serves transformer_lm-shaped models "
            f"(LookupTable, PositionalEncoding, n x decoder block, "
            f"LayerNorm, Linear, LogSoftMax); {what}")


class QuantizationGateError(ValueError):
    """The int8 decode tier failed its admission gate (auditor
    precision-drift pass, or the fp-vs-int8 logits allclose check) —
    the engine refuses to serve quantized rather than drift silently."""


# ---------------------------------------------------------------------------
# model dissection
# ---------------------------------------------------------------------------


class _LMGraph:
    """Static description of a ``transformer_lm`` model: the per-layer
    modules (weights are read through each module's adopted ``.params``
    view, never positional index math) plus the dims the decode step
    closes over."""

    def __init__(self, model):
        import bigdl_tpu.nn as nn
        from bigdl_tpu.models.transformer import (LayerNorm,
                                                  PositionalEncoding,
                                                  _Residual)
        if not isinstance(model, nn.Sequential):
            raise UnsupportedModelError(
                f"got a {type(model).__name__}, not a Sequential")
        ch = list(model.children)
        if len(ch) < 6:
            raise UnsupportedModelError(
                f"expected >= 6 children, got {len(ch)}")
        embed, pos = ch[0], ch[1]
        lnf, head, logsm = ch[-3], ch[-2], ch[-1]
        if not isinstance(embed, nn.LookupTable):
            raise UnsupportedModelError(
                f"child 0 is {type(embed).__name__}, not LookupTable")
        if embed.max_norm != float("inf"):
            raise UnsupportedModelError(
                "LookupTable max-norm renormalisation is not folded into "
                "the decode path")
        if not isinstance(pos, PositionalEncoding):
            raise UnsupportedModelError(
                f"child 1 is {type(pos).__name__}, not PositionalEncoding")
        if not isinstance(lnf, LayerNorm):
            raise UnsupportedModelError(
                f"child -3 is {type(lnf).__name__}, not the final LayerNorm")
        if not isinstance(head, nn.Linear):
            raise UnsupportedModelError(
                f"child -2 is {type(head).__name__}, not the Linear head")
        if not isinstance(logsm, nn.LogSoftMax):
            raise UnsupportedModelError(
                f"child -1 is {type(logsm).__name__}, not LogSoftMax")
        self.layers: List[Dict[str, Any]] = []
        for bi, raw in enumerate(ch[2:-3]):
            blk = raw.children[0] if isinstance(raw, nn.Remat) else raw
            if not (isinstance(blk, nn.Sequential) and
                    len(blk.children) == 2 and
                    all(isinstance(r, _Residual) for r in blk.children)):
                raise UnsupportedModelError(
                    f"block {bi} is not a pair of pre-norm residuals")
            attn_res, ffn_res = blk.children
            ln1, attn = attn_res.children
            ln2, ffn = ffn_res.children
            if not isinstance(attn, nn.MultiHeadAttention):
                raise UnsupportedModelError(
                    f"block {bi} residual 0 wraps {type(attn).__name__}, "
                    "not MultiHeadAttention")
            if not attn.causal:
                raise UnsupportedModelError(
                    f"block {bi} attention is not causal — an acausal "
                    "model has no autoregressive decode")
            if not (isinstance(ffn, nn.Sequential) and
                    len(ffn.children) == 3 and
                    isinstance(ffn.children[0], nn.Linear) and
                    isinstance(ffn.children[1], nn.ReLU) and
                    isinstance(ffn.children[2], nn.Linear)):
                raise UnsupportedModelError(
                    f"block {bi} FFN is not Linear/ReLU/Linear (MoE blocks "
                    "have no single-token decode path yet)")
            self.layers.append({"ln1": ln1, "attn": attn, "ln2": ln2,
                                "up": ffn.children[0],
                                "down": ffn.children[2]})
        if not self.layers:
            raise UnsupportedModelError("model has no decoder blocks")
        heads = {l["attn"].n_head for l in self.layers}
        if len(heads) != 1:
            raise UnsupportedModelError(
                f"heterogeneous head counts across blocks: {sorted(heads)}")
        self.model = model
        self.embed = embed
        self.pos = pos
        self.lnf = lnf
        self.head = head
        self.vocab = int(head.output_size)
        self.d_model = int(embed.n_output)
        self.n_head = int(self.layers[0]["attn"].n_head)
        self.head_dim = int(self.layers[0]["attn"].head_dim)
        self.n_layers = len(self.layers)
        self.max_seq_len = int(pos.max_seq_len)


def _linear_entry(weight, bias) -> Dict[str, Any]:
    return {"w": weight, "b": bias}


def _extract_params(graph: _LMGraph) -> Dict[str, Any]:
    """Snapshot the model's weights into the decode pytree.  Root
    ``.params`` is touched first so lazy init + child adoption happen
    once; every leaf is then the module's own adopted view."""
    _ = graph.model.params
    layers = []
    for l in graph.layers:
        ap, wb = l["attn"].params, l["attn"].with_bias
        layers.append({
            "ln1": {"w": l["ln1"].params["weight"],
                    "b": l["ln1"].params["bias"]},
            "attn": {k: _linear_entry(ap[f"w{k[-1]}"],
                                      ap[f"b{k[-1]}"] if wb else None)
                     for k in ("wq", "wk", "wv", "wo")},
            "ln2": {"w": l["ln2"].params["weight"],
                    "b": l["ln2"].params["bias"]},
            "ffn": {"up": _linear_entry(
                        l["up"].params["weight"],
                        l["up"].params["bias"] if l["up"].with_bias
                        else None),
                    "down": _linear_entry(
                        l["down"].params["weight"],
                        l["down"].params["bias"] if l["down"].with_bias
                        else None)},
        })
    return {"embed": graph.embed.params["weight"],
            "layers": layers,
            "lnf": {"w": graph.lnf.params["weight"],
                    "b": graph.lnf.params["bias"]},
            "head": _linear_entry(
                graph.head.params["weight"],
                graph.head.params["bias"] if graph.head.with_bias
                else None)}


def _quantize_entry(entry: Dict[str, Any]) -> Dict[str, Any]:
    """Per-output-channel symmetric int8: ``s = max|w| / 127`` over the
    input axis, ``q = round(w / s)``.  Dequantization happens in the
    contraction (``(x @ q) * s``), so the auditor's precision pass sees
    an f32 dot — the tier changes storage, not accumulation dtype."""
    w = entry["w"]
    s = jnp.max(jnp.abs(w), axis=0) / 127.0
    s = jnp.where(s == 0, 1.0, s)
    q = jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s, "b": entry["b"]}


def _quantize_params(dp: Dict[str, Any]) -> Dict[str, Any]:
    """int8-quantize every decode matmul; embeddings (a gather) and the
    layer norms stay fp."""
    layers = []
    for l in dp["layers"]:
        layers.append({
            "ln1": l["ln1"],
            "attn": {k: _quantize_entry(e) for k, e in l["attn"].items()},
            "ln2": l["ln2"],
            "ffn": {k: _quantize_entry(e) for k, e in l["ffn"].items()},
        })
    return {"embed": dp["embed"], "layers": layers, "lnf": dp["lnf"],
            "head": _quantize_entry(dp["head"])}


def _apply_linear(x, e):
    """One decode matmul against an fp (``w``) or int8 (``q``/``s``)
    entry — the branch is on pytree STRUCTURE, resolved at trace time,
    so fp and int8 programs compile under their own labels."""
    if "q" in e:
        y = (x @ e["q"].astype(x.dtype)) * e["s"]
    else:
        y = x @ e["w"]
    if e.get("b") is not None:
        y = y + e["b"]
    return y


def _layer_norm(x, p, eps):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    return out * p["w"] + p["b"]


# ---------------------------------------------------------------------------
# step builders (pure functions over the decode pytree + KV pools)
# ---------------------------------------------------------------------------


def _build_decode_fn(graph: _LMGraph, block_size: int, max_blocks: int):
    """One fused decode iteration at the FIXED ``(maxBatch, 1)`` shape:
    embed + positional row, per layer scatter this step's k/v into the
    paged pool (BEFORE the gather, so the current token attends itself),
    gather each sequence's table context, masked paged attention, FFN;
    returns next-token log-probs and the updated pools.  Inactive slots
    compute junk that scatters into the dump block and is discarded on
    the host — occupancy can never mint a new signature."""
    from bigdl_tpu.nn.attention import paged_attention
    pe = graph.pos.pe
    vocab_in = int(graph.embed.n_index)
    H, Dh = graph.n_head, graph.head_dim
    eps1 = [l["ln1"].eps for l in graph.layers]
    eps2 = [l["ln2"].eps for l in graph.layers]
    eps_f = graph.lnf.eps
    S = max_blocks * block_size

    def decode(dp, pool_k, pool_v, tokens, positions, tables, active):
        B = tokens.shape[0]
        idx = jnp.clip(tokens.astype(jnp.int32) - 1, 0, vocab_in - 1)
        x = jnp.take(dp["embed"], idx, axis=0)
        x = x + jnp.take(pe, positions, axis=0)[:, None, :].astype(x.dtype)
        blk = jnp.where(active, tables[jnp.arange(B),
                                       positions // block_size],
                        DUMP_BLOCK)
        slot = positions % block_size
        valid = ((jnp.arange(S)[None, :] <= positions[:, None]) &
                 active[:, None])
        for li, lyr in enumerate(dp["layers"]):
            h = _layer_norm(x, lyr["ln1"], eps1[li])
            q = _apply_linear(h, lyr["attn"]["wq"]).reshape(B, 1, H, Dh)
            k = _apply_linear(h, lyr["attn"]["wk"]).reshape(B, 1, H, Dh)
            v = _apply_linear(h, lyr["attn"]["wv"]).reshape(B, 1, H, Dh)
            pool_k = pool_k.at[li, blk, slot].set(k[:, 0])
            pool_v = pool_v.at[li, blk, slot].set(v[:, 0])
            k_ctx = pool_k[li][tables].reshape(B, S, H, Dh)
            v_ctx = pool_v[li][tables].reshape(B, S, H, Dh)
            att = paged_attention(q, k_ctx, v_ctx, valid)
            x = x + _apply_linear(att.reshape(B, 1, H * Dh),
                                  lyr["attn"]["wo"])
            h = _layer_norm(x, lyr["ln2"], eps2[li])
            h = jax.nn.relu(_apply_linear(h, lyr["ffn"]["up"]))
            x = x + _apply_linear(h, lyr["ffn"]["down"])
        x = _layer_norm(x, dp["lnf"], eps_f)
        logits = _apply_linear(x[:, 0], dp["head"])
        return jax.nn.log_softmax(logits, axis=-1), pool_k, pool_v

    return decode


def _build_prefill_fn(graph: _LMGraph, block_size: int):
    """Bucketed prompt prefill: dense causal attention over the padded
    span (padding sits AFTER every real query, so the causal mask alone
    keeps it out of every real row), scattering each real position's
    k/v into the sequence's blocks (padded rows hit the dump block).
    Returns the last REAL position's log-probs + the updated pools."""
    pe = graph.pos.pe
    vocab_in = int(graph.embed.n_index)
    H, Dh = graph.n_head, graph.head_dim
    eps1 = [l["ln1"].eps for l in graph.layers]
    eps2 = [l["ln2"].eps for l in graph.layers]
    eps_f = graph.lnf.eps
    from bigdl_tpu.nn.attention import scaled_dot_product_attention

    def prefill(dp, pool_k, pool_v, tokens, length, table):
        T = tokens.shape[1]
        idx = jnp.clip(tokens.astype(jnp.int32) - 1, 0, vocab_in - 1)
        x = jnp.take(dp["embed"], idx, axis=0)
        x = x + pe[:T][None].astype(x.dtype)
        pos = jnp.arange(T)
        blkrow = jnp.where(pos < length, table[pos // block_size],
                           DUMP_BLOCK)
        slotrow = pos % block_size
        for li, lyr in enumerate(dp["layers"]):
            h = _layer_norm(x, lyr["ln1"], eps1[li])
            q = _apply_linear(h, lyr["attn"]["wq"]).reshape(1, T, H, Dh)
            k = _apply_linear(h, lyr["attn"]["wk"]).reshape(1, T, H, Dh)
            v = _apply_linear(h, lyr["attn"]["wv"]).reshape(1, T, H, Dh)
            pool_k = pool_k.at[li, blkrow, slotrow].set(k[0])
            pool_v = pool_v.at[li, blkrow, slotrow].set(v[0])
            att = scaled_dot_product_attention(q, k, v, causal=True)
            x = x + _apply_linear(att.reshape(1, T, H * Dh),
                                  lyr["attn"]["wo"])
            h = _layer_norm(x, lyr["ln2"], eps2[li])
            h = jax.nn.relu(_apply_linear(h, lyr["ffn"]["up"]))
            x = x + _apply_linear(h, lyr["ffn"]["down"])
        x = _layer_norm(x, dp["lnf"], eps_f)
        logits = _apply_linear(x[0], dp["head"])
        logp = jax.nn.log_softmax(logits, axis=-1)
        return jnp.take(logp, length - 1, axis=0), pool_k, pool_v

    return prefill


def _build_full_fn(graph: _LMGraph):
    """Teacher-forced full forward over a (1, T) span -> (T, vocab)
    log-probs: the sequential-generation baseline AND the
    decode-parity reference (same closure math as prefill, no pool)."""
    pe = graph.pos.pe
    vocab_in = int(graph.embed.n_index)
    H, Dh = graph.n_head, graph.head_dim
    eps1 = [l["ln1"].eps for l in graph.layers]
    eps2 = [l["ln2"].eps for l in graph.layers]
    eps_f = graph.lnf.eps
    from bigdl_tpu.nn.attention import scaled_dot_product_attention

    def full(dp, tokens):
        T = tokens.shape[1]
        idx = jnp.clip(tokens.astype(jnp.int32) - 1, 0, vocab_in - 1)
        x = jnp.take(dp["embed"], idx, axis=0)
        x = x + pe[:T][None].astype(x.dtype)
        for li, lyr in enumerate(dp["layers"]):
            h = _layer_norm(x, lyr["ln1"], eps1[li])
            q = _apply_linear(h, lyr["attn"]["wq"]).reshape(1, T, H, Dh)
            k = _apply_linear(h, lyr["attn"]["wk"]).reshape(1, T, H, Dh)
            v = _apply_linear(h, lyr["attn"]["wv"]).reshape(1, T, H, Dh)
            att = scaled_dot_product_attention(q, k, v, causal=True)
            x = x + _apply_linear(att.reshape(1, T, H * Dh),
                                  lyr["attn"]["wo"])
            h = _layer_norm(x, lyr["ln2"], eps2[li])
            h = jax.nn.relu(_apply_linear(h, lyr["ffn"]["up"]))
            x = x + _apply_linear(h, lyr["ffn"]["down"])
        x = _layer_norm(x, dp["lnf"], eps_f)
        logits = _apply_linear(x[0], dp["head"])
        return jax.nn.log_softmax(logits, axis=-1)

    return full


# ---------------------------------------------------------------------------
# streaming handle
# ---------------------------------------------------------------------------


class TokenStream:
    """One admitted generation request: a streaming token iterator plus
    a one-shot terminal state that is exactly one of :data:`OUTCOMES`
    (first-wins, like the PR 9 ``RequestHandle`` — a stream can never
    be both shed by the drain and completed by a racing decode).

    Iterating yields tokens AS THE SCHEDULER EMITS THEM; when the
    stream terminates with an error (deadline, hang, drain), iteration
    raises it after the already-streamed tokens — a partially-streamed-
    then-failed request keeps its prefix and learns why it stopped."""

    __slots__ = ("prompt", "index", "seq_id", "max_new_tokens", "eos_id",
                 "submit_ns", "deadline_ns", "first_token_ns", "finish_ns",
                 "outcome", "payload_nbytes", "_tokens", "_error",
                 "_terminal", "_cv", "trace_id")

    def __init__(self, prompt, index: int, submit_ns: int, deadline_ns: int,
                 max_new_tokens: int, eos_id: Optional[int],
                 trace_id: Optional[str] = None):
        self.prompt = prompt
        self.index = index          # admission position (chaos plans key on it)
        self.seq_id = index         # KV-cache sequence id
        self.trace_id = trace_id    # None when request tracing is disarmed
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.submit_ns = submit_ns
        self.deadline_ns = deadline_ns
        self.first_token_ns: Optional[int] = None       # guarded-by: _cv
        self.finish_ns: Optional[int] = None            # guarded-by: _cv
        self.outcome: Optional[str] = None              # guarded-by: _cv
        self.payload_nbytes = 0     # guarded-by: _cv — host bytes charged to the governor
        self._tokens: List[int] = []                    # guarded-by: _cv
        self._error: Optional[BaseException] = None     # guarded-by: _cv
        self._terminal = False                          # guarded-by: _cv
        self._cv = analysis.make_condition("lm.stream")

    # -- scheduler side ---------------------------------------------------

    def _emit(self, tok: int) -> None:
        with self._cv:
            if self._terminal:
                return
            self._tokens.append(int(tok))
            if self.first_token_ns is None:
                self.first_token_ns = telemetry.clock_ns()
            self._cv.notify_all()

    def _finish(self, outcome: str,
                error: Optional[BaseException] = None) -> bool:
        with self._cv:
            if self._terminal:
                return False
            self.outcome = outcome
            self._error = error
            self.finish_ns = telemetry.clock_ns()
            self._terminal = True
            self._cv.notify_all()
        return True

    # -- client side ------------------------------------------------------

    def __iter__(self):
        # bounded: at most max_new_tokens yields, then the terminal check
        for i in range(self.max_new_tokens + 1):
            with self._cv:
                while len(self._tokens) <= i and not self._terminal:
                    self._cv.wait(0.05)
                if i >= len(self._tokens):
                    break
                tok = self._tokens[i]
            yield tok
        if self._error is not None:
            raise self._error

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until terminal; the full token list, or raises the
        terminal error (tokens streamed before the failure stay
        readable via :meth:`tokens`)."""
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        with self._cv:
            while not self._terminal:
                if deadline is not None and time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"stream {self.index} still in flight after "
                        f"{timeout} s")
                self._cv.wait(0.05)
        if self._error is not None:
            raise self._error
        return list(self._tokens)

    def tokens(self) -> List[int]:
        """Tokens streamed so far (snapshot; no blocking)."""
        with self._cv:
            return list(self._tokens)

    def done(self) -> bool:
        return self._terminal

    def error(self) -> Optional[BaseException]:
        return self._error if self._terminal else None

    def ttft_ms(self) -> Optional[float]:
        if self.first_token_ns is None:
            return None
        return (self.first_token_ns - self.submit_ns) / 1e6

    def latency_ms(self) -> Optional[float]:
        if self.finish_ns is None:
            return None
        return (self.finish_ns - self.submit_ns) / 1e6


class _Slot:
    """One occupied decode slot: the stream plus its device-side cursor
    (``position`` = the pool position the NEXT fed token writes)."""

    __slots__ = ("stream", "position", "generated", "last_token",
                 "table_row", "last_emit_ns")

    def __init__(self, stream: TokenStream, position: int, last_token: int,
                 table_row: np.ndarray):
        self.stream = stream
        self.position = position
        self.generated = 1          # prefill emitted the first token
        self.last_token = last_token
        self.table_row = table_row
        self.last_emit_ns = telemetry.clock_ns()


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------


def _tree_spec(tree):
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree)


class LMServingEngine:
    """Continuous-batching token server over ONE decoder-only LM.

    All knobs default from ``bigdl.lm.*`` (see ``docs/configuration.md``);
    constructor arguments override per-engine.  ``submit()`` streams;
    ``generate()`` / ``generate_sequential()`` are the offline
    paged-vs-teacher-forced pair the parity proof and the bench's
    baseline lean on."""

    def __init__(self, model, max_batch: Optional[int] = None,
                 max_context: Optional[int] = None,
                 block_size: Optional[int] = None,
                 cache_blocks: Optional[int] = None,
                 max_new_tokens: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 max_queue_depth: Optional[int] = None,
                 quantize: Optional[str] = None,
                 start: bool = False):
        from bigdl_tpu.utils import config
        self.graph = _LMGraph(model)
        self.max_batch = int(max_batch if max_batch is not None else
                             config.get_int("bigdl.lm.maxBatch", 8))
        self.max_context = int(
            max_context if max_context is not None else
            config.get_int("bigdl.lm.maxContext", 256))
        self.block_size = int(
            block_size if block_size is not None else
            config.get_int("bigdl.lm.blockSize", 16))
        self.max_new_tokens = int(
            max_new_tokens if max_new_tokens is not None else
            config.get_int("bigdl.lm.maxNewTokens", 64))
        self.deadline_ms = float(
            deadline_ms if deadline_ms is not None else
            config.get_float("bigdl.lm.deadlineMs", 5000.0))
        self.max_queue_depth = int(
            max_queue_depth if max_queue_depth is not None else
            config.get_int("bigdl.lm.maxQueueDepth", 128))
        self.admission_factor = config.get_float(
            "bigdl.lm.admissionDeadlineFactor", 0.0)
        self.stall_factor = config.get_float("bigdl.lm.stallFactor", 0.0)
        self.warmup_steps = config.get_int("bigdl.lm.warmupSteps", 3)
        self.cooldown_steps = config.get_int("bigdl.lm.cooldownSteps", 8)
        self.grace_period = config.get_float("bigdl.lm.gracePeriod", 5.0)
        self.poll_interval = config.get_float("bigdl.lm.pollInterval", 0.01)
        self.quantize = str(
            quantize if quantize is not None else
            config.get_property("bigdl.lm.quantize", "off") or "off").lower()
        if self.quantize not in ("off", "int8"):
            raise ValueError(
                f"bigdl.lm.quantize must be 'off' or 'int8', got "
                f"{self.quantize!r}")
        self.quantize_rtol = config.get_float("bigdl.lm.quantizeRtol", 0.05)
        self.quantize_atol = config.get_float("bigdl.lm.quantizeAtol", 0.05)
        if self.max_context > self.graph.max_seq_len:
            raise ValueError(
                f"bigdl.lm.maxContext {self.max_context} exceeds the "
                f"model's PositionalEncoding max_len "
                f"{self.graph.max_seq_len} — build the model with a "
                "larger max_len or lower maxContext")
        if self.max_batch < 1 or self.max_new_tokens < 1:
            raise ValueError("maxBatch and maxNewTokens must be >= 1")

        # -- KV pool: sized ONCE, preflighted against the HBM budget ------
        self._max_blocks = max(1, math.ceil(self.max_context /
                                            self.block_size))
        n_blocks = int(cache_blocks if cache_blocks is not None else
                       config.get_int("bigdl.lm.cacheBlocks", 0))
        if n_blocks <= 0:
            n_blocks = self.max_batch * self._max_blocks + 1
        self.cache = PagedKVCache(self.graph.n_layers, self.graph.n_head,  # guarded-by: _lock
                                  self.graph.head_dim, n_blocks,
                                  self.block_size)
        self._buckets = self._bucket_plan(
            config.get_property("bigdl.lm.prefillBuckets", None))

        # -- compiled steps + retrace sentinels ---------------------------
        self._dp = _extract_params(self.graph)
        self._dp_q = (_quantize_params(self._dp)
                      if self.quantize == "int8" else None)
        self._build_steps()

        # -- scheduler state (PR 9 idioms) --------------------------------
        self._q: "queue.Queue[TokenStream]" = queue.Queue(
            maxsize=self.max_queue_depth)
        self._pending: "deque[TokenStream]" = deque(   # guarded-by: _lock
            maxlen=self.max_queue_depth)
        self._slots: List[Optional[_Slot]] = [None] * self.max_batch
        # the stream currently mid-admission: the watchdog's async abort
        # (PyThreadState_SetAsyncExc) can surface anywhere in the
        # scheduler thread, so a stream popped from the queue must never
        # live only in a local — _shed_active covers this field
        self._admitting: Optional[TokenStream] = None
        self._lock = analysis.make_lock("lm.engine")
        self._payload_acct = _resource_governor.account("lm_admission")
        self._counts: Dict[str, int] = dict.fromkeys(OUTCOMES, 0)  # guarded-by: _lock
        self._counts["submitted"] = 0
        self._next_index = 0
        self._offline_id = 0
        self._cooldown = 0
        self._draining = False                          # guarded-by: _lock
        self._drain_deadline: Optional[float] = None    # guarded-by: _lock
        self._drain_reason = ""                         # guarded-by: _lock
        self._closed = False                            # guarded-by: _lock
        self._started = False                           # guarded-by: _lock
        self._stop_event = threading.Event()
        self._ema = _service_ema(self.warmup_steps)
        self.decode_steps = 0
        self.prefills = 0                               # guarded-by: _lock
        self.tokens_out = 0
        self.watchdog: Optional[HungDispatchWatchdog] = None
        self._thread: Optional[threading.Thread] = None
        window = config.get_int("bigdl.telemetry.percentileWindow", 512)
        self._ttft = telemetry.histogram(
            "LM/ttft_ms", window=window,
            help="submit-to-first-token latency")
        self._itl = telemetry.histogram(
            "LM/itl_ms", window=window,
            help="inter-token gap during streaming decode")
        self._latency = telemetry.histogram(
            "LM/latency_ms", window=window,
            help="per-request submit-to-terminal latency")

        self.quantization_report: Optional[Dict[str, Any]] = None
        if self._dp_q is not None:
            self._quantization_gate()
        if start:
            self.start()

    # -- compile plan -----------------------------------------------------

    def _bucket_plan(self, spec) -> List[int]:
        """Prefill shape ladder: configured ``bigdl.lm.prefillBuckets``
        or a power-of-two ladder from blockSize up; maxContext is
        always IN the plan so the longest admissible prompt has a
        warmed signature."""
        if spec:
            buckets = sorted({int(b) for b in str(spec).split(",") if
                              str(b).strip()})
            if not buckets or buckets[0] < 1:
                raise ValueError(
                    f"bigdl.lm.prefillBuckets must be positive ints, got "
                    f"{spec!r}")
            if buckets[-1] > self.max_context:
                raise ValueError(
                    f"bigdl.lm.prefillBuckets {buckets[-1]} exceeds "
                    f"bigdl.lm.maxContext {self.max_context}")
        else:
            buckets, b = [], max(1, self.block_size)
            for _ in range(64):
                if b >= self.max_context:
                    break
                buckets.append(b)
                b *= 2
        return sorted(set(buckets + [self.max_context]))

    def _prefill_bucket(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        return self._buckets[-1]

    def _build_steps(self) -> None:
        from bigdl_tpu.analysis.program_contracts import (
            lm_decode_contract, lm_full_contract, lm_prefill_contract)
        from bigdl_tpu.analysis.retrace import RetraceSentinel
        from bigdl_tpu.utils.compile_cache import tracked_jit
        B, MB = self.max_batch, self._max_blocks
        pool = jax.ShapeDtypeStruct(self.cache.k.shape, self.cache.k.dtype)
        dec_tail = (pool, pool,
                    jax.ShapeDtypeStruct((B, 1), jnp.int32),
                    jax.ShapeDtypeStruct((B,), jnp.int32),
                    jax.ShapeDtypeStruct((B, MB), jnp.int32),
                    jax.ShapeDtypeStruct((B,), jnp.bool_))
        decode = _build_decode_fn(self.graph, self.block_size, MB)
        prefill = _build_prefill_fn(self.graph, self.block_size)
        full = _build_full_fn(self.graph)

        def wire(fn, label, contract, specs_list):
            cached = tracked_jit(fn, label, contract=contract)
            sentinel = RetraceSentinel.from_config(label)
            if sentinel is not None:
                cached.register_sentinel(sentinel)
                for specs in specs_list:
                    sentinel.register_warmup(specs)
                return sentinel.wrap(cached), cached, sentinel
            return cached, cached, None

        self._decode_specs = (_tree_spec(self._dp),) + dec_tail
        self._decode, self._decode_cached, self._decode_sentinel = wire(
            decode, "lm_decode", lm_decode_contract(),
            [self._decode_specs])
        self._prefill_specs = {
            b: (_tree_spec(self._dp), pool, pool,
                jax.ShapeDtypeStruct((1, b), jnp.int32),
                jax.ShapeDtypeStruct((), jnp.int32),
                jax.ShapeDtypeStruct((MB,), jnp.int32))
            for b in self._buckets}
        self._prefill, self._prefill_cached, self._prefill_sentinel = wire(
            prefill, "lm_prefill", lm_prefill_contract(),
            list(self._prefill_specs.values()))
        self._full_specs = {
            b: (_tree_spec(self._dp),
                jax.ShapeDtypeStruct((1, b), jnp.int32))
            for b in self._buckets}
        self._full, self._full_cached, self._full_sentinel = wire(
            full, "lm_full", lm_full_contract(),
            list(self._full_specs.values()))
        if self._dp_q is not None:
            self._decode_q_specs = (_tree_spec(self._dp_q),) + dec_tail
            (self._decode_q, self._decode_q_cached,
             self._decode_q_sentinel) = wire(
                decode, "lm_decode_int8",
                lm_decode_contract("lm_decode_int8"),
                [self._decode_q_specs])
        else:
            self._decode_q = self._decode_q_cached = None
            self._decode_q_sentinel = None

    @property
    def sentinels(self) -> Dict[str, Any]:
        """Label -> retrace sentinel for every compiled LM step (absent
        labels ran without a sentinel) — the zero-post-warmup-retrace
        proof reads ``.retraces`` off each."""
        out = {}
        for label, s in (("lm_decode", self._decode_sentinel),
                         ("lm_prefill", self._prefill_sentinel),
                         ("lm_full", self._full_sentinel),
                         ("lm_decode_int8", self._decode_q_sentinel)):
            if s is not None:
                out[label] = s
        return out

    def warmup(self) -> None:
        """AOT-compile every planned signature (decode at its one fixed
        shape, each prefill/full bucket, the int8 tier when enabled) so
        no request ever pays a compile against its deadline."""
        self._decode_cached.warmup(*self._decode_specs)
        for specs in self._prefill_specs.values():
            self._prefill_cached.warmup(*specs)
        for specs in self._full_specs.values():
            self._full_cached.warmup(*specs)
        if self._decode_q_cached is not None:
            self._decode_q_cached.warmup(*self._decode_q_specs)

    # -- int8 gate --------------------------------------------------------

    def _quantization_gate(self) -> None:
        """Admission gate for the int8 decode tier: (1) the HLO
        auditor's precision-drift pass over the quantized program, (2)
        fp-vs-int8 next-token log-probs allclose on IDENTICAL KV-pool
        inputs.  Either failing raises :class:`QuantizationGateError` —
        the engine never silently serves drifted logits."""
        from bigdl_tpu.analysis import hlo_audit
        from bigdl_tpu.analysis.hostsync import host_pull
        from bigdl_tpu.analysis.program_contracts import lm_decode_contract
        # audit-only lowering — the gate inspects HLO text; serving
        # dispatch still goes through the tracked CachedStep
        lowered = self._decode_q_cached.lower(  # lint: allow(untracked-jit)
            *self._decode_q_specs)
        report = hlo_audit.audit_step(
            "lm_decode_int8", lowered.as_text(),
            contract=lm_decode_contract("lm_decode_int8"))
        B, MB = self.max_batch, self._max_blocks
        P = max(1, min(8, self.max_context - 1))
        prompt = (np.arange(P, dtype=np.int32) % self.graph.vocab) + 1
        seq_id = -1
        self.cache.allocate(seq_id, P + 1)
        try:
            tok, table_row = self._prefill_step_raw(seq_id, prompt)
            tokens = np.full((B, 1), 1, np.int32)
            positions = np.zeros((B,), np.int32)
            tables = np.full((B, MB), DUMP_BLOCK, np.int32)
            active = np.zeros((B,), bool)
            tokens[0, 0], positions[0] = tok, P
            tables[0], active[0] = table_row, True
            args = (self.cache.k, self.cache.v, tokens, positions, tables,
                    active)
            lp_fp = self._decode(self._dp, *args)[0]
            lp_q = self._decode_q(self._dp_q, *args)[0]
            a = np.asarray(host_pull(lp_fp, what="lm gate fp logits"))[0]
            b = np.asarray(host_pull(lp_q, what="lm gate int8 logits"))[0]
        finally:
            self.cache.free_seq(seq_id)
        close = bool(np.allclose(b, a, rtol=self.quantize_rtol,
                                 atol=self.quantize_atol))
        diff = float(np.max(np.abs(b - a)))
        self.quantization_report = {
            "audit_ok": bool(report.ok),
            "violations": [str(v) for v in report.violations],
            "allclose": close, "max_abs_diff": diff,
            "rtol": self.quantize_rtol, "atol": self.quantize_atol}
        if not report.ok:
            raise QuantizationGateError(
                "int8 decode tier failed the auditor precision gate: "
                + "; ".join(str(v) for v in report.violations))
        if not close:
            raise QuantizationGateError(
                f"int8 decode logits drifted past the gate: max |diff| "
                f"{diff:.4g} vs rtol={self.quantize_rtol} "
                f"atol={self.quantize_atol} — raise the thresholds "
                "explicitly or serve fp")

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "LMServingEngine":
        if self._closed:
            raise ServingInfraError(
                "engine is terminal: stop() is one-way — build a new "
                "engine instead of restarting this one")
        if self._started:
            return self
        with self._lock:
            self._started = True
        self._thread = threading.Thread(target=self._scheduler_loop,
                                        daemon=True, name="lm-scheduler")
        self._thread.start()
        return self

    def stop(self, grace: Optional[float] = None) -> None:
        """Graceful shutdown (idempotent + terminal, the PR 9
        contract): admission closes, queued prompts and in-flight
        sequences drain within ``grace``, leftovers are shed
        retriably."""
        if not self._started or self._closed:
            with self._lock:
                self._closed = True
            self._drain_leftovers()
            return
        with self._lock:
            if not self._draining:
                self._begin_drain_locked("stop", time.monotonic(), grace)
            elif grace is not None:
                self._drain_deadline = time.monotonic() + grace
        self._stop_event.set()
        t = self._thread
        if t is not None:
            budget = grace if grace is not None else self.grace_period
            t.join(timeout=budget + 10.0)
        self._drain_leftovers()
        with self._lock:
            self._closed = True

    def close(self) -> None:
        self.stop()

    def __enter__(self) -> "LMServingEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def terminal(self) -> bool:
        return self._closed

    @property
    def draining(self) -> bool:
        return self._draining

    def queue_depth(self) -> int:
        return self._q.qsize() + len(self._pending)

    def scheduler_alive(self) -> bool:
        t = self._thread
        return bool(t is not None and t.is_alive())

    # -- admission --------------------------------------------------------

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               eos_id: Optional[int] = None) -> TokenStream:
        """Admit one prompt or raise :class:`Overloaded` — fast, at the
        door.  Returns the streaming :class:`TokenStream` handle."""
        now = telemetry.clock_ns()
        deadline = float(deadline_ms if deadline_ms is not None
                         else self.deadline_ms)
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else self.max_new_tokens)
        payload_nbytes = _item_nbytes(prompt)
        _resource_governor.check_item("lm_admission", payload_nbytes)
        telemetry.counter("LM/submitted").inc()
        # trace id minted at the admission door — BEFORE the rejection
        # checks, so a rejected prompt still explains itself
        tid = request_trace.mint("lm", deadline_ms=deadline,
                                 max_new_tokens=max_new)
        with self._lock:
            self._counts["submitted"] += 1
            if self._closed or (self._stop_event.is_set() and
                                not self._draining):
                raise self._reject_locked("closed", trace_id=tid)
            if self._draining:
                raise self._reject_locked("draining", trace_id=tid)
            if self._cooldown > 0:
                raise self._reject_locked("cooldown", trace_id=tid)
            depth = self._q.qsize() + len(self._pending)
            if depth >= self.max_queue_depth:
                raise self._reject_locked("queue full", depth,
                                          trace_id=tid)
            n = getattr(prompt, "shape", None)
            n = (int(np.prod(n)) if n is not None
                 else len(prompt) if hasattr(prompt, "__len__") else None)
            if (n is not None and self.cache.blocks_for(n + max_new) >
                    self.cache.allocatable_blocks):
                # can NEVER be scheduled: larger than the entire pool
                raise self._reject_locked("kv blocks exhausted", depth,
                                          trace_id=tid)
            if self.admission_factor > 0:
                ema = self._ema.ema
                if ema is not None:
                    waves = math.ceil((depth + 1) / self.max_batch)
                    projected = waves * ema * max_new
                    if projected > self.admission_factor * deadline:
                        raise self._reject_locked(
                            "projected wait", depth, trace_id=tid,
                            projected_wait_ms=projected,
                            deadline_ms=deadline)
            stream = TokenStream(prompt, self._next_index, now,
                                 now + int(deadline * 1e6), max_new,
                                 eos_id, trace_id=tid)
            self._next_index += 1
        request_trace.instant(tid, "request/admit", index=stream.index,
                              depth=depth)
        # charged BEFORE the enqueue — once the stream is in the queue
        # the scheduler owns it, and a completion racing a post-enqueue
        # charge would read payload_nbytes == 0 and leak the accounting
        with stream._cv:
            stream.payload_nbytes = payload_nbytes
        self._payload_acct.add(payload_nbytes)
        try:
            self._q.put_nowait(stream)
        except queue.Full:
            with stream._cv:
                stream.payload_nbytes = 0
            self._payload_acct.sub(payload_nbytes)
            with self._lock:
                raise self._reject_locked("queue full",
                                          self.max_queue_depth,
                                          trace_id=tid)
        if self._closed:
            # scheduler exited between the admission check and the
            # enqueue (it marks _closed BEFORE its final sweep) — shed
            # it NOW rather than strand it unaccounted
            self._drain_leftovers()
        telemetry.gauge("LM/queue_depth").set(self.queue_depth())
        return stream

    def _reject_locked(self, reason: str, depth: Optional[int] = None,
                       trace_id: Optional[str] = None, **kw) -> Overloaded:
        self._counts["rejected"] += 1
        telemetry.counter("LM/rejected").inc()
        telemetry.counter("LM/rejected",
                          labels={"reason": reason.replace(" ", "_")}).inc()
        err = Overloaded(reason,
                         queue_depth=(depth if depth is not None
                                      else self.queue_depth()),
                         max_depth=self.max_queue_depth, **kw)
        request_trace.verdict(trace_id, "rejected", error=err,
                              reason=reason.replace(" ", "_"))
        return err

    def _validate(self, stream: TokenStream, chaos) -> np.ndarray:
        """Per-request prompt validation — the taxonomy choke point:
        anything wrong with the PAYLOAD raises :class:`ServingDataError`
        here, quarantining one stream instead of killing a batch."""
        if chaos.poison_prompt(stream.index):
            raise ServingDataError(
                f"chaos: poison prompt at admission position "
                f"{stream.index}")
        try:
            row = np.asarray(stream.prompt)
        except Exception as e:
            raise ServingDataError(
                f"undecodable prompt payload: {e!r}") from e
        if row.ndim != 1 or row.size == 0:
            raise ServingDataError(
                f"prompt must be a non-empty 1-D token-id sequence, got "
                f"shape {row.shape}")
        if not np.issubdtype(row.dtype, np.integer):
            raise ServingDataError(
                f"prompt token ids must be integers, got dtype "
                f"{row.dtype}")
        if row.size + stream.max_new_tokens > self.max_context:
            raise ServingDataError(
                f"prompt of {row.size} token(s) + max_new_tokens "
                f"{stream.max_new_tokens} exceeds bigdl.lm.maxContext "
                f"{self.max_context}")
        return row.astype(np.int32)

    # -- accounting -------------------------------------------------------

    def _finish_stream(self, stream: TokenStream, outcome: str,
                       error: Optional[BaseException] = None,
                       reason: Optional[str] = None) -> bool:
        if not stream._finish(outcome, error=error):
            return False
        with stream._cv:
            nbytes = stream.payload_nbytes
            stream.payload_nbytes = 0
        if nbytes:
            self._payload_acct.sub(nbytes)
        with self._lock:
            self._counts[outcome] += 1
        # the trace-recording choke point for every LM terminal verdict;
        # a completed tail stream becomes a latency-histogram exemplar
        request_trace.verdict(stream.trace_id, outcome, error=error,
                              reason=reason)
        telemetry.counter(f"LM/{outcome}").inc()
        if reason:
            telemetry.counter(f"LM/{outcome}",
                              labels={"reason": reason}).inc()
        if outcome == "completed":
            self._latency.observe(stream.latency_ms(),
                                  exemplar=stream.trace_id)
        return True

    def stats(self) -> Dict[str, Any]:
        """Outcome counters + the accounting identity residual
        (``unaccounted`` includes streams still in flight — quiesce
        first for the exact identity)."""
        with self._lock:
            out: Dict[str, Any] = dict(self._counts)
        out["unaccounted"] = out["submitted"] - sum(out[o]
                                                    for o in OUTCOMES)
        out["decode_steps"] = self.decode_steps
        out["prefills"] = self.prefills
        out["tokens_out"] = self.tokens_out
        out["queue_depth"] = self.queue_depth()
        out["decode_ema_ms"] = self._ema.ema
        out["cooldown"] = self._cooldown
        out["draining"] = self._draining
        out["active_slots"] = sum(s is not None for s in self._slots)
        out["free_blocks"] = self.cache.free_blocks
        out["used_blocks"] = self.cache.used_blocks
        return out

    # -- the scheduler thread --------------------------------------------

    def _any_active(self) -> bool:
        return any(s is not None for s in self._slots)

    def _scheduler_loop(self) -> None:
        telemetry.name_thread("lm-scheduler")
        wd = None
        if self.stall_factor > 0:
            wd = HungDispatchWatchdog(
                self.stall_factor, warmup=self.warmup_steps,
                cooldown=self.cooldown_steps,
                poll_interval=min(self.poll_interval, 0.05))
            wd.start()                    # driver tid = this thread
            self.watchdog = wd
        try:
            drained = False
            while not drained:
                if not self._draining:
                    if elastic.preemption_requested():
                        with self._lock:
                            self._begin_drain_locked(
                                "preemption",
                                elastic.preemption_requested_at() or
                                time.monotonic())
                    elif self._stop_event.is_set():
                        with self._lock:
                            self._begin_drain_locked("stop",
                                                     time.monotonic())
                if self._draining:
                    if time.monotonic() > self._drain_deadline:
                        self._drain_leftovers()
                        self._shed_active(ServingInfraError(
                            "engine draining: decode did not finish "
                            "within the grace period — retriable"),
                            "drained")
                        drained = True
                        continue
                    if (self._q.empty() and not self._pending and
                            not self._any_active()):
                        drained = True
                        continue
                active = True
                try:
                    # the watchdog abort can surface during admission
                    # (validate/prefill) just as during decode — both run
                    # on the step clock, so both sit under one handler
                    self._admit_waiting(wd)
                    active = self._any_active()
                    if active:
                        self._decode_iteration(wd)
                except HungDispatchError:
                    ema = self._ema.ema
                    baseline = (f"{ema:.1f} ms EMA" if ema is not None
                                else "unseeded EMA")
                    diag = HungDispatchError(
                        f"decode step wedged past "
                        f"{self.stall_factor:.1f}x the iteration "
                        f"baseline ({baseline}) — the hung-dispatch "
                        "watchdog aborted it")
                    self._shed_active(diag, "hung_decode", cool=True)
                    if wd is not None:
                        wd.heartbeat()
                except Exception as e:  # noqa: BLE001 — must outlive
                    self._shed_active(ServingInfraError(
                        f"decode failed: {e!r}"), "infra")
                if not active:
                    try:
                        with (wd.paused() if wd is not None
                              else nullcontext()):
                            stream = self._q.get(
                                timeout=self.poll_interval)
                        with self._lock:
                            self._pending.append(stream)
                    except queue.Empty:
                        with self._lock:
                            if self._cooldown:
                                # backlog clear: a cooldown with no
                                # traffic would never end
                                self._cooldown = 0
        finally:
            if wd is not None:
                wd.stop()
            # _closed BEFORE the sweep: a racing submit that enqueued
            # past the drain either observes _closed (and sheds its own
            # stream) or enqueued before this sweep — exactly one
            with self._lock:
                self._closed = True
            self._drain_leftovers()
            self._shed_active(ServingInfraError(
                "scheduler exited with the sequence in flight — "
                "retriable"), "infra")

    def _begin_drain_locked(self, reason: str, started_at: float,
                            grace: Optional[float] = None) -> None:
        budget = grace if grace is not None else self.grace_period
        # deadline published BEFORE the flag (lock-free readers)
        self._drain_deadline = started_at + budget
        self._drain_reason = reason
        self._draining = True
        incident.record("lm/drain", reason=reason, grace_s=budget,
                        queued=self.queue_depth())
        logger.info("LM engine draining (%s): grace %.1f s, %d queued, "
                    "%d active", reason, budget, self.queue_depth(),
                    sum(s is not None for s in self._slots))

    def _drain_leftovers(self) -> None:
        """Shed everything still waiting (queue + block-starved pending
        holdover) — retriable by construction.  Bounded sweeps: both
        containers are capped at ``maxQueueDepth``."""
        shed = 0
        for src in ("queue", "pending"):
            for _ in range(self.max_queue_depth + 1):
                if src == "queue":
                    try:
                        stream = self._q.get_nowait()
                    except queue.Empty:
                        break
                else:
                    try:
                        with self._lock:
                            stream = self._pending.popleft()
                    except IndexError:
                        break
                err = ServingInfraError(
                    "engine draining: prompt was not scheduled within "
                    "the grace period — retriable")
                shed += self._finish_stream(stream, "shed", error=err,
                                            reason="drained")
        if shed:
            incident.record("lm/drain_shed", count=shed)
            logger.warning("LM drain shed %d queued stream(s)", shed)
        telemetry.gauge("LM/queue_depth").set(self.queue_depth())

    def _shed_active(self, error: Exception, reason: str,
                     cool: bool = False) -> None:
        """Fail every in-flight sequence with the diagnosis and free
        its blocks.  Each victim gets its OWN exception instance —
        concurrent ``result()`` raises on a shared object would
        interleave tracebacks across client threads."""
        failed = 0
        first_trace: Optional[str] = None
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            self._slots[i] = None
            self.cache.free_seq(slot.stream.seq_id)
            if first_trace is None:
                first_trace = slot.stream.trace_id
            failed += self._finish_stream(
                slot.stream, "shed", error=type(error)(*error.args),
                reason=reason)
        stream = self._admitting
        if stream is not None:
            # an abort landed mid-admission: the stream was popped from
            # the queue but never reached a slot.  free_seq and
            # _finish_stream are both idempotent, so overlap with the
            # slot sweep above is harmless.
            self._admitting = None
            self.cache.free_seq(stream.seq_id)
            if first_trace is None:
                first_trace = stream.trace_id
            failed += self._finish_stream(
                stream, "shed", error=type(error)(*error.args),
                reason=reason)
        if cool:
            with self._lock:
                self._cooldown = max(self._cooldown, self.cooldown_steps)
        if failed:
            incident.record("lm/shed_active", reason=reason,
                            victims=failed, error=type(error).__name__)
            incident.maybe_dump(f"lm/{reason}", trace_id=first_trace)
            logger.error(
                "LM decode aborted (%s): %d in-flight stream(s) failed "
                "with %s%s", reason, failed, type(error).__name__,
                f"; cooling down for {self.cooldown_steps} steps"
                if cool else "")

    def _admit_waiting(self, wd) -> None:
        """Fill vacant decode slots from the pending holdover then the
        queue: expired prompts are shed, poison ones quarantined
        (neither consumes a slot); a block-starved prompt goes back to
        the FRONT of the holdover (FIFO) and admission stops until a
        finishing sequence frees blocks."""
        from bigdl_tpu.utils import chaos
        for _ in range(self.max_batch):
            slot_idx = next((i for i, s in enumerate(self._slots)
                             if s is None), None)
            if slot_idx is None:
                return
            stream = None
            with self._lock:
                if self._pending:
                    stream = self._pending.popleft()
            if stream is None:
                try:
                    stream = self._q.get_nowait()
                except queue.Empty:
                    return
            # published so the watchdog's async abort cannot strand a
            # stream that lives only in this local (cleared at every
            # resting point; double-finish below is a guarded no-op)
            self._admitting = stream
            now = telemetry.clock_ns()
            request_trace.record_span(stream.trace_id,
                                      "request/queue_wait",
                                      stream.submit_ns, now)
            if now > stream.deadline_ns:
                waited = (now - stream.submit_ns) / 1e6
                deadline = (stream.deadline_ns - stream.submit_ns) / 1e6
                self._finish_stream(
                    stream, "shed",
                    error=DeadlineExceeded(waited, deadline),
                    reason="expired")
                self._admitting = None
                continue
            try:
                prompt = self._validate(stream, chaos)
            except ServingDataError as e:
                incident.record("lm/quarantine", index=stream.index,
                                error=type(e).__name__)
                self._finish_stream(stream, "quarantined", error=e)
                # bundle AFTER the verdict so the trace it embeds is
                # terminal; the write stalls the scheduler for tens of
                # ms — legitimate work, not a hung decode step, so the
                # watchdog is paused or it would fire a spurious abort
                with (wd.paused() if wd is not None else nullcontext()):
                    incident.maybe_dump("lm/quarantine",
                                        trace_id=stream.trace_id)
                self._admitting = None
                continue
            if not self.cache.can_allocate(prompt.size +
                                           stream.max_new_tokens):
                with self._lock:
                    self._pending.appendleft(stream)
                self._admitting = None
                return
            self.cache.allocate(stream.seq_id,
                                prompt.size + stream.max_new_tokens)
            t_pf = telemetry.clock_ns()
            try:
                tok, table_row = self._prefill_step_raw(stream.seq_id,
                                                        prompt)
            except Exception as e:  # noqa: BLE001 — fail one stream
                self.cache.free_seq(stream.seq_id)
                self._finish_stream(stream, "shed", error=ServingInfraError(
                    f"prefill failed: {e!r}"), reason="infra")
                self._admitting = None
                continue
            if wd is not None:
                wd.heartbeat()
            request_trace.record_span(stream.trace_id, "request/prefill",
                                      t_pf, telemetry.clock_ns(),
                                      prompt_tokens=int(prompt.size))
            stream._emit(tok)
            request_trace.instant(stream.trace_id, "request/emit",
                                  token=int(tok), first=True)
            self._ttft.observe(stream.ttft_ms(),
                               exemplar=stream.trace_id)
            telemetry.counter("LM/tokens").inc()
            self.tokens_out += 1
            if ((stream.eos_id is not None and tok == stream.eos_id) or
                    stream.max_new_tokens <= 1):
                self.cache.free_seq(stream.seq_id)
                self._finish_stream(stream, "completed")
                self._admitting = None
                continue
            self._slots[slot_idx] = _Slot(stream, int(prompt.size), tok,
                                          table_row)
            self._admitting = None
        telemetry.gauge("LM/queue_depth").set(self.queue_depth())

    def _prefill_step_raw(self, seq_id: int, prompt: np.ndarray
                          ) -> Tuple[int, np.ndarray]:
        """Run the bucketed prefill for an ALLOCATED sequence: scatter
        the prompt's k/v into its blocks, return the first greedy token
        (1-based) and the dump-padded table row the decode step
        gathers through."""
        from bigdl_tpu.analysis.hostsync import host_pull
        t0 = telemetry.clock_ns()
        P = int(prompt.size)
        bucket = self._prefill_bucket(P)
        padded = np.ones((1, bucket), np.int32)
        padded[0, :P] = prompt
        blocks = self.cache.table(seq_id)
        table_row = np.full((self._max_blocks,), DUMP_BLOCK, np.int32)
        table_row[:len(blocks)] = blocks
        lp, new_k, new_v = self._prefill(self._dp, self.cache.k,
                                         self.cache.v, padded,
                                         np.int32(P), table_row)
        with self._lock:
            self.cache.k, self.cache.v = new_k, new_v
        lp = np.asarray(host_pull(lp, what="lm prefill logits"))
        with self._lock:
            self.prefills += 1
        telemetry.counter("LM/prefills").inc()
        telemetry.gauge("LM/prefill_ms").set(
            (telemetry.clock_ns() - t0) / 1e6)
        return int(np.argmax(lp)) + 1, table_row

    def _decode_iteration(self, wd) -> None:
        """ONE fused decode step over every occupied slot — the
        continuous-batching heartbeat.  Finished sequences vacate their
        slot and free their blocks before the next admission pass."""
        from bigdl_tpu.analysis.hostsync import host_pull
        from bigdl_tpu.utils import chaos
        self.decode_steps += 1
        step = self.decode_steps
        telemetry.counter("LM/decode_steps").inc()
        chaos.on_decode_step(step)
        if chaos.evict_block(step):
            victim = next((i for i, s in enumerate(self._slots)
                           if s is not None), None)
            if victim is not None:
                # finish-FIRST: the watchdog's async abort sweeps slots
                # and _admitting only — a stream finished before its
                # slot clears is a guarded no-op for the sweep, but a
                # slot cleared before the finish would strand the stream
                # unaccounted forever
                slot = self._slots[victim]
                self._finish_stream(slot.stream, "shed",
                                    error=ServingInfraError(
                                        "chaos: kv blocks evicted under "
                                        "an active sequence — retriable"),
                                    reason="evicted")
                self._slots[victim] = None
                self.cache.free_seq(slot.stream.seq_id)
            if not self._any_active():
                return
        t0 = telemetry.clock_ns()
        B, MB = self.max_batch, self._max_blocks
        tokens = np.ones((B, 1), np.int32)
        positions = np.zeros((B,), np.int32)
        tables = np.full((B, MB), DUMP_BLOCK, np.int32)
        active = np.zeros((B,), bool)
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            tokens[i, 0] = slot.last_token
            positions[i] = slot.position
            tables[i] = slot.table_row
            active[i] = True
        dp, fn = ((self._dp_q, self._decode_q)
                  if self._dp_q is not None else (self._dp, self._decode))
        lp, new_k, new_v = fn(dp, self.cache.k, self.cache.v, tokens,
                              positions, tables, active)
        with self._lock:
            self.cache.k, self.cache.v = new_k, new_v
        lp = np.asarray(host_pull(lp, what="lm decode logits"))
        now = telemetry.clock_ns()
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            stream = slot.stream
            tok = int(np.argmax(lp[i])) + 1
            slot.position += 1
            slot.generated += 1
            slot.last_token = tok
            self._itl.observe((now - slot.last_emit_ns) / 1e6)
            slot.last_emit_ns = now
            stream._emit(tok)
            request_trace.record_span(stream.trace_id,
                                      "request/decode_step", t0, now,
                                      step=step, token=tok)
            telemetry.counter("LM/tokens").inc()
            self.tokens_out += 1
            if ((stream.eos_id is not None and tok == stream.eos_id) or
                    slot.generated >= stream.max_new_tokens):
                # finish-FIRST (same discipline as the eviction branch):
                # an async watchdog abort landing between these lines
                # must find either an occupied slot (sweep accounts it)
                # or a finished stream (sweep no-ops) — never a cleared
                # slot with an unaccounted stream
                self._finish_stream(stream, "completed")
                self._slots[i] = None
                self.cache.free_seq(stream.seq_id)
            elif now > stream.deadline_ns:
                # mid-stream expiry AFTER emitting: the streamed prefix
                # stays with the client, the terminal error says why it
                # stopped — the partially-streamed-then-failed shape
                waited = (now - stream.submit_ns) / 1e6
                deadline = (stream.deadline_ns - stream.submit_ns) / 1e6
                self._finish_stream(
                    stream, "shed",
                    error=DeadlineExceeded(waited, deadline),
                    reason="expired")
                self._slots[i] = None
                self.cache.free_seq(stream.seq_id)
        ms = (telemetry.clock_ns() - t0) / 1e6
        self._ema.observe(ms)
        if wd is not None:
            wd.heartbeat()
        with self._lock:
            if self._cooldown > 0:
                self._cooldown -= 1
        telemetry.gauge("LM/decode_ms").set(ms)
        telemetry.gauge("LM/slot_occupancy").set(
            sum(s is not None for s in self._slots) / max(1, B))

    # -- offline generation (parity + baseline) ---------------------------

    def _offline_seq_id(self) -> int:
        # negative ids so offline allocations can never collide with a
        # stream's admission-index seq_id
        self._offline_id -= 1
        return self._offline_id

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 return_logps: bool = False):
        """Offline greedy generation through the PAGED path (prefill +
        single-token decode over the block table) — the exact compiled
        steps the scheduler dispatches, minus the scheduler.  Refused
        while the scheduler runs (it owns the slots and pools)."""
        from bigdl_tpu.analysis.hostsync import host_pull
        if self._started:
            raise ServingInfraError(
                "generate() is the offline path — the scheduler owns the "
                "decode slots once start() has run; use submit()")
        prompt = np.asarray(prompt)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ServingDataError(
                f"prompt must be a non-empty 1-D token-id sequence, got "
                f"shape {prompt.shape}")
        prompt = prompt.astype(np.int32)
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else self.max_new_tokens)
        if prompt.size + max_new > self.max_context:
            raise ServingDataError(
                f"prompt of {prompt.size} token(s) + max_new_tokens "
                f"{max_new} exceeds bigdl.lm.maxContext "
                f"{self.max_context}")
        B, MB = self.max_batch, self._max_blocks
        seq_id = self._offline_seq_id()
        self.cache.allocate(seq_id, int(prompt.size) + max_new)
        try:
            tok, table_row = self._prefill_step_raw(seq_id, prompt)
            out_tokens = [tok]
            logps: List[np.ndarray] = []
            dp, fn = ((self._dp_q, self._decode_q)
                      if self._dp_q is not None
                      else (self._dp, self._decode))
            position = int(prompt.size)
            for _ in range(max_new - 1):
                if eos_id is not None and out_tokens[-1] == eos_id:
                    break
                tokens = np.ones((B, 1), np.int32)
                positions = np.zeros((B,), np.int32)
                tables = np.full((B, MB), DUMP_BLOCK, np.int32)
                active = np.zeros((B,), bool)
                tokens[0, 0], positions[0] = out_tokens[-1], position
                tables[0], active[0] = table_row, True
                lp, new_k, new_v = fn(dp, self.cache.k, self.cache.v,
                                      tokens, positions, tables, active)
                with self._lock:
                    self.cache.k, self.cache.v = new_k, new_v
                row = np.asarray(host_pull(
                    lp, what="lm offline decode logits"))[0]
                out_tokens.append(int(np.argmax(row)) + 1)
                logps.append(row)
                position += 1
        finally:
            self.cache.free_seq(seq_id)
        return (out_tokens, logps) if return_logps else out_tokens

    def generate_sequential(self, prompt,
                            max_new_tokens: Optional[int] = None,
                            eos_id: Optional[int] = None,
                            return_logps: bool = False):
        """The KV-cache-free baseline the bench's speedup claim is
        measured against: one TEACHER-FORCED full forward over the
        whole growing sequence per emitted token (what serving without
        a decode cache actually costs).  Greedy tokens are bit-identical
        to :meth:`generate`; per-position log-probs agree to allclose
        (the reductions are shaped differently)."""
        from bigdl_tpu.analysis.hostsync import host_pull
        prompt = np.asarray(prompt)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ServingDataError(
                f"prompt must be a non-empty 1-D token-id sequence, got "
                f"shape {prompt.shape}")
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else self.max_new_tokens)
        if prompt.size + max_new > self.max_context:
            raise ServingDataError(
                f"prompt of {prompt.size} token(s) + max_new_tokens "
                f"{max_new} exceeds bigdl.lm.maxContext "
                f"{self.max_context}")
        seq = [int(t) for t in prompt]
        out_tokens: List[int] = []
        logps: List[np.ndarray] = []
        for _ in range(max_new):
            if (eos_id is not None and out_tokens and
                    out_tokens[-1] == eos_id):
                break
            t = len(seq)
            bucket = self._prefill_bucket(t)
            padded = np.ones((1, bucket), np.int32)
            padded[0, :t] = seq
            lp = self._full(self._dp, padded)
            row = np.asarray(host_pull(
                lp, what="lm sequential logits"))[t - 1]
            tok = int(np.argmax(row)) + 1
            seq.append(tok)
            out_tokens.append(tok)
            logps.append(row)
        return (out_tokens, logps) if return_logps else out_tokens


__all__ = ["LMServingEngine", "TokenStream", "PagedKVCache",
           "QuantizationGateError", "UnsupportedModelError"]
