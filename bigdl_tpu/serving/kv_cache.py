"""Paged block-table KV cache for autoregressive decode.

vLLM-style paging brought to the tracked-jit world: one fixed
device-resident pool of key/value blocks shaped ``(layer, block,
block_size, head, head_dim)``, a HOST-side free-list, and a per-sequence
block table mapping token positions to pool blocks.  Heterogeneous
sequence lengths share the same device memory with fragmentation bounded
by the block granularity — a sequence wastes at most ``block_size - 1``
slots, never a max-context reservation.

Three invariants the serving tests bit-assert:

- **Block 0 is the dump block.**  It is never allocated: padded prefill
  positions and inactive decode slots scatter their (junk) k/v there, so
  the fused step keeps ONE fixed shape regardless of occupancy and a
  stray write can never land in another sequence's block.
- **Freed blocks are zero-scrubbed** before they re-enter the free-list:
  a reused block carries no residue of the previous request's tokens
  (no cross-request leakage, asserted bit-exactly by reading the pool).
- **Exhaustion is structured.**  An allocation the free-list cannot
  satisfy raises the serving taxonomy's retriable
  :class:`~bigdl_tpu.serving.engine.Overloaded` — the pool is sized ONCE
  at construction (gated by the HBM preflight budget,
  :func:`bigdl_tpu.resources.device.preflight_pool`), so running out of
  blocks is an admission-control answer, never a device OOM.

The pool arrays are functional: the compiled prefill/decode steps take
them as inputs and return the updated pools, which the engine writes
back to :attr:`k` / :attr:`v`.  The free-list and tables are plain host
state under a lock (allocation is scheduler-thread work, microseconds).
"""

from __future__ import annotations

import math
from typing import Dict, List

import jax.numpy as jnp
import numpy as np

from bigdl_tpu import analysis, telemetry
from bigdl_tpu.serving.engine import Overloaded

#: block id every padded / inactive-slot scatter targets — reserved at
#: construction, never handed out by the free-list
DUMP_BLOCK = 0

#: freed block ids are zero-scrubbed in fixed-size batches (padded with
#: the dump block) so the eager scatter keeps ONE cached computation
#: instead of one per distinct free-list length
_SCRUB_CHUNK = 8


class PagedKVCache:
    """Fixed device pool of (layer, block, block_size, head, head_dim)
    K/V blocks + host free-list + per-sequence block tables."""

    def __init__(self, n_layers: int, n_head: int, head_dim: int,
                 n_blocks: int, block_size: int, dtype=jnp.float32,
                 label: str = "lm_kv_cache"):
        if n_blocks < 2:
            raise ValueError(
                f"paged KV cache needs >= 2 blocks (block {DUMP_BLOCK} is "
                f"the reserved dump block), got n_blocks={n_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.n_layers = int(n_layers)
        self.n_head = int(n_head)
        self.head_dim = int(head_dim)
        self.n_blocks = int(n_blocks)
        self.block_size = int(block_size)
        self.dtype = jnp.dtype(dtype)
        self.label = label
        shape = (self.n_layers, self.n_blocks, self.block_size,
                 self.n_head, self.head_dim)
        self.pool_nbytes = 2 * int(np.prod(shape)) * self.dtype.itemsize
        # gate BEFORE the buffers exist: an over-budget pool is a plan
        # error answered while device state is still untouched
        from bigdl_tpu.resources.device import preflight_pool
        preflight_pool(self.pool_nbytes, label)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)
        self._free: List[int] = list(range(self.n_blocks - 1, 0, -1))
        self._tables: Dict[int, List[int]] = {}
        self._lock = analysis.make_lock("lm.kv_cache")
        self._occupancy = telemetry.gauge(
            "LM/block_occupancy",
            help="allocated KV-cache blocks / allocatable blocks")
        self._occupancy.set(0.0)

    # -- capacity ---------------------------------------------------------

    @property
    def allocatable_blocks(self) -> int:
        """Total blocks the free-list can ever hand out (pool minus the
        dump block)."""
        return self.n_blocks - 1

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.allocatable_blocks - self.free_blocks

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks a sequence of ``n_tokens`` total positions occupies."""
        return max(1, math.ceil(n_tokens / self.block_size))

    def can_allocate(self, n_tokens: int) -> bool:
        with self._lock:
            return self.blocks_for(n_tokens) <= len(self._free)

    # -- allocation -------------------------------------------------------

    def allocate(self, seq_id: int, n_tokens: int) -> List[int]:
        """Reserve the blocks for a sequence of up to ``n_tokens`` total
        positions, or raise a structured retriable
        :class:`Overloaded` — the free-list is the bound; exhaustion is
        an admission answer, never an allocation attempt on device."""
        need = self.blocks_for(n_tokens)
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id} already holds "
                                 f"{len(self._tables[seq_id])} block(s)")
            if need > len(self._free):
                err = Overloaded(
                    "kv blocks exhausted",
                    queue_depth=self.allocatable_blocks - len(self._free),
                    max_depth=self.allocatable_blocks)
                err.blocks_needed = need
                err.blocks_free = len(self._free)
                raise err
            blocks = [self._free.pop() for _ in range(need)]
            self._tables[seq_id] = blocks
        self._publish_occupancy()
        return list(blocks)

    def table(self, seq_id: int) -> List[int]:
        with self._lock:
            return list(self._tables[seq_id])

    def free_seq(self, seq_id: int) -> int:
        """Release a sequence's blocks back to the free-list, ZEROING
        them on device first — a later allocation of the same block ids
        starts bit-clean (the no-cross-request-leakage proof reads the
        pool and asserts exactly this).  Returns the block count (0 when
        the sequence holds nothing — idempotent)."""
        with self._lock:
            blocks = self._tables.pop(seq_id, None)
            if not blocks:
                return 0
        self._scrub(blocks)
        with self._lock:
            self._free.extend(blocks)
        self._publish_occupancy()
        return len(blocks)

    def _scrub(self, blocks: List[int]) -> None:
        """Zero the named blocks across all layers.  Ids are padded to
        ``_SCRUB_CHUNK`` with the dump block (re-zeroing junk is free),
        so the eager scatter-set has a fixed shape and XLA caches one
        computation for every free."""
        zeros = jnp.zeros((self.n_layers, _SCRUB_CHUNK, self.block_size,
                           self.n_head, self.head_dim), self.dtype)
        for at in range(0, len(blocks), _SCRUB_CHUNK):
            chunk = blocks[at:at + _SCRUB_CHUNK]
            ids = np.full((_SCRUB_CHUNK,), DUMP_BLOCK, np.int32)
            ids[:len(chunk)] = chunk
            self.k = self.k.at[:, ids].set(zeros)
            self.v = self.v.at[:, ids].set(zeros)

    def _publish_occupancy(self) -> None:
        denom = max(1, self.allocatable_blocks)
        self._occupancy.set(self.used_blocks / denom)
