"""The serving engine: bounded admission, micro-batching, graceful decay.

Request lifecycle (every submitted request terminates with EXACTLY one
outcome — the accounting identity the chaos proofs assert)::

    submit ──► rejected   (Overloaded at the door: queue full, projected
       │                   wait past the deadline budget, cooldown after
       │                   a watchdog fire, or draining — always fast,
       │                   always structured, retriable where retrying
       │                   elsewhere can help)
       ▼
    admission queue (bounded: bigdl.serving.maxQueueDepth)
       │
       ▼  batcher thread coalesces up to bigdl.serving.maxBatch
    ── shed        (deadline expired at DEQUEUE time — before the
       │            request wastes a device slot; also: in-flight
       │            victims of a hung-dispatch abort, and requests left
       │            queued when the drain grace period lapses)
    ── quarantined (poison payload: undecodable / ill-shaped — a
       │            ServingDataError fails the ONE offending request
       │            and the batch stays alive)
       ▼
    dispatch (pad to the compile-bucket plan → tracked executable →
       │      one explicit host pull) ──► completed (per-row fan-out)

The dispatcher pads every batch to ``bigdl.compile.buckets`` (falling
back to a single ``maxBatch`` bucket when unset), so arbitrary request
arrival patterns hit only pre-compiled signatures — the PR 4 strict
retrace sentinel proves zero post-warmup retraces.  A hung dispatch is
aborted by :class:`HungDispatchWatchdog` (the PR 6 async-raise machinery
with the PR 5 warmup-minimum EMA seeding) and the engine re-admits after
``bigdl.serving.cooldownSteps`` batches (or as soon as the backlog
clears).  SIGTERM — via the PR 6 ``elastic`` preemption flag — stops
admission, drains in-flight batches within ``bigdl.serving.gracePeriod``
seconds, and rejects late arrivals with a retriable marker.
"""

from __future__ import annotations

import logging
import math
import queue
import threading
import time
from contextlib import nullcontext
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu import analysis, telemetry
from bigdl_tpu.resources import GOVERNOR as _resource_governor
from bigdl_tpu.resources import item_nbytes as _item_nbytes
from bigdl_tpu.telemetry import incident, request_trace
from bigdl_tpu.utils import elastic

logger = logging.getLogger("bigdl_tpu")


class ServingError(RuntimeError):
    """Base class of the serving-path taxonomy.  ``retriable`` tells the
    client whether the same payload can succeed later / elsewhere."""

    retriable = False


class Overloaded(ServingError):
    """Admission control said no — at the door, in microseconds.  The
    structured alternative to silent tail-latency collapse: the client
    learns queue depth, the projected wait, and whether a retry can help
    (it can, except when its own deadline already cannot be met)."""

    retriable = True

    def __init__(self, reason: str, queue_depth: int = 0,
                 max_depth: int = 0,
                 projected_wait_ms: Optional[float] = None,
                 deadline_ms: Optional[float] = None):
        self.reason = reason
        self.queue_depth = queue_depth
        self.max_depth = max_depth
        self.projected_wait_ms = projected_wait_ms
        self.deadline_ms = deadline_ms
        detail = f"rejected at admission ({reason}): depth " \
                 f"{queue_depth}/{max_depth}"
        if projected_wait_ms is not None:
            detail += (f", projected wait {projected_wait_ms:.1f} ms vs "
                       f"deadline {deadline_ms:.1f} ms")
        super().__init__(detail + " — retriable")


class DeadlineExceeded(ServingError):
    """The request aged past its deadline while queued and was shed at
    dequeue time — it never occupied a device slot."""

    retriable = True

    def __init__(self, waited_ms: float, deadline_ms: float):
        self.waited_ms = waited_ms
        self.deadline_ms = deadline_ms
        super().__init__(
            f"shed: waited {waited_ms:.1f} ms in queue, deadline was "
            f"{deadline_ms:.1f} ms — retriable (but mind your own deadline)")


class ServingDataError(ServingError):
    """A poison request: undecodable or ill-shaped payload.  A DATA
    fault — quarantined, never retried (re-decoding poison yields
    poison), and never allowed to kill the batch it rode in with."""

    retriable = False


class ServingInfraError(ServingError):
    """An infrastructure fault on the serving path (dispatch failure,
    drain timeout): the request payload is fine — retry it."""

    retriable = True


class HungDispatchError(ServingInfraError):
    """Injected into the batcher thread by the hung-dispatch watchdog: a
    dispatch exceeded ``bigdl.serving.stallFactor`` x the batch-time
    EMA.  In-flight requests fail with this diagnosis; the engine cools
    down before re-admitting."""


class HungDispatchWatchdog(elastic.HungStepWatchdog):
    """The PR 6 hung-step machinery pointed at the serving batcher: same
    monitor thread, same warmup-minimum EMA seeding, same async-raise
    abort — but it injects :class:`HungDispatchError` and counts under
    ``Serving/watchdog_*``."""

    EXC = HungDispatchError
    METRIC_PREFIX = "Serving"
    INSTANT_NAME = "serving/hung_dispatch"


#: terminal request outcomes — the accounting identity is
#: completed + shed + rejected + quarantined == submitted
OUTCOMES = ("completed", "shed", "rejected", "quarantined")


class RequestHandle:
    """One admitted request: a one-shot future whose terminal state is
    exactly one of :data:`OUTCOMES` (``_finish`` is first-wins, so a
    request can never be both shed by the drain and completed by a
    racing dispatch)."""

    __slots__ = ("raw", "index", "submit_ns", "deadline_ns", "finish_ns",
                 "outcome", "_result", "_error", "_done", "payload_nbytes",
                 "_lock", "trace_id")

    def __init__(self, raw, index: int, submit_ns: int, deadline_ns: int,
                 trace_id: Optional[str] = None):
        self.raw = raw
        self.index = index            # admission position (chaos plans key on it)
        self.submit_ns = submit_ns
        self.deadline_ns = deadline_ns
        self.trace_id = trace_id      # None when request tracing is disarmed
        self._lock = analysis.make_lock("serving.handle")
        self.payload_nbytes = 0       # guarded-by: _lock — host bytes charged to the governor
        self.finish_ns: Optional[int] = None            # guarded-by: _lock
        self.outcome: Optional[str] = None              # guarded-by: _lock
        self._result = None                             # guarded-by: _lock
        self._error: Optional[BaseException] = None     # guarded-by: _lock
        self._done = threading.Event()

    def _finish(self, outcome: str, result=None,
                error: Optional[BaseException] = None) -> bool:
        # first-wins must be ATOMIC: the engine's dispatch completion and
        # a supervisor's abandon() race here from different threads, and
        # a bare Event check would let both pass the gate and double-count
        with self._lock:
            if self._done.is_set():
                return False
            self.outcome = outcome
            self._result = result
            self._error = error
            self.finish_ns = telemetry.clock_ns()
            self._done.set()
        return True

    def latency_ms(self) -> Optional[float]:
        """Submit-to-terminal-state latency; None while in flight."""
        if self.finish_ns is None:
            return None
        return (self.finish_ns - self.submit_ns) / 1e6

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        """The per-request model output, or raises the terminal error
        (:class:`DeadlineExceeded` / :class:`ServingDataError` / ...)."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.index} still in flight after {timeout} s")
        if self._error is not None:
            raise self._error
        return self._result

    def error(self) -> Optional[BaseException]:
        return self._error if self._done.is_set() else None

    def abandon(self, error: Optional[BaseException] = None,
                reason: str = "abandoned") -> bool:
        """Terminally shed this request from OUTSIDE the engine — the
        fleet supervisor sweeping the in-flight requests of a crashed
        replica (whose batcher died mid-dispatch and can never account
        them).  First-wins like every terminal transition, so a racing
        dispatch completion or drain shed is never double-counted; the
        queued-payload bytes charged at admission are released here
        because the engine that charged them may be dead.  True when
        THIS call finished the request."""
        err = error if error is not None else ServingInfraError(
            "request abandoned by its supervisor — retriable")
        if not self._finish("shed", error=err):
            return False
        # the trace's verdict distinguishes the supervisor-side abort
        # from an engine-side shed even though both count under "shed"
        request_trace.verdict(self.trace_id, "aborted", error=err,
                              reason=reason)
        with self._lock:
            nbytes = self.payload_nbytes
            self.payload_nbytes = 0
        if nbytes:
            _resource_governor.account("serving_admission").sub(nbytes)
        telemetry.counter("Serving/shed").inc()
        telemetry.counter("Serving/shed", labels={"reason": reason}).inc()
        return True


def _service_ema(warmup: int):
    """The admission controller's batch service-time estimator: a PR 5
    :class:`~bigdl_tpu.telemetry.step_stats.SlowStepDetector` used as a
    pure warmup-minimum-seeded EMA (``factor=inf`` — nothing is ever
    'slow'; detection is the watchdog's job, this one only projects
    queue waits).  One implementation of the compile-exemption seeding,
    not a parallel copy."""
    from bigdl_tpu.telemetry import SlowStepDetector
    return SlowStepDetector(math.inf, warmup=warmup, cooldown=0)


class ServingEngine:
    """Continuous micro-batching inference server over one model.

    ``fold_bn=True`` serves a clone with every conv+BN pair folded (the
    ``Predictor`` contract); the forward executes through the tracked
    compile cache, so with ``bigdl.compile.cacheDir`` armed a second
    process warm-loads instead of compiling.  All knobs default from
    ``bigdl.serving.*`` (see ``docs/configuration.md``); constructor
    arguments override per-engine.
    """

    def __init__(self, model, fold_bn: bool = False,
                 max_batch: Optional[int] = None,
                 max_queue_depth: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 admission_factor: Optional[float] = None,
                 stall_factor: Optional[float] = None,
                 grace_period: Optional[float] = None,
                 cooldown_batches: Optional[int] = None,
                 start: bool = True):
        from bigdl_tpu.utils import compile_cache, config
        from bigdl_tpu.optim.predictor import Predictor
        self.model = Predictor(model, fold_bn=fold_bn).model
        self.max_batch = int(max_batch if max_batch is not None else
                             config.get_int("bigdl.serving.maxBatch", 16))
        self.max_queue_depth = int(
            max_queue_depth if max_queue_depth is not None else
            config.get_int("bigdl.serving.maxQueueDepth", 128))
        self.deadline_ms = float(
            deadline_ms if deadline_ms is not None else
            config.get_float("bigdl.serving.deadlineMs", 1000.0))
        self.admission_factor = float(
            admission_factor if admission_factor is not None else
            config.get_float("bigdl.serving.admissionDeadlineFactor", 1.0))
        self.stall_factor = float(
            stall_factor if stall_factor is not None else
            config.get_float("bigdl.serving.stallFactor", 0.0))
        self.grace_period = float(
            grace_period if grace_period is not None else
            config.get_float("bigdl.serving.gracePeriod", 5.0))
        self.cooldown_batches = int(
            cooldown_batches if cooldown_batches is not None else
            config.get_int("bigdl.serving.cooldownSteps", 8))
        self.linger_ms = config.get_float("bigdl.serving.lingerMs", 0.0)
        self.poll_interval = config.get_float("bigdl.serving.pollInterval",
                                              0.05)
        self.warmup_batches = config.get_int("bigdl.serving.warmupBatches",
                                             3)
        # the shape plan: every dispatch pads to a bucket, so arrival
        # patterns can never mint a new signature.  maxBatch is always
        # IN the plan — otherwise an occupancy past the largest
        # configured bucket would round to a multiple warmup never
        # compiled and pay a full compile against its batch's deadlines
        self._buckets = sorted(set(
            (compile_cache.configured_buckets() or []) + [self.max_batch]))
        from bigdl_tpu.optim.evaluator import _eval_forward
        self._forward = _eval_forward(self.model)
        # the admission queue IS the bound: put_nowait + Full -> Overloaded
        self._q: "queue.Queue[RequestHandle]" = queue.Queue(
            maxsize=self.max_queue_depth)
        self._lock = analysis.make_lock("serving.engine")
        # queued + in-flight payload bytes, rolled into Resources/host_bytes
        self._payload_acct = _resource_governor.account("serving_admission")
        self._counts: Dict[str, int] = dict.fromkeys(OUTCOMES, 0)  # guarded-by: _lock
        self._counts["submitted"] = 0
        self._next_index = 0
        self._cooldown = 0
        self._draining = False                          # guarded-by: _lock
        self._drain_deadline: Optional[float] = None    # guarded-by: _lock
        self._drain_reason = ""                         # guarded-by: _lock
        self._closed = False                            # guarded-by: _lock
        self._started = False                           # guarded-by: _lock
        self._stop_event = threading.Event()
        self._template: Optional[Tuple[Tuple[int, ...], str]] = None  # guarded-by: _lock
        self._ema = _service_ema(self.warmup_batches)
        self.batches = 0
        self.watchdog: Optional[HungDispatchWatchdog] = None
        self._thread: Optional[threading.Thread] = None
        window = config.get_int("bigdl.telemetry.percentileWindow", 512)
        self._latency = telemetry.histogram(
            "Serving/latency_ms", window=window,
            help="per-request submit-to-result latency")
        self._occupancy = telemetry.histogram(
            "Serving/batch_occupancy",
            help="true (unpadded) requests per dispatched batch")
        if start:
            self.start()

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "ServingEngine":
        if self._closed:
            # one-way lifecycle: a stopped engine's queue was swept and
            # its counters closed out — "restarting" it would serve from
            # a half-torn state.  Structured and retriable: build a new
            # engine (warm-loading makes that cheap), don't revive this
            # one.
            raise ServingInfraError(
                "engine is terminal: stop() is one-way — build a new "
                "engine instead of restarting this one")
        if self._started:
            return self
        with self._lock:
            self._started = True
        self._thread = threading.Thread(target=self._batcher_loop,
                                        daemon=True,
                                        name="serving-batcher")
        self._thread.start()
        return self

    def warmup(self, example_row: np.ndarray) -> None:
        """AOT: run one forward per facts-on-the-ground bucket so the
        first real request never pays a compile against its deadline.
        ``example_row`` is one request payload; it also pins the row
        template (shape+dtype) later requests are validated against."""
        row = np.asarray(example_row)
        with self._lock:
            self._template = (row.shape, str(row.dtype))
        biggest = max(self._buckets)
        batch = np.broadcast_to(row, (biggest,) + row.shape).copy()
        # one call per bucket: with configured buckets the first call's
        # AOT precompile covers the rest, but calling each keeps the
        # no-bucket (single maxBatch bucket) path identical
        for b in self._buckets:
            self._run_forward(batch[:b])

    def stop(self, grace: Optional[float] = None) -> None:
        """Graceful shutdown: admission closes (late arrivals get a
        retriable :class:`Overloaded`), queued work drains within
        ``grace`` (default ``bigdl.serving.gracePeriod``) and leftovers
        are shed retriably.

        The restart/reuse contract (the router's drain-then-discard path
        leans on it): ``stop()`` is IDEMPOTENT and TERMINAL.  A second
        ``stop()`` — concurrent or sequential — re-sweeps leftovers and
        returns; it never raises and never blocks on a dead thread.
        After the first ``stop()`` returns, :attr:`terminal` is True,
        ``submit()`` answers with a structured retriable
        :class:`Overloaded` (reason ``"closed"``), and ``start()``
        refuses with :class:`ServingInfraError` — an engine is never
        revived from a half-torn state; build a new one (the compile
        cache makes that a warm load, not a recompile)."""
        if not self._started or self._closed:
            with self._lock:
                self._closed = True  # before the sweep — see _batcher_loop
            self._drain_leftovers()
            return
        with self._lock:
            if not self._draining:
                self._begin_drain_locked("stop", time.monotonic(),
                                         grace)
            elif grace is not None:
                # a drain is already running (e.g. preemption started
                # it) — an explicit stop(grace=...) re-budgets it, so
                # the caller's window and the join timeout below agree
                self._drain_deadline = time.monotonic() + grace
        self._stop_event.set()
        t = self._thread
        if t is not None:
            budget = (grace if grace is not None else self.grace_period)
            t.join(timeout=budget + 10.0)
        self._drain_leftovers()
        with self._lock:
            self._closed = True

    def close(self) -> None:
        self.stop()

    @property
    def terminal(self) -> bool:
        """True once the engine can never serve again (``stop()``
        finished, or the batcher thread exited and swept the queue):
        ``submit()`` now returns structured retriable rejections and
        ``start()`` refuses — the documented end state of the one-way
        lifecycle."""
        return self._closed

    @property
    def draining(self) -> bool:
        return self._draining

    def queue_depth(self) -> int:
        """Current admission-queue depth (the fleet autoscaler's load
        signal, cheap enough for every supervisor tick)."""
        return self._q.qsize()

    def batcher_alive(self) -> bool:
        """True while the batcher thread is running — the liveness probe
        a fleet supervisor polls."""
        t = self._thread
        return bool(t is not None and t.is_alive())

    def batcher_ident(self) -> Optional[int]:
        """The batcher thread's ident (None before ``start()``) — the
        chaos harness's kill target."""
        t = self._thread
        return t.ident if t is not None else None

    def crashed(self) -> bool:
        """True when the batcher thread died WITHOUT an orderly drain or
        stop — an async kill or an escaped internal error.  This (not
        mere thread death, which a clean drain also produces) is the
        signal a fleet supervisor keys replica restarts on."""
        t = self._thread
        return bool(self._started and t is not None and not t.is_alive()
                    and not self._draining and
                    not self._stop_event.is_set())

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- admission --------------------------------------------------------

    def submit(self, inputs, deadline_ms: Optional[float] = None
               ) -> RequestHandle:
        """Admit one request or raise :class:`Overloaded` — fast, at the
        door, before the request can rot in a queue it will never leave
        in time.  Returns a :class:`RequestHandle` future."""
        now = telemetry.clock_ns()
        deadline = float(deadline_ms if deadline_ms is not None
                         else self.deadline_ms)
        # one payload larger than the whole host-memory budget can never
        # be admitted — escalate BEFORE it counts as submitted, so the
        # outcome accounting identity stays intact
        payload_nbytes = _item_nbytes(inputs)
        _resource_governor.check_item("serving_admission", payload_nbytes)
        telemetry.counter("Serving/submitted").inc()
        # the trace id is minted at the admission door — BEFORE the
        # rejection checks, so a rejected request still explains itself
        tid = request_trace.mint("req", deadline_ms=deadline)
        with self._lock:
            self._counts["submitted"] += 1
            if self._closed or (self._stop_event.is_set() and
                                not self._draining):
                raise self._reject_locked("closed", trace_id=tid)
            if self._draining:
                raise self._reject_locked("draining", trace_id=tid)
            if self._cooldown > 0:
                raise self._reject_locked("cooldown", trace_id=tid)
            depth = self._q.qsize()
            if depth >= self.max_queue_depth:
                raise self._reject_locked("queue full", depth,
                                          trace_id=tid)
            ema = self._ema.ema
            if ema is not None:
                waves = math.ceil((depth + 1) / self.max_batch)
                projected = waves * ema
                if projected > self.admission_factor * deadline:
                    raise self._reject_locked(
                        "projected wait", depth, trace_id=tid,
                        projected_wait_ms=projected,
                        deadline_ms=deadline)
            req = RequestHandle(inputs, self._next_index, now,
                                now + int(deadline * 1e6), trace_id=tid)
            self._next_index += 1
        request_trace.instant(tid, "request/admit", index=req.index,
                              depth=depth)
        # admission-queue bytes: charged while the payload is queued or
        # in flight, released at the terminal state.  Charged BEFORE the
        # enqueue — once the handle is in the queue the batcher owns it,
        # and a completion that raced a post-enqueue charge would read
        # payload_nbytes == 0 and leak the governor accounting
        with req._lock:
            req.payload_nbytes = payload_nbytes
        self._payload_acct.add(payload_nbytes)
        try:
            self._q.put_nowait(req)
        except queue.Full:
            # a racing submit filled the last slot between the depth
            # check and here — same answer, same speed (the request's
            # admission index is abandoned; positions may skip, never
            # repeat).  Refund the never-queued payload first.
            with req._lock:
                req.payload_nbytes = 0
            self._payload_acct.sub(payload_nbytes)
            with self._lock:
                raise self._reject_locked("queue full",
                                          self.max_queue_depth,
                                          trace_id=tid)
        if self._closed:
            # the batcher exited between the admission check and the
            # enqueue (it marks _closed BEFORE its final leftover sweep,
            # so whichever of the two sweeps runs last sees this
            # request): nobody will ever pop the queue again — shed it
            # retriably NOW rather than strand it unaccounted
            self._drain_leftovers()
        telemetry.gauge("Serving/queue_depth").set(self._q.qsize())
        return req

    def _reject_locked(self, reason: str, depth: Optional[int] = None,
                       trace_id: Optional[str] = None, **kw) -> Overloaded:
        """Build the structured rejection and account it (caller raises).
        Runs under ``self._lock``.  The trace-recording choke point for
        the ``rejected`` verdict: the error carries its trace id."""
        self._counts["rejected"] += 1
        telemetry.counter("Serving/rejected").inc()
        telemetry.counter("Serving/rejected",
                          labels={"reason": reason.replace(" ", "_")}).inc()
        err = Overloaded(reason,
                         queue_depth=(depth if depth is not None
                                      else self._q.qsize()),
                         max_depth=self.max_queue_depth, **kw)
        request_trace.verdict(trace_id, "rejected", error=err,
                              reason=reason.replace(" ", "_"))
        return err

    # -- accounting -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """Outcome counters plus the accounting identity residual
        (``unaccounted`` includes requests still in flight — read after
        quiescing for the exact identity)."""
        with self._lock:
            out: Dict[str, Any] = dict(self._counts)
        out["unaccounted"] = out["submitted"] - sum(out[o] for o in OUTCOMES)
        out["batches"] = self.batches
        out["queue_depth"] = self._q.qsize()
        out["batch_ema_ms"] = self._ema.ema
        out["cooldown"] = self._cooldown
        out["draining"] = self._draining
        return out

    @property
    def sentinel(self):
        """The retrace sentinel guarding the serving forward (present
        when ``bigdl.compile.buckets`` is configured) — the chaos proof
        reads ``sentinel.retraces`` to assert zero post-warmup
        retraces."""
        fn = getattr(self.model, "_eval_jit", {}).get(id(None))
        return getattr(fn, "sentinel", None)

    def _account(self, req: RequestHandle, outcome: str,
                 error: Optional[BaseException] = None,
                 result=None, reason: Optional[str] = None) -> bool:
        if not req._finish(outcome, result=result, error=error):
            return False
        with req._lock:
            nbytes = req.payload_nbytes
            req.payload_nbytes = 0
        if nbytes:
            self._payload_acct.sub(nbytes)
        with self._lock:
            self._counts[outcome] += 1
        # the trace-recording choke point for every engine-side terminal
        # verdict; a completed tail request becomes a histogram exemplar
        request_trace.verdict(req.trace_id, outcome, error=error,
                              reason=reason)
        telemetry.counter(f"Serving/{outcome}").inc()
        if reason:
            telemetry.counter(f"Serving/{outcome}",
                              labels={"reason": reason}).inc()
        if outcome == "completed":
            self._latency.observe(req.latency_ms(),
                                  exemplar=req.trace_id)
        return True

    # -- the batcher thread -----------------------------------------------

    def _batcher_loop(self) -> None:
        telemetry.name_thread("serving-batcher")
        wd = None
        if self.stall_factor > 0:
            wd = HungDispatchWatchdog(
                self.stall_factor, warmup=self.warmup_batches,
                cooldown=self.cooldown_batches,
                poll_interval=min(self.poll_interval, 0.05))
            wd.start()                    # driver tid = this thread
            self.watchdog = wd
        try:
            while True:
                if not self._draining:
                    if elastic.preemption_requested():
                        with self._lock:
                            self._begin_drain_locked(
                                "preemption",
                                elastic.preemption_requested_at() or
                                time.monotonic())
                    elif self._stop_event.is_set():
                        with self._lock:
                            self._begin_drain_locked("stop",
                                                     time.monotonic())
                if self._draining:
                    if self._q.empty():
                        break
                    if time.monotonic() > self._drain_deadline:
                        self._drain_leftovers()
                        break
                try:
                    with (wd.paused() if wd is not None else nullcontext()):
                        first = self._q.get(timeout=self.poll_interval)
                except queue.Empty:
                    with self._lock:
                        if self._cooldown:
                            # backlog clear: nothing left to prove — a
                            # cooldown with no traffic would never end
                            self._cooldown = 0
                    continue
                batch: List[RequestHandle] = []
                try:
                    self._assemble(first, batch, wd)
                    if batch:
                        self._dispatch_batch(batch, wd)
                    elif wd is not None:
                        # a round that shed/quarantined everything it
                        # popped supervised no dispatch: restart the
                        # open interval so shed-storm bookkeeping can
                        # never accumulate into a spurious fire
                        wd.reset_interval()
                except HungDispatchError:
                    # re-raise the injected (argument-less) class as a
                    # DIAGNOSED instance: clients see why their request
                    # died, not just that it did
                    ema = self._ema.ema
                    baseline = (f"{ema:.1f} ms EMA" if ema is not None
                                else "unseeded EMA")
                    diag = HungDispatchError(
                        f"dispatch wedged past {self.stall_factor:.1f}x "
                        f"the batch-time baseline ({baseline}) — the "
                        "hung-dispatch watchdog aborted it")
                    self._abort_inflight(batch, diag, "hung_dispatch", wd,
                                         cool=True)
                except Exception as e:  # noqa: BLE001 — engine must outlive
                    self._abort_inflight(
                        batch,
                        ServingInfraError(f"dispatch failed: {e!r}"),
                        "infra", wd, cool=False)
        finally:
            if wd is not None:
                wd.stop()
            # _closed BEFORE the sweep: a racing submit that enqueued
            # past the drain either observes _closed (and sheds its own
            # request) or enqueued before this sweep (which sheds it) —
            # exactly one of the two, never neither
            with self._lock:
                self._closed = True
            self._drain_leftovers()

    def _begin_drain_locked(self, reason: str, started_at: float,
                            grace: Optional[float] = None) -> None:
        """Enter drain mode (callers hold ``self._lock``): admission now
        rejects retriably, the batcher keeps dispatching until the queue
        empties or the grace clock — started when the preemption/stop was
        REQUESTED, not when the batcher noticed — runs out.  The
        deadline is published BEFORE the flag: the batcher reads both
        lock-free, and flag-first would let it compare against a still-
        None deadline."""
        budget = grace if grace is not None else self.grace_period
        self._drain_deadline = started_at + budget
        self._drain_reason = reason
        self._draining = True
        incident.record("serving/drain", reason=reason, grace_s=budget,
                        queued=self._q.qsize())
        logger.info("serving engine draining (%s): grace %.1f s, "
                    "%d request(s) queued", reason, budget,
                    self._q.qsize())

    def _drain_leftovers(self) -> None:
        """Shed everything still queued (drain deadline lapsed, or the
        engine is going down) — retriable by construction: the payloads
        were never the problem."""
        shed = 0
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            err = ServingInfraError(
                "engine draining: request was not dispatched within the "
                "grace period — retriable")
            shed += self._account(req, "shed", error=err, reason="drained")
        if shed:
            incident.record("serving/drain_shed", count=shed)
            logger.warning("serving drain shed %d queued request(s)", shed)
        telemetry.gauge("Serving/queue_depth").set(self._q.qsize())

    def _assemble(self, first: RequestHandle, batch: List[RequestHandle],
                  wd) -> None:
        """Coalesce up to ``maxBatch`` VALID requests into ``batch``:
        expired ones are shed (cheap, before any device work), poison
        ones quarantined — neither consumes a slot."""
        from bigdl_tpu.utils import chaos
        req: Optional[RequestHandle] = first
        linger_until = (time.monotonic() + self.linger_ms / 1e3
                        if self.linger_ms > 0 else None)
        dequeued_ns: Dict[int, int] = {}
        while True:
            if req is not None:
                now = telemetry.clock_ns()
                request_trace.record_span(req.trace_id,
                                          "request/queue_wait",
                                          req.submit_ns, now)
                dequeued_ns[id(req)] = now
                if now > req.deadline_ns:
                    waited = (now - req.submit_ns) / 1e6
                    deadline = (req.deadline_ns - req.submit_ns) / 1e6
                    self._account(
                        req, "shed",
                        error=DeadlineExceeded(waited, deadline),
                        reason="expired")
                else:
                    try:
                        row = self._decode(req, chaos)
                    except ServingDataError as e:
                        incident.record("serving/quarantine",
                                        index=req.index,
                                        error=type(e).__name__)
                        self._account(req, "quarantined", error=e)
                        # bundle AFTER the verdict so the trace it
                        # embeds is terminal; the write stalls this
                        # thread for tens of ms — legitimate work, not
                        # a wedged dispatch, so the watchdog is paused
                        with (wd.paused() if wd is not None
                              else nullcontext()):
                            incident.maybe_dump("serving/quarantine",
                                                trace_id=req.trace_id)
                    else:
                        req.raw = row
                        batch.append(req)
            if len(batch) >= self.max_batch:
                break
            try:
                req = self._q.get_nowait()
                continue
            except queue.Empty:
                req = None
            if linger_until is None or not batch:
                break
            remaining = linger_until - time.monotonic()
            if remaining <= 0:
                break
            try:
                with (wd.paused() if wd is not None else nullcontext()):
                    req = self._q.get(timeout=remaining)
            except queue.Empty:
                break
        if request_trace.enabled() and batch:
            done = telemetry.clock_ns()
            for r in batch:
                t0 = dequeued_ns.get(id(r))
                if t0 is not None:
                    request_trace.record_span(r.trace_id,
                                              "request/coalesce",
                                              t0, done, size=len(batch))
        telemetry.gauge("Serving/queue_depth").set(self._q.qsize())

    def _decode(self, req: RequestHandle, chaos) -> np.ndarray:
        """Per-request validation — the taxonomy choke point: anything
        wrong with the PAYLOAD raises :class:`ServingDataError` here,
        where it can fail one request instead of a batch."""
        chaos.on_serving_request(req.index)
        if chaos.poison_request(req.index):
            raise ServingDataError(
                f"chaos: poison request at admission position {req.index}")
        try:
            row = np.asarray(req.raw)
        except Exception as e:
            raise ServingDataError(
                f"undecodable request payload: {e!r}") from e
        if not np.issubdtype(row.dtype, np.number):
            raise ServingDataError(
                f"non-numeric request payload (dtype {row.dtype})")
        if self._template is None:
            with self._lock:
                if self._template is None:
                    self._template = (row.shape, str(row.dtype))
        if (row.shape, str(row.dtype)) != self._template:
            raise ServingDataError(
                f"ill-shaped request: got {row.shape} {row.dtype}, this "
                f"engine serves {self._template[0]} {self._template[1]} "
                "(a mismatched row would retrace the fused step for "
                "everyone)")
        return row

    def _run_forward(self, rows: np.ndarray):
        """Pad to the bucket plan, execute the tracked executable, pull
        host results once, slice the padding back off."""
        from bigdl_tpu.analysis.hostsync import host_pull
        from bigdl_tpu.engine import to_device
        from bigdl_tpu.utils import compile_cache
        n = rows.shape[0]
        eff = compile_cache.bucket_size(n, self._buckets)
        inputs = (compile_cache.pad_batch(rows, n, eff)
                  if eff != n else rows)
        out_dev = self._forward(to_device(inputs))
        out = host_pull(out_dev, what="serving outputs")
        return compile_cache.slice_rows(out, n)

    def _dispatch_batch(self, batch: List[RequestHandle], wd) -> None:
        from bigdl_tpu.utils import chaos, compile_cache
        t0 = telemetry.clock_ns()
        self.batches += 1
        chaos.on_dispatch(f"batch {self.batches}")
        out = self._run_forward(np.stack([r.raw for r in batch]))
        if request_trace.enabled():
            t1 = telemetry.clock_ns()
            padded = compile_cache.bucket_size(len(batch), self._buckets)
            for req in batch:
                request_trace.record_span(
                    req.trace_id, "request/dispatch", t0, t1,
                    batch=self.batches, rows=len(batch),
                    pad_to_bucket=padded)
        import jax
        for i, req in enumerate(batch):
            row_out = jax.tree_util.tree_map(lambda x, _i=i: x[_i], out)
            self._account(req, "completed", result=row_out)
        ms = (telemetry.clock_ns() - t0) / 1e6
        self._ema.observe(ms)
        if wd is not None:
            wd.heartbeat()
        with self._lock:
            if self._cooldown > 0:
                self._cooldown -= 1
        self._occupancy.observe(len(batch))
        telemetry.counter("Serving/batches").inc()
        g = telemetry.gauge
        g("Serving/batch_ms").set(ms)
        for q in (50, 95, 99):
            g(f"Serving/p{q}_ms").set(self._latency.percentile(q))

    def _abort_inflight(self, batch: List[RequestHandle],
                        error: ServingError, reason: str, wd,
                        cool: bool) -> None:
        """A dispatch died under the batch: fail every unfinished
        in-flight request with the diagnosis and — for a hung dispatch —
        close admission until the engine proves itself again
        (``cooldownSteps`` clean batches, or the backlog clearing).
        Each victim gets its OWN exception instance: concurrent
        ``result()`` raises on a shared object would interleave
        tracebacks across client threads."""
        failed = sum(
            self._account(r, "shed", error=type(error)(*error.args),
                          reason=reason)
            for r in batch)
        incident.record("serving/abort_inflight", reason=reason,
                        victims=failed, error=type(error).__name__)
        incident.maybe_dump(f"serving/{reason}",
                            trace_id=batch[0].trace_id if batch else None)
        if cool:
            with self._lock:
                self._cooldown = max(self._cooldown, self.cooldown_batches)
        logger.error(
            "serving dispatch aborted (%s): %d in-flight request(s) "
            "failed with %s%s", reason, failed, type(error).__name__,
            f"; cooling down for {self.cooldown_batches} batches"
            if cool else "")
        if wd is not None:
            # the stall is over from the monitor's view: reset its open
            # interval so the NEXT dispatch is judged on its own clock
            wd.heartbeat()
