"""Overload-tolerant serving: the admission-controlled request path.

The repo's ``Predictor`` serves an *array*; production serves a *queue*.
This package is the request path built on top of ``Predictor``/
``fold_bn`` whose headline property is that it degrades gracefully
instead of falling over:

- :class:`~bigdl_tpu.serving.engine.ServingEngine` — a bounded admission
  queue with per-request deadlines feeding a continuous micro-batching
  dispatcher: requests coalesce up to ``bigdl.serving.maxBatch``, pad to
  the ``bigdl.compile.buckets`` shape plan (zero post-warmup retraces
  under arbitrary arrival patterns), execute through the tracked compile
  cache, and fan back per-request.
- Robustness is the build, not a bolt-on: admission rejects fast with a
  structured :class:`~bigdl_tpu.serving.engine.Overloaded` (reject at
  the door, never silent tail-latency collapse); expired requests are
  shed at dequeue time before wasting a device slot; a poison-request
  quarantine (:class:`~bigdl_tpu.serving.engine.ServingDataError` vs
  :class:`~bigdl_tpu.serving.engine.ServingInfraError` — the PR 7
  taxonomy) fails the one offending request and keeps the batch alive;
  a hung-dispatch watchdog aborts a wedged dispatch and cools the
  engine down; SIGTERM drains in-flight work within
  ``bigdl.serving.gracePeriod`` and rejects late arrivals retriably.
- :class:`~bigdl_tpu.serving.lm.LMServingEngine` — LM TOKEN serving:
  continuous (iteration-level) batching over a paged block-table KV
  cache (:class:`~bigdl_tpu.serving.kv_cache.PagedKVCache`, sized once
  under the HBM preflight budget), one fixed ``(maxBatch, 1)`` decode
  shape plus a bucketed prefill plan under the strict retrace-sentinel
  contract, per-request streaming :class:`~bigdl_tpu.serving.lm.
  TokenStream` output, and an optional int8 decode-weight tier gated by
  the HLO auditor's precision pass + an fp-vs-int8 logits allclose
  (``docs/optimization.md`` "LM serving").
- :mod:`~bigdl_tpu.serving.loadgen` — the Poisson open-loop load
  generator the bench legs (``bench.py --serving-only`` /
  ``--lm-serving-only``) and the chaos proofs drive the engines with,
  including the ``bigdl.chaos.burstArrivals`` thundering-herd injector;
  :func:`~bigdl_tpu.serving.loadgen.run_lm_open_loop` adds client-side
  TTFT / inter-token-latency percentiles over streamed tokens.

Everything is instrumented through the PR 5 metrics registry
(``Serving/*`` and ``LM/*``: latency percentiles, queue depth, outcome
counters, block/slot occupancy) with Prometheus export, and
chaos-proven by the ``bigdl.chaos.slowRequestAt`` / ``poisonRequestAt``
/ ``hangDispatchAt`` / ``burstArrivals`` injectors plus the LM trio
``poisonPromptAt`` / ``hangDecodeAt`` / ``evictBlockAt``.
"""

from bigdl_tpu.serving.engine import (HungDispatchError, Overloaded,
                                      RequestHandle, ServingDataError,
                                      ServingEngine, ServingError,
                                      ServingInfraError)
from bigdl_tpu.serving.kv_cache import PagedKVCache
from bigdl_tpu.serving.lm import (LMServingEngine, QuantizationGateError,
                                  TokenStream, UnsupportedModelError)
from bigdl_tpu.serving.loadgen import (run_lm_open_loop, run_open_loop,
                                       sample_lm_workload)

__all__ = [
    "ServingEngine", "RequestHandle", "ServingError", "Overloaded",
    "ServingDataError", "ServingInfraError", "HungDispatchError",
    "LMServingEngine", "TokenStream", "PagedKVCache",
    "QuantizationGateError", "UnsupportedModelError",
    "run_open_loop", "run_lm_open_loop", "sample_lm_workload",
]
