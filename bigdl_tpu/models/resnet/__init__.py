"""ResNet family — CIFAR-10 and ImageNet variants
(reference ``models/resnet/ResNet.scala:57,132,211-244``).

TPU note: the reference's ``optnet`` buffer sharing (SpatialShareConvolution,
shareGradInput) is a CPU memory trick; under XLA buffer reuse is the
compiler's job, so plain convolutions are used everywhere.  Builders default
to ``layout="NHWC"``: the conv trunk computes channels-last (the TPU-native
image layout, ``nn/layout.py``) behind the unchanged NCHW input facade.
"""

import math

import jax
import jax.numpy as jnp

from bigdl_tpu.nn import (Sequential, SpatialConvolution, SpatialMaxPooling,
                          SpatialAveragePooling, SpatialBatchNormalization,
                          ReLU, ConcatTable, CAddTable, Identity, Linear,
                          View, Concat, MulConstant, Module, apply_layout)


class DatasetType:
    CIFAR10 = "cifar10"
    IMAGENET = "imagenet"


class ShortcutType:
    A = "A"  # zero-padded identity on dim change
    B = "B"  # 1x1 conv on dim change, identity otherwise
    C = "C"  # 1x1 conv everywhere


def _shortcut(n_in, n_out, stride, shortcut_type):
    use_conv = shortcut_type == ShortcutType.C or (
        shortcut_type == ShortcutType.B and n_in != n_out)
    if use_conv:
        s = Sequential()
        s.add(SpatialConvolution(n_in, n_out, 1, 1, stride, stride))
        s.add(SpatialBatchNormalization(n_out))
        return s
    if n_in != n_out:
        # Type A: strided subsample then pad channels with zeros by
        # concatenating a zeroed copy (reference ResNet.scala:139-144).
        s = Sequential()
        s.add(SpatialAveragePooling(1, 1, stride, stride))
        s.add(Concat(2).add(Identity()).add(MulConstant(0.0)))
        return s
    return Identity()


def _basic_block(n_in, n, stride, shortcut_type):
    s = Sequential()
    s.add(SpatialConvolution(n_in, n, 3, 3, stride, stride, 1, 1))
    s.add(SpatialBatchNormalization(n))
    s.add(ReLU())
    s.add(SpatialConvolution(n, n, 3, 3, 1, 1, 1, 1))
    s.add(SpatialBatchNormalization(n))
    block = Sequential()
    block.add(ConcatTable().add(s).add(_shortcut(n_in, n, stride, shortcut_type)))
    block.add(CAddTable())
    block.add(ReLU())
    return block, n


def _bottleneck(n_in, n, stride, shortcut_type):
    s = Sequential()
    s.add(SpatialConvolution(n_in, n, 1, 1, 1, 1, 0, 0))
    s.add(SpatialBatchNormalization(n))
    s.add(ReLU())
    s.add(SpatialConvolution(n, n, 3, 3, stride, stride, 1, 1))
    s.add(SpatialBatchNormalization(n))
    s.add(ReLU())
    s.add(SpatialConvolution(n, n * 4, 1, 1, 1, 1, 0, 0))
    s.add(SpatialBatchNormalization(n * 4))
    block = Sequential()
    block.add(ConcatTable().add(s).add(_shortcut(n_in, n * 4, stride, shortcut_type)))
    block.add(CAddTable())
    block.add(ReLU())
    return block, n * 4


def _layer(block_fn, n_in, features, count, stride, shortcut_type):
    s = Sequential()
    for i in range(count):
        b, n_in = block_fn(n_in, features, stride if i == 0 else 1, shortcut_type)
        s.add(b)
    return s, n_in


# (block counts per stage, final feature width, block fn)
_IMAGENET_CFG = {
    18: ((2, 2, 2, 2), 512, _basic_block),
    34: ((3, 4, 6, 3), 512, _basic_block),
    50: ((3, 4, 6, 3), 2048, _bottleneck),
    101: ((3, 4, 23, 3), 2048, _bottleneck),
    152: ((3, 8, 36, 3), 2048, _bottleneck),
    200: ((3, 24, 36, 3), 2048, _bottleneck),
}


def resnet(class_num: int, depth: int = 18,
           shortcut_type: str = ShortcutType.B,
           dataset: str = DatasetType.CIFAR10,
           layout: str = "NHWC") -> Sequential:
    model = Sequential()
    if dataset == DatasetType.IMAGENET:
        if depth not in _IMAGENET_CFG:
            raise ValueError(f"Invalid depth {depth}")
        counts, n_features, block = _IMAGENET_CFG[depth]
        model.add(SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3))
        model.add(SpatialBatchNormalization(64))
        model.add(ReLU())
        model.add(SpatialMaxPooling(3, 3, 2, 2, 1, 1))
        ch = 64
        for i, (features, count) in enumerate(zip((64, 128, 256, 512), counts)):
            l, ch = _layer(block, ch, features, count, 1 if i == 0 else 2,
                           shortcut_type)
            model.add(l)
        model.add(SpatialAveragePooling(7, 7, 1, 1))
        model.add(View(n_features).set_num_input_dims(3))
        model.add(Linear(n_features, class_num))
    elif dataset == DatasetType.CIFAR10:
        if (depth - 2) % 6 != 0:
            raise ValueError("depth should be one of 20, 32, 44, 56, 110, 1202")
        n = (depth - 2) // 6
        model.add(SpatialConvolution(3, 16, 3, 3, 1, 1, 1, 1))
        model.add(SpatialBatchNormalization(16))
        model.add(ReLU())
        ch = 16
        l, ch = _layer(_basic_block, ch, 16, n, 1, shortcut_type)
        model.add(l)
        l, ch = _layer(_basic_block, ch, 32, n, 2, shortcut_type)
        model.add(l)
        l, ch = _layer(_basic_block, ch, 64, n, 2, shortcut_type)
        model.add(l)
        model.add(SpatialAveragePooling(8, 8, 1, 1))
        model.add(View(64).set_num_input_dims(3))
        model.add(Linear(64, class_num))
    else:
        raise ValueError(f"Unknown dataset {dataset}")
    return apply_layout(model, layout)


def model_init(model: Module, rng=None) -> Module:
    """He-init convolutions, (1, 0) batchnorm, zero linear bias
    (reference ``ResNet.modelInit``, ``models/resnet/ResNet.scala:103-130``)."""
    model._ensure_init()
    if rng is None:
        rng = jax.random.PRNGKey(0)
    for m in model.modules():
        if isinstance(m, SpatialConvolution):
            rng, k = jax.random.split(rng)
            n = m.kernel_w * m.kernel_w * m.n_output_plane
            w = m.params["weight"]
            m.params["weight"] = (jax.random.normal(k, w.shape, w.dtype)
                                  * math.sqrt(2.0 / n))
            if m.with_bias:
                m.params["bias"] = jnp.zeros_like(m.params["bias"])
        elif isinstance(m, SpatialBatchNormalization):
            if "weight" in m.params:
                m.params["weight"] = jnp.ones_like(m.params["weight"])
            if "bias" in m.params:
                m.params["bias"] = jnp.zeros_like(m.params["bias"])
        elif isinstance(m, Linear):
            if "bias" in m.params:
                m.params["bias"] = jnp.zeros_like(m.params["bias"])
    return model
