"""ResNet Train driver — BASELINE config #3.

Reference equivalent: ``models/resnet/Train.scala`` — CIFAR-10 driver with
depth-20/32/... basic-block ResNets (the ImageNet architectures exist in the
same builder, reference ``ResNet.scala:211-244``); momentum SGD with
warm-up-free step decay, shortcut type A for CIFAR.

``--dataset imagenet`` trains the ImageNet-layout architecture on an
image-folder tree (or synthetic 224x224 records).

Run::

    python -m bigdl_tpu.models.resnet.train -f <cifar-folder> --depth 20
    python -m bigdl_tpu.models.resnet.train --synthetic 512 --depth 20
    python -m bigdl_tpu.models.resnet.train --synthetic 64 --dataset imagenet --depth 50
"""

import numpy as np

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import Sample
from bigdl_tpu.dataset.datasets import (CIFAR_MEAN_BGR, CIFAR_STD_BGR,
                                        load_cifar10)
from bigdl_tpu.models import driver_utils
from bigdl_tpu.models.resnet import (DatasetType, ShortcutType, model_init,
                                     resnet)


def _cifar_samples(images) -> list:
    mean = np.asarray(CIFAR_MEAN_BGR, dtype=np.float32)
    std = np.asarray(CIFAR_STD_BGR, dtype=np.float32)
    return [Sample(((img.data - mean) / std).transpose(2, 0, 1)
                   .astype(np.float32), np.float32(img.label))
            for img in images]


def _synthetic(n: int, side: int, classes: int, seed: int = 1) -> list:
    rng = np.random.RandomState(seed)
    out = []
    half = side // 2
    for lab in rng.randint(0, classes, size=n):
        img = rng.normal(0, 0.3, size=(3, side, side)).astype(np.float32)
        r, c = divmod(int(lab) % 4, 2)
        img[:, r * half:(r + 1) * half, c * half:(c + 1) * half] += 1.0
        out.append(Sample(img, np.float32(lab + 1)))
    return out


def main(argv=None):
    p = driver_utils.base_parser("Train ResNet on CIFAR-10 / ImageNet layout")
    p.add_argument("--depth", type=int, default=20,
                   help="20/32/44/56/110 (cifar10) or 18/34/50/101/152/200 "
                        "(imagenet)")
    p.add_argument("--dataset", choices=["cifar10", "imagenet"],
                   default="cifar10")
    p.add_argument("--classes", type=int, default=None)
    args = p.parse_args(argv)
    driver_utils.init_logging()

    imagenet = args.dataset == "imagenet"
    batch = args.batch_size or (64 if imagenet else 128)
    classes = args.classes or (1000 if imagenet else 10)
    side = 224 if imagenet else 32

    if args.synthetic:
        train = _synthetic(args.synthetic, side, min(classes, 4))
        val = _synthetic(max(args.synthetic // 4, 8), side, min(classes, 4),
                         seed=2)
    elif imagenet:
        from bigdl_tpu.dataset.dataset import DataSet
        raise SystemExit(
            "real ImageNet training needs the image-folder pipeline: "
            "point -f at a label-per-subdirectory tree or use --synthetic")
    else:
        train = _cifar_samples(load_cifar10(args.folder, "train"))
        val = _cifar_samples(load_cifar10(args.folder, "test"))

    def build():
        m = resnet(classes, depth=args.depth,
                   shortcut_type=(ShortcutType.B if imagenet
                                  else ShortcutType.A),
                   dataset=(DatasetType.IMAGENET if imagenet
                            else DatasetType.CIFAR10))
        return model_init(m)

    model, method = driver_utils.load_snapshots(
        args, build,
        lambda: optim.SGD(learning_rate=args.learning_rate or 0.1,
                          learning_rate_decay=0.0, weight_decay=1e-4,
                          momentum=0.9, dampening=0.0, nesterov=True))

    ds = driver_utils.make_dataset(train, args, batch)
    criterion = nn.CrossEntropyCriterion()
    opt = optim.Optimizer.create(model, ds, criterion)
    opt.set_optim_method(method)
    driver_utils.configure(opt, args, default_epochs=165, app_name="resnet")
    opt.set_validation(optim.every_epoch(), val, [optim.Top1Accuracy()],
                       batch_size=batch)
    trained = opt.optimize()

    from bigdl_tpu.optim.evaluator import Evaluator
    results = Evaluator(trained).test(val, [optim.Top1Accuracy()], batch)
    print(f"Final Top1Accuracy: {results[0][1]}")
    return trained


if __name__ == "__main__":
    main()
