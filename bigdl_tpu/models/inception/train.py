"""Inception v1/v2 ImageNet Train driver.

Reference equivalent: ``models/inception/Train.scala:39`` — ImageNet via the
SequenceFile pipeline, SGD with poly learning-rate decay, aux-classifier
heads folded into the loss.

``-f`` points at a SequenceFile tree (``DataSet.seq_file_folder``) or use
``--synthetic N``.

Run::

    python -m bigdl_tpu.models.inception.train --synthetic 64 -b 16
"""

import numpy as np

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import Sample
from bigdl_tpu.models import driver_utils
from bigdl_tpu.models.inception import (inception_v1_no_aux_classifier,
                                        inception_v2_no_aux_classifier)


def _synthetic(n: int, classes: int, seed: int = 1) -> list:
    rng = np.random.RandomState(seed)
    out = []
    for lab in rng.randint(0, min(classes, 4), size=n):
        img = rng.normal(0, 0.3, size=(3, 224, 224)).astype(np.float32)
        r, c = divmod(int(lab) % 4, 2)
        img[:, r * 112:(r + 1) * 112, c * 112:(c + 1) * 112] += 1.0
        out.append(Sample(img, np.float32(lab + 1)))
    return out


def _seqfile_dataset(folder: str, batch: int, partitions: int):
    """LAZY ImageNet pipeline: seq-file byte records -> per-pass decode,
    scale, crop, normalize, CHW sample, batch — nothing decodes up-front
    (the reference's transformer chain over the cached byte RDD)."""
    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.image import (BGRImgNormalizer, BGRImgToSample,
                                         CenterCrop, Scale)
    from bigdl_tpu.dataset.transformer import SampleToMiniBatch
    ds = DataSet.seq_file_folder(folder)
    return (ds.transform(Scale(256)).transform(CenterCrop(224, 224))
              .transform(BGRImgNormalizer((104.0, 117.0, 123.0),
                                          (1.0, 1.0, 1.0)))
              .transform(BGRImgToSample())
              .transform(SampleToMiniBatch(batch, max(1, partitions))))


def main(argv=None):
    p = driver_utils.base_parser("Train Inception v1/v2 (ImageNet layout)")
    p.add_argument("--version", choices=["v1", "v2"], default="v1")
    p.add_argument("--classes", type=int, default=1000)
    args = p.parse_args(argv)
    driver_utils.init_logging()
    batch = args.batch_size or 32

    if args.synthetic:
        train = _synthetic(args.synthetic, args.classes)
        val = _synthetic(max(args.synthetic // 4, 8), args.classes, seed=2)
        ds = driver_utils.make_dataset(train, args, batch)
    else:
        # lazy seq-file pipeline; validation needs its own folder in a real
        # deployment (reference Train.scala takes train/val dirs)
        ds = _seqfile_dataset(args.folder, batch, args.partitions)
        val = None

    build = (inception_v1_no_aux_classifier if args.version == "v1"
             else inception_v2_no_aux_classifier)

    model, method = driver_utils.load_snapshots(
        args, lambda: build(args.classes),
        lambda: optim.SGD(learning_rate=args.learning_rate or 0.01,
                          learning_rate_decay=0.0, weight_decay=0.0002,
                          momentum=0.9,
                          learning_rate_schedule=optim.Poly(0.5, 62000)))

    opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
    opt.set_optim_method(method)
    driver_utils.configure(opt, args, default_epochs=10,
                           app_name="inception")
    if val is not None:
        opt.set_validation(optim.every_epoch(), val,
                           [optim.Top1Accuracy(), optim.Top5Accuracy()],
                           batch_size=batch)
    trained = opt.optimize()
    print("Training done.")
    return trained


if __name__ == "__main__":
    main()
