"""Inception v1 / v2 for ImageNet
(reference ``models/inception/Inception_v1.scala:102``, ``Inception_v2.scala:152``).

Builders default to ``layout="NHWC"``: the whole inception trunk (towers,
channel concats, aux-head pools) computes channels-last behind the NCHW
facade (``nn/layout.py``).
"""

from bigdl_tpu.nn import (Sequential, SpatialConvolution, SpatialMaxPooling,
                          SpatialAveragePooling, SpatialCrossMapLRN,
                          SpatialBatchNormalization, ReLU, Concat, Dropout,
                          View, Linear, LogSoftMax, Xavier, Zeros,
                          apply_layout)


def _conv(n_in, n_out, kw, kh, sw=1, sh=1, pw=0, ph=0, name=None,
          propagate_back=True, xavier=True):
    c = SpatialConvolution(n_in, n_out, kw, kh, sw, sh, pw, ph, 1,
                           propagate_back, name=name)
    if xavier:
        c.set_init_method(Xavier(), Zeros())
    return c


def inception_layer_v1(input_size, config, name_prefix=""):
    """One GoogLeNet inception block: 1x1 / 3x3 / 5x5 / pool-proj towers
    concatenated along channels.  ``config = ((c1,), (r3, c3), (r5, c5), (cp,))``.
    """
    concat = Concat(2, name=name_prefix + "output")
    conv1 = Sequential()
    conv1.add(_conv(input_size, config[0][0], 1, 1, name=name_prefix + "1x1"))
    conv1.add(ReLU())
    concat.add(conv1)
    conv3 = Sequential()
    conv3.add(_conv(input_size, config[1][0], 1, 1, name=name_prefix + "3x3_reduce"))
    conv3.add(ReLU())
    conv3.add(_conv(config[1][0], config[1][1], 3, 3, 1, 1, 1, 1,
                    name=name_prefix + "3x3"))
    conv3.add(ReLU())
    concat.add(conv3)
    conv5 = Sequential()
    conv5.add(_conv(input_size, config[2][0], 1, 1, name=name_prefix + "5x5_reduce"))
    conv5.add(ReLU())
    conv5.add(_conv(config[2][0], config[2][1], 5, 5, 1, 1, 2, 2,
                    name=name_prefix + "5x5"))
    conv5.add(ReLU())
    concat.add(conv5)
    pool = Sequential()
    pool.add(SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil())
    pool.add(_conv(input_size, config[3][0], 1, 1, name=name_prefix + "pool_proj"))
    pool.add(ReLU())
    concat.add(pool)
    return concat


def _v1_stem():
    f = Sequential()
    f.add(_conv(3, 64, 7, 7, 2, 2, 3, 3, name="conv1/7x7_s2",
                propagate_back=False))
    f.add(ReLU())
    f.add(SpatialMaxPooling(3, 3, 2, 2).ceil())
    f.add(SpatialCrossMapLRN(5, 0.0001, 0.75))
    f.add(_conv(64, 64, 1, 1, name="conv2/3x3_reduce"))
    f.add(ReLU())
    f.add(_conv(64, 192, 3, 3, 1, 1, 1, 1, name="conv2/3x3"))
    f.add(ReLU())
    f.add(SpatialCrossMapLRN(5, 0.0001, 0.75))
    f.add(SpatialMaxPooling(3, 3, 2, 2).ceil())
    f.add(inception_layer_v1(192, ((64,), (96, 128), (16, 32), (32,)),
                             "inception_3a/"))
    f.add(inception_layer_v1(256, ((128,), (128, 192), (32, 96), (64,)),
                             "inception_3b/"))
    f.add(SpatialMaxPooling(3, 3, 2, 2).ceil())
    f.add(inception_layer_v1(480, ((192,), (96, 208), (16, 48), (64,)),
                             "inception_4a/"))
    return f


def inception_v1_no_aux_classifier(class_num: int = 1000,
                                   layout: str = "NHWC") -> Sequential:
    m = _v1_stem()
    m.add(inception_layer_v1(512, ((160,), (112, 224), (24, 64), (64,)),
                             "inception_4b/"))
    m.add(inception_layer_v1(512, ((128,), (128, 256), (24, 64), (64,)),
                             "inception_4c/"))
    m.add(inception_layer_v1(512, ((112,), (144, 288), (32, 64), (64,)),
                             "inception_4d/"))
    m.add(inception_layer_v1(528, ((256,), (160, 320), (32, 128), (128,)),
                             "inception_4e/"))
    m.add(SpatialMaxPooling(3, 3, 2, 2).ceil())
    m.add(inception_layer_v1(832, ((256,), (160, 320), (32, 128), (128,)),
                             "inception_5a/"))
    m.add(inception_layer_v1(832, ((384,), (192, 384), (48, 128), (128,)),
                             "inception_5b/"))
    m.add(SpatialAveragePooling(7, 7, 1, 1))
    m.add(Dropout(0.4))
    m.add(View(1024).set_num_input_dims(3))
    m.add(Linear(1024, class_num, name="loss3/classifier"))
    m.add(LogSoftMax())
    return apply_layout(m, layout)


def inception_v1(class_num: int = 1000, layout: str = "NHWC") -> Sequential:
    """Full GoogLeNet with the two auxiliary classifier heads; output is the
    channel-concat of [main, aux2, aux1] log-probabilities
    (reference ``Inception_v1.scala:104-186``)."""
    feature1 = _v1_stem()

    output1 = Sequential()
    output1.add(SpatialAveragePooling(5, 5, 3, 3).ceil())
    output1.add(_conv(512, 128, 1, 1, name="loss1/conv", xavier=False))
    output1.add(ReLU())
    output1.add(View(128 * 4 * 4).set_num_input_dims(3))
    output1.add(Linear(128 * 4 * 4, 1024, name="loss1/fc"))
    output1.add(ReLU())
    output1.add(Dropout(0.7))
    output1.add(Linear(1024, class_num, name="loss1/classifier"))
    output1.add(LogSoftMax())

    feature2 = Sequential()
    feature2.add(inception_layer_v1(512, ((160,), (112, 224), (24, 64), (64,)),
                                    "inception_4b/"))
    feature2.add(inception_layer_v1(512, ((128,), (128, 256), (24, 64), (64,)),
                                    "inception_4c/"))
    feature2.add(inception_layer_v1(512, ((112,), (144, 288), (32, 64), (64,)),
                                    "inception_4d/"))

    output2 = Sequential()
    output2.add(SpatialAveragePooling(5, 5, 3, 3))
    output2.add(_conv(528, 128, 1, 1, name="loss2/conv", xavier=False))
    output2.add(ReLU())
    output2.add(View(128 * 4 * 4).set_num_input_dims(3))
    output2.add(Linear(128 * 4 * 4, 1024, name="loss2/fc"))
    output2.add(ReLU())
    output2.add(Dropout(0.7))
    output2.add(Linear(1024, class_num, name="loss2/classifier"))
    output2.add(LogSoftMax())

    output3 = Sequential()
    output3.add(inception_layer_v1(528, ((256,), (160, 320), (32, 128), (128,)),
                                   "inception_4e/"))
    output3.add(SpatialMaxPooling(3, 3, 2, 2).ceil())
    output3.add(inception_layer_v1(832, ((256,), (160, 320), (32, 128), (128,)),
                                   "inception_5a/"))
    output3.add(inception_layer_v1(832, ((384,), (192, 384), (48, 128), (128,)),
                                   "inception_5b/"))
    output3.add(SpatialAveragePooling(7, 7, 1, 1))
    output3.add(Dropout(0.4))
    output3.add(View(1024).set_num_input_dims(3))
    output3.add(Linear(1024, class_num, name="loss3/classifier"))
    output3.add(LogSoftMax())

    split2 = Concat(2).add(output3).add(output2)
    main_branch = Sequential().add(feature2).add(split2)
    split1 = Concat(2).add(main_branch).add(output1)
    return apply_layout(Sequential().add(feature1).add(split1), layout)


def _conv_bn(seq, n_in, n_out, kw, kh, sw=1, sh=1, pw=0, ph=0, name="",
             propagate_back=True):
    seq.add(SpatialConvolution(n_in, n_out, kw, kh, sw, sh, pw, ph, 1,
                               propagate_back, name=name))
    seq.add(SpatialBatchNormalization(n_out, 1e-3))
    seq.add(ReLU())


def inception_layer_v2(input_size, config, name_prefix=""):
    """BN-Inception block.  ``config = ((c1,), (r3, c3), (r33, c33),
    (pool_kind, cp))`` where pool_kind in {"max", "avg"}; c1 == 0 drops the
    1x1 tower and the 3x3 towers stride 2 when cp == 0 under max pooling
    (reference ``Inception_v2.scala:27-115``)."""
    concat = Concat(2, name=name_prefix + "output")
    pool_kind, cp = config[3]
    reduce_grid = pool_kind == "max" and cp == 0

    if config[0][0] != 0:
        conv1 = Sequential()
        _conv_bn(conv1, input_size, config[0][0], 1, 1, name=name_prefix + "1x1")
        concat.add(conv1)

    conv3 = Sequential()
    _conv_bn(conv3, input_size, config[1][0], 1, 1,
             name=name_prefix + "3x3_reduce")
    stride = 2 if reduce_grid else 1
    _conv_bn(conv3, config[1][0], config[1][1], 3, 3, stride, stride, 1, 1,
             name=name_prefix + "3x3")
    concat.add(conv3)

    conv33 = Sequential()
    _conv_bn(conv33, input_size, config[2][0], 1, 1,
             name=name_prefix + "double3x3_reduce")
    _conv_bn(conv33, config[2][0], config[2][1], 3, 3, 1, 1, 1, 1,
             name=name_prefix + "double3x3a")
    _conv_bn(conv33, config[2][1], config[2][1], 3, 3, stride, stride, 1, 1,
             name=name_prefix + "double3x3b")
    concat.add(conv33)

    pool = Sequential()
    if pool_kind == "max":
        if cp != 0:
            pool.add(SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil())
        else:
            pool.add(SpatialMaxPooling(3, 3, 2, 2).ceil())
    elif pool_kind == "avg":
        p = SpatialAveragePooling(3, 3, 1, 1, 1, 1, ceil_mode=True)
        pool.add(p)
    else:
        raise ValueError(pool_kind)
    if cp != 0:
        _conv_bn(pool, input_size, cp, 1, 1, name=name_prefix + "pool_proj")
    concat.add(pool)
    return concat


_V2_BLOCKS_3 = [
    (192, ((64,), (64, 64), (64, 96), ("avg", 32)), "inception_3a/"),
    (256, ((64,), (64, 96), (64, 96), ("avg", 64)), "inception_3b/"),
    (320, ((0,), (128, 160), (64, 96), ("max", 0)), "inception_3c/"),
]
_V2_BLOCKS_4 = [
    (576, ((224,), (64, 96), (96, 128), ("avg", 128)), "inception_4a/"),
    (576, ((192,), (96, 128), (96, 128), ("avg", 128)), "inception_4b/"),
    (576, ((160,), (128, 160), (128, 160), ("avg", 96)), "inception_4c/"),
    (576, ((96,), (128, 192), (160, 192), ("avg", 96)), "inception_4d/"),
    (576, ((0,), (128, 192), (192, 256), ("max", 0)), "inception_4e/"),
]
_V2_BLOCKS_5 = [
    (1024, ((352,), (192, 320), (160, 224), ("avg", 128)), "inception_5a/"),
    (1024, ((352,), (192, 320), (192, 224), ("max", 128)), "inception_5b/"),
]


def _v2_stem():
    f = Sequential()
    _conv_bn(f, 3, 64, 7, 7, 2, 2, 3, 3, name="conv1/7x7_s2",
             propagate_back=False)
    f.add(SpatialMaxPooling(3, 3, 2, 2).ceil())
    _conv_bn(f, 64, 64, 1, 1, name="conv2/3x3_reduce")
    _conv_bn(f, 64, 192, 3, 3, 1, 1, 1, 1, name="conv2/3x3")
    f.add(SpatialMaxPooling(3, 3, 2, 2).ceil())
    return f


def inception_v2_no_aux_classifier(class_num: int = 1000,
                                   layout: str = "NHWC") -> Sequential:
    m = _v2_stem()
    for size, cfg, prefix in _V2_BLOCKS_3 + _V2_BLOCKS_4 + _V2_BLOCKS_5:
        m.add(inception_layer_v2(size, cfg, prefix))
    m.add(SpatialAveragePooling(7, 7, 1, 1, ceil_mode=True))
    m.add(View(1024).set_num_input_dims(3))
    m.add(Linear(1024, class_num, name="loss3/classifier"))
    m.add(LogSoftMax())
    return apply_layout(m, layout)


def inception_v2(class_num: int = 1000, layout: str = "NHWC") -> Sequential:
    features1 = _v2_stem()
    for size, cfg, prefix in _V2_BLOCKS_3:
        features1.add(inception_layer_v2(size, cfg, prefix))

    output1 = Sequential()
    p1 = SpatialAveragePooling(5, 5, 3, 3, ceil_mode=True)
    output1.add(p1)
    _conv_bn(output1, 576, 128, 1, 1, name="loss1/conv")
    output1.add(View(128 * 4 * 4).set_num_input_dims(3))
    output1.add(Linear(128 * 4 * 4, 1024, name="loss1/fc"))
    output1.add(ReLU())
    output1.add(Linear(1024, class_num, name="loss1/classifier"))
    output1.add(LogSoftMax())

    features2 = Sequential()
    for size, cfg, prefix in _V2_BLOCKS_4:
        features2.add(inception_layer_v2(size, cfg, prefix))

    output2 = Sequential()
    p2 = SpatialAveragePooling(5, 5, 3, 3, ceil_mode=True)
    output2.add(p2)
    _conv_bn(output2, 1024, 128, 1, 1, name="loss2/conv")
    output2.add(View(128 * 2 * 2).set_num_input_dims(3))
    output2.add(Linear(128 * 2 * 2, 1024, name="loss2/fc"))
    output2.add(ReLU())
    output2.add(Linear(1024, class_num, name="loss2/classifier"))
    output2.add(LogSoftMax())

    output3 = Sequential()
    for size, cfg, prefix in _V2_BLOCKS_5:
        output3.add(inception_layer_v2(size, cfg, prefix))
    output3.add(SpatialAveragePooling(7, 7, 1, 1, ceil_mode=True))
    output3.add(View(1024).set_num_input_dims(3))
    output3.add(Linear(1024, class_num, name="loss3/classifier"))
    output3.add(LogSoftMax())

    split2 = Concat(2).add(output3).add(output2)
    main_branch = Sequential().add(features2).add(split2)
    split1 = Concat(2).add(main_branch).add(output1)
    return apply_layout(Sequential().add(features1).add(split1), layout)
