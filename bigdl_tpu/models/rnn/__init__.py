"""Character-level simple RNN language model
(reference ``models/rnn/SimpleRNN.scala:22``)."""

from bigdl_tpu.nn import (Sequential, Recurrent, RnnCell, Tanh,
                          TimeDistributed, Linear, LogSoftMax)


def simple_rnn(input_size: int, hidden_size: int, output_size: int) -> Sequential:
    m = Sequential()
    m.add(Recurrent().add(RnnCell(input_size, hidden_size, Tanh())))
    m.add(TimeDistributed(Linear(hidden_size, output_size)))
    return m


def lstm_lm(input_size: int, hidden_size: int, output_size: int) -> Sequential:
    """LSTM language model used by the PTB-style config (BASELINE #5)."""
    from bigdl_tpu.nn import LSTM
    m = Sequential()
    m.add(Recurrent().add(LSTM(input_size, hidden_size)))
    m.add(TimeDistributed(Linear(hidden_size, output_size)))
    m.add(TimeDistributed(LogSoftMax()))
    return m
