"""RNN / LSTM language-model Train driver — BASELINE config #5.

Reference equivalent: ``models/rnn/Train.scala`` — tokenized corpus →
Dictionary → TextToLabeledSentence (LM shift pairs) → one-hot
LabeledSentenceToSample, SimpleRNN trained with TimeDistributedCriterion
(ClassNLL over every timestep).  ``--cell lstm`` trains the LSTM-LM (the
PTB-style config).

Run::

    python -m bigdl_tpu.models.rnn.train -f <corpus.txt> --cell lstm
    python -m bigdl_tpu.models.rnn.train --synthetic 256     # no data needed
"""

import os

import numpy as np

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset.text import (Dictionary, LabeledSentenceToSample,
                                    SentenceTokenizer, TextToLabeledSentence)
from bigdl_tpu.models import driver_utils
from bigdl_tpu.models.rnn import lstm_lm, simple_rnn


def _synthetic_corpus(n: int, seed: int = 1):
    """Deterministic bigram language: next word = (w + 1) % V with noise —
    learnable structure for convergence checks."""
    rng = np.random.RandomState(seed)
    vocab = [f"w{i}" for i in range(20)]
    sents = []
    for _ in range(n):
        start = rng.randint(0, 20)
        words = [vocab[(start + i) % 20] for i in range(12)]
        sents.append(words)
    return sents


def main(argv=None):
    p = driver_utils.base_parser("Train a character/word-level RNN LM")
    p.add_argument("--cell", choices=["rnn", "lstm"], default="rnn")
    p.add_argument("--hidden", type=int, default=40,
                   help="hidden size (reference hiddenSize=40)")
    p.add_argument("--vocab", type=int, default=4000,
                   help="max dictionary size (reference vocabSize)")
    p.add_argument("--seq-len", type=int, default=12,
                   help="fixed unroll length (padding/truncation)")
    args = p.parse_args(argv)
    driver_utils.init_logging()
    batch = args.batch_size or 32

    if args.synthetic:
        sentences = _synthetic_corpus(args.synthetic)
    else:
        path = args.folder
        if os.path.isdir(path):
            path = os.path.join(path, "input.txt")
        with open(path) as f:
            text = f.read()
        tok = SentenceTokenizer()
        sentences = [s for s in tok(iter(text.split("\n"))) if len(s) > 2]

    dictionary = Dictionary(sentences, args.vocab)
    vocab = dictionary.vocab_size() + 1

    to_lm = TextToLabeledSentence(dictionary)
    to_sample = LabeledSentenceToSample(vocab, fixed_length=args.seq_len,
                                        one_hot=True)
    records = list(to_sample(to_lm(iter(sentences))))
    split = max(1, int(len(records) * 0.9))
    train, val = records[:split], records[split:] or records[:1]

    def build():
        if args.cell == "lstm":
            return lstm_lm(vocab, args.hidden, vocab)
        m = simple_rnn(vocab, args.hidden, vocab)
        m.add(nn.TimeDistributed(nn.LogSoftMax()))
        return m

    model, method = driver_utils.load_snapshots(
        args, build,
        lambda: optim.Adagrad(learning_rate=args.learning_rate or 0.1,
                              learning_rate_decay=0.001))

    ds = driver_utils.make_dataset(train, args, batch)
    criterion = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                            size_average=True)
    opt = optim.Optimizer.create(model, ds, criterion)
    opt.set_optim_method(method)
    driver_utils.configure(opt, args, default_epochs=30, app_name="rnn")
    opt.set_validation(optim.every_epoch(), val, [optim.Loss(criterion)],
                       batch_size=batch)
    trained = opt.optimize()
    print("Training done.")
    return trained


if __name__ == "__main__":
    main()
