"""VGG family (reference ``models/vgg/VggForCifar10.scala:22,71,124``).

Builders default to ``layout="NHWC"``: channels-last conv trunk behind the
NCHW facade (``nn/layout.py``)."""

from bigdl_tpu.nn import (Sequential, SpatialConvolution, SpatialMaxPooling,
                          SpatialBatchNormalization, BatchNormalization, ReLU,
                          Dropout, View, Linear, LogSoftMax, Threshold,
                          apply_layout)


def vgg_for_cifar10(class_num: int = 10, layout: str = "NHWC") -> Sequential:
    """VGG-16-style BN+Dropout net for 32x32 CIFAR-10 images."""
    m = Sequential()

    def conv_bn_relu(n_in, n_out):
        m.add(SpatialConvolution(n_in, n_out, 3, 3, 1, 1, 1, 1))
        m.add(SpatialBatchNormalization(n_out, 1e-3))
        m.add(ReLU())

    conv_bn_relu(3, 64); m.add(Dropout(0.3))
    conv_bn_relu(64, 64)
    m.add(SpatialMaxPooling(2, 2, 2, 2).ceil())
    conv_bn_relu(64, 128); m.add(Dropout(0.4))
    conv_bn_relu(128, 128)
    m.add(SpatialMaxPooling(2, 2, 2, 2).ceil())
    conv_bn_relu(128, 256); m.add(Dropout(0.4))
    conv_bn_relu(256, 256); m.add(Dropout(0.4))
    conv_bn_relu(256, 256)
    m.add(SpatialMaxPooling(2, 2, 2, 2).ceil())
    conv_bn_relu(256, 512); m.add(Dropout(0.4))
    conv_bn_relu(512, 512); m.add(Dropout(0.4))
    conv_bn_relu(512, 512)
    m.add(SpatialMaxPooling(2, 2, 2, 2).ceil())
    conv_bn_relu(512, 512); m.add(Dropout(0.4))
    conv_bn_relu(512, 512); m.add(Dropout(0.4))
    conv_bn_relu(512, 512)
    m.add(SpatialMaxPooling(2, 2, 2, 2).ceil())
    m.add(View(512))

    m.add(Dropout(0.5))
    m.add(Linear(512, 512))
    m.add(BatchNormalization(512))
    m.add(ReLU())
    m.add(Dropout(0.5))
    m.add(Linear(512, class_num))
    m.add(LogSoftMax())
    return apply_layout(m, layout)


def _vgg_imagenet(block_convs, class_num: int, layout: str) -> Sequential:
    m = Sequential()
    n_in = 3
    widths = (64, 128, 256, 512, 512)
    for width, n_convs in zip(widths, block_convs):
        for _ in range(n_convs):
            m.add(SpatialConvolution(n_in, width, 3, 3, 1, 1, 1, 1))
            m.add(ReLU())
            n_in = width
        m.add(SpatialMaxPooling(2, 2, 2, 2))
    m.add(View(512 * 7 * 7))
    m.add(Linear(512 * 7 * 7, 4096))
    m.add(Threshold(0, 1e-6))
    m.add(Dropout(0.5))
    m.add(Linear(4096, 4096))
    m.add(Threshold(0, 1e-6))
    m.add(Dropout(0.5))
    m.add(Linear(4096, class_num))
    m.add(LogSoftMax())
    return apply_layout(m, layout)


def vgg16(class_num: int = 1000, layout: str = "NHWC") -> Sequential:
    return _vgg_imagenet((2, 2, 3, 3, 3), class_num, layout)


def vgg19(class_num: int = 1000, layout: str = "NHWC") -> Sequential:
    return _vgg_imagenet((2, 2, 4, 4, 4), class_num, layout)
