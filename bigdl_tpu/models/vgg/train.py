"""VGG/CIFAR-10 Train driver — BASELINE config #2.

Reference equivalent: ``models/vgg/Train.scala`` — CIFAR-10 binary batches,
BGR normalization, VggForCifar10, SGD with momentum/weight decay, Top1
validation per epoch.  ``--partitions N`` trains data-parallel over the
device mesh (the reference's DistriOptimizer deployment).

Run::

    python -m bigdl_tpu.models.vgg.train -f <cifar-folder> --partitions 8
    python -m bigdl_tpu.models.vgg.train --synthetic 512     # no data needed
"""

import numpy as np

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import Sample
from bigdl_tpu.dataset.datasets import (CIFAR_MEAN_BGR, CIFAR_STD_BGR,
                                        load_cifar10)
from bigdl_tpu.models import driver_utils
from bigdl_tpu.models.vgg import vgg_for_cifar10


def _to_samples(images) -> list:
    mean = np.asarray(CIFAR_MEAN_BGR, dtype=np.float32)
    std = np.asarray(CIFAR_STD_BGR, dtype=np.float32)
    out = []
    for img in images:
        chw = ((img.data - mean) / std).transpose(2, 0, 1)
        out.append(Sample(chw.astype(np.float32), np.float32(img.label)))
    return out


def _synthetic(n: int, seed: int = 1) -> list:
    rng = np.random.RandomState(seed)
    out = []
    for lab in rng.randint(0, 10, size=n):
        img = rng.normal(0, 0.3, size=(3, 32, 32)).astype(np.float32)
        r, c = divmod(int(lab) % 4, 2)
        img[:, r * 16:(r + 1) * 16, c * 16:(c + 1) * 16] += 1.0 + 0.1 * lab
        out.append(Sample(img, np.float32(lab + 1)))
    return out


def main(argv=None):
    p = driver_utils.base_parser("Train VGG on CIFAR-10")
    args = p.parse_args(argv)
    driver_utils.init_logging()
    batch = args.batch_size or 128

    if args.synthetic:
        train, val = _synthetic(args.synthetic), _synthetic(
            max(args.synthetic // 4, 10), seed=2)
    else:
        train = _to_samples(load_cifar10(args.folder, "train"))
        val = _to_samples(load_cifar10(args.folder, "test"))

    model, method = driver_utils.load_snapshots(
        args, lambda: vgg_for_cifar10(10),
        lambda: optim.SGD(learning_rate=args.learning_rate or 0.01,
                          learning_rate_decay=0.0, weight_decay=0.0005,
                          momentum=0.9, dampening=0.0))

    ds = driver_utils.make_dataset(train, args, batch)
    opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
    opt.set_optim_method(method)
    driver_utils.configure(opt, args, default_epochs=90, app_name="vgg")
    opt.set_validation(optim.every_epoch(), val, [optim.Top1Accuracy()],
                       batch_size=batch)
    trained = opt.optimize()

    from bigdl_tpu.optim.evaluator import Evaluator
    results = Evaluator(trained).test(val, [optim.Top1Accuracy()], batch)
    print(f"Final Top1Accuracy: {results[0][1]}")
    return trained


if __name__ == "__main__":
    main()
