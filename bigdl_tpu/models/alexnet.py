"""AlexNet (reference ``example/loadmodel/AlexNet.scala``).

Builders default to ``layout="NHWC"``: channels-last conv trunk behind the
NCHW facade (``nn/layout.py``)."""

from bigdl_tpu.nn import (Sequential, SpatialConvolution, SpatialMaxPooling,
                          SpatialCrossMapLRN, ReLU, Dropout, View, Linear,
                          LogSoftMax, apply_layout)


def alexnet_owt(class_num: int = 1000, has_dropout: bool = True,
                first_layer_propagate_back: bool = False,
                layout: str = "NHWC") -> Sequential:
    """One-weird-trick AlexNet (no LRN, no grouping)."""
    m = Sequential()
    m.add(SpatialConvolution(3, 64, 11, 11, 4, 4, 2, 2, 1,
                             first_layer_propagate_back, name="conv1"))
    m.add(ReLU())
    m.add(SpatialMaxPooling(3, 3, 2, 2))
    m.add(SpatialConvolution(64, 192, 5, 5, 1, 1, 2, 2, name="conv2"))
    m.add(ReLU())
    m.add(SpatialMaxPooling(3, 3, 2, 2))
    m.add(SpatialConvolution(192, 384, 3, 3, 1, 1, 1, 1, name="conv3"))
    m.add(ReLU())
    m.add(SpatialConvolution(384, 256, 3, 3, 1, 1, 1, 1, name="conv4"))
    m.add(ReLU())
    m.add(SpatialConvolution(256, 256, 3, 3, 1, 1, 1, 1, name="conv5"))
    m.add(ReLU())
    m.add(SpatialMaxPooling(3, 3, 2, 2))
    m.add(View(256 * 6 * 6))
    m.add(Linear(256 * 6 * 6, 4096, name="fc6"))
    m.add(ReLU())
    if has_dropout:
        m.add(Dropout(0.5))
    m.add(Linear(4096, 4096, name="fc7"))
    m.add(ReLU())
    if has_dropout:
        m.add(Dropout(0.5))
    m.add(Linear(4096, class_num, name="fc8"))
    m.add(LogSoftMax())
    return apply_layout(m, layout)


def alexnet(class_num: int = 1000, layout: str = "NHWC") -> Sequential:
    """Original grouped AlexNet with cross-map LRN."""
    m = Sequential()
    m.add(SpatialConvolution(3, 96, 11, 11, 4, 4, 0, 0, 1, False, name="conv1"))
    m.add(ReLU())
    m.add(SpatialCrossMapLRN(5, 0.0001, 0.75))
    m.add(SpatialMaxPooling(3, 3, 2, 2))
    m.add(SpatialConvolution(96, 256, 5, 5, 1, 1, 2, 2, 2, name="conv2"))
    m.add(ReLU())
    m.add(SpatialCrossMapLRN(5, 0.0001, 0.75))
    m.add(SpatialMaxPooling(3, 3, 2, 2))
    m.add(SpatialConvolution(256, 384, 3, 3, 1, 1, 1, 1, name="conv3"))
    m.add(ReLU())
    m.add(SpatialConvolution(384, 384, 3, 3, 1, 1, 1, 1, 2, name="conv4"))
    m.add(ReLU())
    m.add(SpatialConvolution(384, 256, 3, 3, 1, 1, 1, 1, 2, name="conv5"))
    m.add(ReLU())
    m.add(SpatialMaxPooling(3, 3, 2, 2))
    m.add(View(256 * 6 * 6))
    m.add(Linear(256 * 6 * 6, 4096, name="fc6"))
    m.add(ReLU())
    m.add(Dropout(0.5))
    m.add(Linear(4096, 4096, name="fc7"))
    m.add(ReLU())
    m.add(Dropout(0.5))
    m.add(Linear(4096, class_num, name="fc8"))
    m.add(LogSoftMax())
    return apply_layout(m, layout)
