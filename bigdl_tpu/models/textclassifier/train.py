"""TextClassifier CNN Train driver — BASELINE config #4.

Reference equivalent: ``example/textclassification/TextClassifier.scala:42``
— GloVe word vectors + a newsgroup-style corpus (label-per-subdirectory),
tokenize, embed to (seq_len, embed_dim) float features, train the temporal
CNN (``example/utils/TextClassifier.scala:171``) with Adagrad.

Run::

    python -m bigdl_tpu.models.textclassifier.train -f <base-dir>
      # <base-dir>/glove.6B/glove.6B.200d.txt
      # <base-dir>/20news-18828/<category>/<doc>
    python -m bigdl_tpu.models.textclassifier.train --synthetic 256
"""

import os

import numpy as np

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import Sample
from bigdl_tpu.dataset.datasets import load_glove
from bigdl_tpu.dataset.text import SentenceTokenizer
from bigdl_tpu.models import driver_utils
from bigdl_tpu.models.textclassifier import text_classifier

SEQ_LEN = 1000       # reference maxSequenceLength
EMBED_DIM = 200      # reference embeddingDim (glove.6B.200d)


def _synthetic(n: int, classes: int = 4, seed: int = 1) -> list:
    """Class-dependent mean direction + noise over the embedded sequence."""
    rng = np.random.RandomState(seed)
    directions = rng.normal(0, 1, size=(classes, EMBED_DIM)).astype(np.float32)
    out = []
    for lab in rng.randint(0, classes, size=n):
        seq = rng.normal(0, 0.5, size=(SEQ_LEN, EMBED_DIM)).astype(np.float32)
        seq += 0.3 * directions[lab]
        out.append(Sample(seq, np.float32(lab + 1)))
    return out


def _load_corpus(base_dir: str, max_words: int):
    glove_path = os.path.join(base_dir, "glove.6B",
                              f"glove.6B.{EMBED_DIM}d.txt")
    vectors = load_glove(glove_path, EMBED_DIM)
    news_dir = None
    for cand in ("20news-18828", "20_newsgroup", "texts"):
        d = os.path.join(base_dir, cand)
        if os.path.isdir(d):
            news_dir = d
            break
    if news_dir is None:
        raise SystemExit(f"no corpus directory under {base_dir}")

    tok = SentenceTokenizer()
    records = []
    classes = sorted(d for d in os.listdir(news_dir)
                     if os.path.isdir(os.path.join(news_dir, d)))
    for label, cls in enumerate(classes, start=1):
        cdir = os.path.join(news_dir, cls)
        for fname in sorted(os.listdir(cdir)):
            with open(os.path.join(cdir, fname), errors="ignore") as f:
                words = next(tok(iter([f.read()])), [])[:max_words]
            seq = np.zeros((SEQ_LEN, EMBED_DIM), dtype=np.float32)
            for i, w in enumerate(words[:SEQ_LEN]):
                v = vectors.get(w)
                if v is not None:
                    seq[i] = v
            records.append(Sample(seq, np.float32(label)))
    return records, len(classes)


def main(argv=None):
    p = driver_utils.base_parser("Train the GloVe text-classification CNN")
    p.add_argument("--max-words", type=int, default=SEQ_LEN)
    p.add_argument("--training-split", type=float, default=0.8)
    args = p.parse_args(argv)
    driver_utils.init_logging()
    batch = args.batch_size or 128

    if args.synthetic:
        records, classes = _synthetic(args.synthetic), 4
    else:
        records, classes = _load_corpus(args.folder, args.max_words)
    rng = np.random.RandomState(42)
    order = rng.permutation(len(records))
    split = int(len(records) * args.training_split)
    train = [records[i] for i in order[:split]]
    val = [records[i] for i in order[split:]] or train[:1]

    model, method = driver_utils.load_snapshots(
        args, lambda: text_classifier(classes, EMBED_DIM, SEQ_LEN),
        lambda: optim.Adagrad(learning_rate=args.learning_rate or 0.01,
                              learning_rate_decay=0.0002))

    ds = driver_utils.make_dataset(train, args, batch)
    opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
    opt.set_optim_method(method)
    driver_utils.configure(opt, args, default_epochs=20,
                           app_name="textclassifier")
    opt.set_validation(optim.every_epoch(), val, [optim.Top1Accuracy()],
                       batch_size=batch)
    trained = opt.optimize()

    from bigdl_tpu.optim.evaluator import Evaluator
    results = Evaluator(trained).test(val, [optim.Top1Accuracy()], batch)
    print(f"Final Top1Accuracy: {results[0][1]}")
    return trained


if __name__ == "__main__":
    main()
