"""Text-classification CNN over word embeddings
(reference ``example/utils/TextClassifier.scala:171``).

Input: (batch, seq_len, embedding_dim) GloVe-embedded token sequences.
The reference reshapes to a (embedding_dim, 1, seq_len) image and runs
SpatialConvolution as a temporal conv; here TemporalConvolution maps
directly onto a single MXU matmul per window.
"""

from bigdl_tpu.nn import (Sequential, Reshape, Transpose, SpatialConvolution,
                          SpatialMaxPooling, ReLU, Linear, LogSoftMax)


def text_classifier(class_num: int, embedding_dim: int = 200,
                    sequence_length: int = 1000) -> Sequential:
    m = Sequential()
    # (batch, seq, embed) -> (batch, embed, 1, seq) image
    m.add(Transpose([(2, 3)]))
    m.add(Reshape((embedding_dim, 1, sequence_length)))
    m.add(SpatialConvolution(embedding_dim, 128, 5, 1))
    m.add(ReLU())
    m.add(SpatialMaxPooling(5, 1, 5, 1))
    m.add(SpatialConvolution(128, 128, 5, 1))
    m.add(ReLU())
    m.add(SpatialMaxPooling(5, 1, 5, 1))
    m.add(SpatialConvolution(128, 128, 5, 1))
    m.add(ReLU())
    m.add(SpatialMaxPooling(35, 1, 35, 1))
    m.add(Reshape((128,)))
    m.add(Linear(128, 100))
    m.add(Linear(100, class_num))
    m.add(LogSoftMax())
    return m
