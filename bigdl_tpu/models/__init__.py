"""bigdl_tpu.models — the model zoo (reference ``models/`` + ``example/``)."""

from bigdl_tpu.models.lenet import lenet5
from bigdl_tpu.models.autoencoder import autoencoder
from bigdl_tpu.models.vgg import vgg_for_cifar10, vgg16, vgg19
from bigdl_tpu.models.resnet import resnet, model_init, DatasetType, ShortcutType
from bigdl_tpu.models.inception import (inception_v1, inception_v1_no_aux_classifier,
                                        inception_v2, inception_v2_no_aux_classifier,
                                        inception_layer_v1, inception_layer_v2)
from bigdl_tpu.models.alexnet import alexnet, alexnet_owt
from bigdl_tpu.models.rnn import simple_rnn, lstm_lm
from bigdl_tpu.models.textclassifier import text_classifier

__all__ = [
    "lenet5", "autoencoder", "vgg_for_cifar10", "vgg16", "vgg19",
    "resnet", "model_init", "DatasetType", "ShortcutType",
    "inception_v1", "inception_v1_no_aux_classifier",
    "inception_v2", "inception_v2_no_aux_classifier",
    "inception_layer_v1", "inception_layer_v2",
    "alexnet", "alexnet_owt", "simple_rnn", "lstm_lm", "text_classifier",
]
