"""Model-zoo performance harness.

Reference equivalents: ``models/utils/LocalOptimizerPerf.scala`` and
``DistriOptimizerPerf.scala:82-140`` — synthetic-input training-throughput
benchmarks over the zoo, reporting the driver-log ``Throughput is N
records/second`` protocol.

Run::

    python -m bigdl_tpu.models.perf -m alexnet -b 64 -i 20
    python -m bigdl_tpu.models.perf -m resnet50 --partitions 8   # mesh DP
"""

from __future__ import annotations

import argparse

import numpy as np

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import Sample
from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.dataset.transformer import SampleToMiniBatch
from bigdl_tpu.models import driver_utils

# model name -> (builder, input CHW shape, classes)  — the reference
# harness's inputShape table (DistriOptimizerPerf.scala:100-120)
_MODELS = {
    "lenet5": (lambda: _logits_free("lenet"), (28, 28), 10),
    "alexnet": (lambda: _zoo("alexnet_owt"), (3, 224, 224), 1000),
    "vgg16": (lambda: _zoo("vgg16"), (3, 224, 224), 1000),
    "vgg19": (lambda: _zoo("vgg19"), (3, 224, 224), 1000),
    "inception_v1": (lambda: _zoo("inception_v1_no_aux_classifier"),
                     (3, 224, 224), 1000),
    "resnet50": (lambda: _resnet50(), (3, 224, 224), 1000),
    # token LM: (T,) int features, per-timestep targets (beyond-reference)
    "transformer": (lambda: _transformer(), (128,), 1024),
}


def _zoo(name):
    # zoo builders already end in LogSoftMax; only resnet emits raw logits
    import bigdl_tpu.models as models
    return getattr(models, name)()


def _logits_free(name):
    from bigdl_tpu.models.lenet import lenet5
    return lenet5(10)


def _resnet50():
    from bigdl_tpu.models.resnet import resnet, model_init, DatasetType
    m = model_init(resnet(1000, depth=50, dataset=DatasetType.IMAGENET))
    m.add(nn.LogSoftMax())
    return m


def _transformer():
    from bigdl_tpu.models.transformer import transformer_lm
    return transformer_lm(1024, d_model=256, n_head=8, n_layers=4,
                          max_len=128)


def main(argv=None):
    p = argparse.ArgumentParser(description="zoo throughput harness")
    p.add_argument("-m", "--model", choices=sorted(_MODELS), default="lenet5")
    p.add_argument("-b", "--batch-size", type=int, default=64)
    p.add_argument("-i", "--iterations", type=int, default=20)
    p.add_argument("--partitions", type=int, default=1,
                   help=">1: DistriOptimizer over the device mesh")
    p.add_argument("--precision", choices=["fp32", "bf16"], default="fp32",
                   help="fused-step compute precision (fp32 matches the "
                        "reference harness; bf16 is the TPU-first mode "
                        "the headline bench uses)")
    args = p.parse_args(argv)
    driver_utils.init_logging()

    build, shape, classes = _MODELS[args.model]
    model = build()
    rng = np.random.RandomState(0)
    n_records = max(args.batch_size * 2, args.partitions * 2)
    if args.model == "transformer":
        records = [Sample(rng.randint(1, classes + 1, shape)
                          .astype(np.float32),
                          rng.randint(1, classes + 1, shape)
                          .astype(np.float32))
                   for _ in range(n_records)]
        criterion = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                                size_average=True)
    else:
        records = [Sample(rng.uniform(-1, 1, size=shape).astype(np.float32),
                          np.float32(rng.randint(1, classes + 1)))
                   for _ in range(n_records)]
        criterion = nn.ClassNLLCriterion()
    ds = DataSet.array(records, args.partitions).transform(
        SampleToMiniBatch(args.batch_size, max(1, args.partitions)))

    opt = optim.Optimizer.create(model, ds, criterion)
    opt.set_optim_method(optim.SGD(learning_rate=0.01, momentum=0.9))
    if args.precision == "bf16":
        opt.set_precision("bf16")
    # warm-up run absorbs the jit compile; the timed run is steady-state
    # (the reference harness likewise reports per-iteration throughput,
    # DistriOptimizerPerf.scala:130-140)
    import time
    opt.set_end_when(optim.max_iteration(2))
    opt.optimize()
    t0 = time.time()
    opt.set_end_when(optim.max_iteration(args.iterations + 2))
    opt.optimize()
    dt = time.time() - t0
    print(f"[{args.model}] steady-state throughput "
          f"{args.batch_size * args.iterations / dt:.1f} records/second "
          f"({dt / args.iterations * 1e3:.1f} ms/iteration, batch "
          f"{args.batch_size})")
    return opt


if __name__ == "__main__":
    main()
