"""Model-zoo performance harness.

Reference equivalents: ``models/utils/LocalOptimizerPerf.scala`` and
``DistriOptimizerPerf.scala:82-140`` — synthetic-input training-throughput
benchmarks over the zoo, reporting the driver-log ``Throughput is N
records/second`` protocol.

Run::

    python -m bigdl_tpu.models.perf -m alexnet -b 64 -i 20
    python -m bigdl_tpu.models.perf -m resnet50 --partitions 8   # mesh DP
    python -m bigdl_tpu.models.perf -m resnet50 --per-layer      # attribution
    python -m bigdl_tpu.models.perf -m resnet50 --layout nchw    # layout A/B

``--per-layer`` prints the layer-by-layer forward time / FLOPs / MFU
attribution (:func:`per_layer_report`) instead of the training loop — the
tool that makes a layout or fusion change attributable layer by layer.
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import Sample
from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.dataset.transformer import SampleToMiniBatch
from bigdl_tpu.models import driver_utils

# model name -> (builder(layout), input CHW shape, classes)  — the reference
# harness's inputShape table (DistriOptimizerPerf.scala:100-120)
_MODELS = {
    "lenet5": (lambda layout: _logits_free("lenet", layout), (28, 28), 10),
    "alexnet": (lambda layout: _zoo("alexnet_owt", layout), (3, 224, 224), 1000),
    "vgg16": (lambda layout: _zoo("vgg16", layout), (3, 224, 224), 1000),
    "vgg19": (lambda layout: _zoo("vgg19", layout), (3, 224, 224), 1000),
    "inception_v1": (lambda layout: _zoo("inception_v1_no_aux_classifier",
                                         layout), (3, 224, 224), 1000),
    "resnet50": (lambda layout: _resnet50(layout), (3, 224, 224), 1000),
    # token LM: (T,) int features, per-timestep targets (beyond-reference)
    "transformer": (lambda layout: _transformer(), (128,), 1024),
}


def _zoo(name, layout="NHWC"):
    # zoo builders already end in LogSoftMax; only resnet emits raw logits
    import bigdl_tpu.models as models
    return getattr(models, name)(layout=layout)


def _logits_free(name, layout="NHWC"):
    from bigdl_tpu.models.lenet import lenet5
    return lenet5(10, layout=layout)


def _resnet50(layout="NHWC"):
    from bigdl_tpu.models.resnet import resnet, model_init, DatasetType
    m = model_init(resnet(1000, depth=50, dataset=DatasetType.IMAGENET,
                          layout=layout))
    m.add(nn.LogSoftMax())
    return m


def _transformer():
    from bigdl_tpu.models.transformer import transformer_lm
    return transformer_lm(1024, d_model=256, n_head=8, n_layers=4,
                          max_len=128)


# ---------------------------------------------------------------------------
# per-layer time / FLOPs / MFU attribution
# ---------------------------------------------------------------------------

def _layer_flops(m, in_shape, out_shape) -> float:
    """Matmul FLOPs of one leaf's forward (0 for memory-bound layers)."""
    if isinstance(m, nn.SpatialFullConvolution):
        # every input pixel scatters a kh*kw patch into every output plane
        in_pix = int(np.prod(in_shape)) // m.n_input_plane
        return 2.0 * m.kh * m.kw * m.n_input_plane * m.n_output_plane * in_pix
    if isinstance(m, nn.SpatialConvolution):
        if m.format == "NHWC":
            out_pix = int(np.prod(out_shape[:-1]))
        else:
            out_pix = int(np.prod(out_shape)) // m.n_output_plane
        taps = m.kernel_h * m.kernel_w * (m.n_input_plane // m.n_group)
        return 2.0 * taps * m.n_output_plane * out_pix
    if isinstance(m, nn.SpatialDilatedConvolution):
        out_pix = (int(np.prod(out_shape[:-1])) if m.format == "NHWC"
                   else int(np.prod(out_shape)) // m.n_output_plane)
        return 2.0 * m.kh * m.kw * m.n_input_plane * m.n_output_plane * out_pix
    if isinstance(m, nn.Linear):
        rows = int(np.prod(out_shape)) // m.output_size
        return 2.0 * m.input_size * m.output_size * rows
    return 0.0


def _walk_forward(m, x, rows):
    """Execute ``m`` child by child (each leaf's own jitted, device-synced
    forward) collecting (module, input shape, output) rows.  Containers the
    walk understands are expanded; anything else times as one leaf."""
    import jax.numpy as jnp
    from bigdl_tpu.nn.structural import _axis

    if isinstance(m, (nn.Sequential, nn.Remat)):
        for c in m.children:
            x = _walk_forward(c, x, rows)
        return x
    if isinstance(m, nn.Concat):
        outs = [_walk_forward(c, x, rows) for c in m.children]
        return jnp.concatenate(outs, axis=_axis(m.dimension, outs[0].ndim))
    if isinstance(m, nn.ConcatTable):
        return [_walk_forward(c, x, rows) for c in m.children]
    in_shape = getattr(x, "shape", None)
    m.forward_time = 0
    out = m.forward(x)
    rows.append((m, in_shape, out))
    return out


def per_layer_report(model, input, peak_tflops=None, file=None):
    """Layer-by-layer forward attribution: wall time, share of total, FLOPs
    and achieved TFLOP/s (plus MFU when ``peak_tflops`` names the chip's
    peak) for every leaf module, in execution order.

    Per-layer dispatch defeats cross-layer XLA fusion, so the TOTAL here
    exceeds the fused step the trainers run — read the numbers as relative
    attribution (which layers move when a layout/fusion change lands), not
    absolute throughput.  Returns the list of per-layer record dicts.
    """
    file = file or sys.stderr
    model._ensure_init()
    # two passes: the first absorbs each leaf's jit compile
    _walk_forward(model, input, [])
    rows = []
    _walk_forward(model, input, rows)
    total_ns = sum(m.forward_time for m, _, _ in rows) or 1
    records = []
    print(f"{'layer':<34}{'type':<28}{'out_shape':<20}"
          f"{'ms':>8}{'%time':>7}{'GFLOP':>9}{'TFLOP/s':>9}"
          + (f"{'MFU%':>7}" if peak_tflops else ""), file=file)
    for m, in_shape, out in rows:
        out_shape = (out[0].shape if isinstance(out, (list, tuple))
                     else out.shape)
        ms = m.forward_time / 1e6
        flops = _layer_flops(m, in_shape, out_shape)
        tflops = flops / (m.forward_time or 1) / 1e3
        rec = {"name": m.name, "type": type(m).__name__,
               "out_shape": tuple(out_shape), "ms": round(ms, 3),
               "time_share": round(m.forward_time / total_ns, 4),
               "gflop": round(flops / 1e9, 3),
               "tflops": round(tflops, 3)}
        line = (f"{m.name:<34}{type(m).__name__:<28}"
                f"{str(tuple(out_shape)):<20}{ms:>8.2f}"
                f"{100 * m.forward_time / total_ns:>6.1f}%"
                f"{flops / 1e9:>9.2f}{tflops:>9.2f}")
        if peak_tflops:
            rec["mfu"] = round(tflops / peak_tflops, 4)
            line += f"{100 * tflops / peak_tflops:>6.1f}%"
        print(line, file=file)
        records.append(rec)
    tot_gflop = sum(r["gflop"] for r in records)
    tot_tflops = tot_gflop * 1e6 / total_ns     # GFLOP / (ns -> ms) = TFLOP/s
    line = (f"{'TOTAL':<34}{'':<28}{'':<20}{total_ns / 1e6:>8.2f}"
            f"{100.0:>6.1f}%{tot_gflop:>9.2f}{tot_tflops:>9.2f}")
    if peak_tflops:
        line += f"{100 * tot_tflops / peak_tflops:>6.1f}%"
    print(line, file=file)
    return records


def main(argv=None):
    p = argparse.ArgumentParser(description="zoo throughput harness")
    p.add_argument("-m", "--model", choices=sorted(_MODELS), default="lenet5")
    p.add_argument("-b", "--batch-size", type=int, default=64)
    p.add_argument("-i", "--iterations", type=int, default=20)
    p.add_argument("--partitions", type=int, default=1,
                   help=">1: DistriOptimizer over the device mesh")
    p.add_argument("--precision", choices=["fp32", "bf16"], default="fp32",
                   help="fused-step compute precision (fp32 matches the "
                        "reference harness; bf16 is the TPU-first mode "
                        "the headline bench uses)")
    p.add_argument("--layout", choices=["nhwc", "nchw"], default="nhwc",
                   help="convnet compute layout: nhwc = channels-last "
                        "trunk (TPU-native default), nchw = the classic "
                        "Torch layout, for before/after A-B runs")
    p.add_argument("--per-layer", action="store_true",
                   help="print the layer-by-layer forward time/FLOPs/MFU "
                        "attribution instead of running the training loop")
    p.add_argument("--peak-tflops", type=float, default=None,
                   help="chip peak for the per-layer MFU column (e.g. 197 "
                        "for one v5e chip at bf16)")
    args = p.parse_args(argv)
    driver_utils.init_logging()

    build, shape, classes = _MODELS[args.model]
    model = build(args.layout.upper())
    rng = np.random.RandomState(0)

    if args.per_layer:
        import jax.numpy as jnp
        if args.model == "transformer":   # 1-based token ids, not pixels
            x = jnp.asarray(rng.randint(1, classes + 1,
                                        (args.batch_size,) + shape)
                            .astype(np.float32))
        else:
            x = jnp.asarray(rng.uniform(-1, 1, (args.batch_size,) + shape)
                            .astype(np.float32))
        print(f"[{args.model}] per-layer forward attribution "
              f"(batch {args.batch_size}, layout {args.layout})",
              file=sys.stderr)
        return per_layer_report(model, x, peak_tflops=args.peak_tflops)
    n_records = max(args.batch_size * 2, args.partitions * 2)
    if args.model == "transformer":
        records = [Sample(rng.randint(1, classes + 1, shape)
                          .astype(np.float32),
                          rng.randint(1, classes + 1, shape)
                          .astype(np.float32))
                   for _ in range(n_records)]
        criterion = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                                size_average=True)
    else:
        records = [Sample(rng.uniform(-1, 1, size=shape).astype(np.float32),
                          np.float32(rng.randint(1, classes + 1)))
                   for _ in range(n_records)]
        criterion = nn.ClassNLLCriterion()
    ds = DataSet.array(records, args.partitions).transform(
        SampleToMiniBatch(args.batch_size, max(1, args.partitions)))

    opt = optim.Optimizer.create(model, ds, criterion)
    opt.set_optim_method(optim.SGD(learning_rate=0.01, momentum=0.9))
    if args.precision == "bf16":
        opt.set_precision("bf16")
    # warm-up run absorbs the jit compile; the timed run is steady-state
    # (the reference harness likewise reports per-iteration throughput,
    # DistriOptimizerPerf.scala:130-140)
    import time
    opt.set_end_when(optim.max_iteration(2))
    opt.optimize()
    t0 = time.time()
    opt.set_end_when(optim.max_iteration(args.iterations + 2))
    opt.optimize()
    dt = time.time() - t0
    print(f"[{args.model}] steady-state throughput "
          f"{args.batch_size * args.iterations / dt:.1f} records/second "
          f"({dt / args.iterations * 1e3:.1f} ms/iteration, batch "
          f"{args.batch_size})")
    return opt


if __name__ == "__main__":
    main()
