"""Decoder-only transformer language model.

No reference equivalent (the reference has no attention op at all,
SURVEY §5.7) — this is the flagship long-context model family: causal
MultiHeadAttention blocks with pre-norm residuals, trainable on a
``("data", "seq")`` mesh where attention runs as a ppermute ring
(``bigdl_tpu/parallel/ring_attention.py``) and optionally with
Megatron-split MLPs (``parallel/tensor_parallel.py``).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.module import Container, Module, _child_rng


class PositionOutOfRange(ValueError):
    """A position past the sinusoidal table's capacity.  Structured —
    names the offending position and the limit — because the silent
    alternatives are worse: a short slice broadcasts into a confusing
    shape error and ``dynamic_slice`` silently CLAMPS, feeding wrong
    position signal with no symptom at all."""

    def __init__(self, position: int, max_len: int):
        self.position = int(position)
        self.max_len = int(max_len)
        super().__init__(
            f"position {self.position} is out of range for a "
            f"PositionalEncoding table of max_len {self.max_len} — build "
            f"the model with max_len > {self.position} or truncate the "
            "sequence")


class PositionalEncoding(Module):
    """Sinusoidal position signal added to (B, T, D) embeddings.

    Position-dependent, so under sequence parallelism each time shard must
    offset into the table by its chunk start: the trainer wires
    ``set_sequence_parallel`` (duck-typed, like MultiHeadAttention's ring
    path) and the offset engages only while the seq axis is bound.

    ``apply(..., offset=k)`` reads table rows ``k .. k+T`` instead of
    ``0 .. T`` — the decode path hands a sequence's resume position here.
    Out-of-range static positions (``T > max_len``, or ``offset + T >
    max_len``) raise :class:`PositionOutOfRange`; traced offsets (the
    sequence-parallel shard index) stay the caller's contract, as
    before."""

    def __init__(self, d_model: int, max_len: int = 4096, name=None):
        super().__init__(name)
        pos = np.arange(max_len)[:, None]
        div = np.exp(np.arange(0, d_model, 2) * (-math.log(10000.0) / d_model))
        pe = np.zeros((max_len, d_model), np.float32)
        pe[:, 0::2] = np.sin(pos * div)
        pe[:, 1::2] = np.cos(pos * div[: d_model // 2])
        self.pe = jnp.asarray(pe)
        self.sequence_parallel = None

    @property
    def max_seq_len(self) -> int:
        """Table capacity — the sp trainer validates global T against this
        (dynamic_slice would silently clamp out-of-range shard offsets)."""
        return int(self.pe.shape[0])

    def set_sequence_parallel(self, axis_name) -> "PositionalEncoding":
        self.sequence_parallel = axis_name
        self._jit_apply = None
        return self

    def rows(self, positions) -> jnp.ndarray:
        """Table rows for explicit positions — the decode step's
        per-sequence position lookup (each decode slot sits at its own
        offset).  Concrete (host) positions are range-checked; traced
        positions were validated against :attr:`max_seq_len` by the
        caller's admission path (``jnp.take`` would silently clip)."""
        if isinstance(positions, (int, np.integer, list, tuple,
                                  np.ndarray)):
            pos = np.asarray(positions)
            if pos.size and int(pos.max()) >= self.max_seq_len:
                raise PositionOutOfRange(int(pos.max()), self.max_seq_len)
        return jnp.take(self.pe, jnp.asarray(positions), axis=0)

    def apply(self, params, input, state, training=False, rng=None,
              offset: int = 0):
        from bigdl_tpu.nn.attention import _axis_bound
        t = input.shape[1]
        if self.sequence_parallel and _axis_bound(self.sequence_parallel):
            start = jax.lax.axis_index(self.sequence_parallel) * t
            if offset:
                start = start + offset
            pe = jax.lax.dynamic_slice_in_dim(self.pe, start, t, 0)
        else:
            if offset + t > self.max_seq_len:
                raise PositionOutOfRange(offset + t - 1, self.max_seq_len)
            pe = self.pe[offset:offset + t]
        return input + pe[None].astype(input.dtype), state


class LayerNorm(Module):
    """Feature-axis layer normalization (pre-norm transformer blocks;
    time-pointwise, so it composes with sequence parallelism)."""

    def __init__(self, d_model: int, eps: float = 1e-5, name=None):
        super().__init__(name)
        self.d_model = d_model
        self.eps = eps

    def _init_params(self, rng):
        return {"weight": jnp.ones((self.d_model,)),
                "bias": jnp.zeros((self.d_model,))}

    def apply(self, params, input, state, training=False, rng=None):
        mean = jnp.mean(input, axis=-1, keepdims=True)
        var = jnp.var(input, axis=-1, keepdims=True)
        out = (input - mean) * jax.lax.rsqrt(var + self.eps)
        return out * params["weight"] + params["bias"], state


class _Residual(Container):
    """x + inner(norm(x)) — pre-norm residual.

    A real Container (children = [norm, inner]) so child param views stay
    adopted: sublayer ``.forward()``, ``get_parameters_table()``, and the
    TrainSummary "Parameters" histogram walk all see the trained weights,
    and tp_specs/sequence-parallel wiring recurse naturally."""

    def __init__(self, d_model: int, inner: Module, name=None):
        super().__init__(name)
        self.add(LayerNorm(d_model)).add(inner)

    def apply(self, params, input, state, training=False, rng=None):
        norm, inner = self.children
        h, _ = norm.apply(params[0], input, state[0], training=training)
        h, new_inner = inner.apply(params[1], h, state[1],
                                   training=training,
                                   rng=_child_rng(rng, 1))
        return input + h, [state[0], new_inner]


def transformer_block(d_model: int, n_head: int, ff_mult: int = 4,
                      tp: bool = False,
                      moe_experts: int = 0,
                      moe_capacity_factor: float = 1.25,
                      moe_top_k: int = 1) -> nn.Sequential:
    """One pre-norm decoder block: causal MHA + MLP, both residual.

    ``tp=True`` tags the MLP pair column/row for the Megatron split
    (``parallel.tp_specs`` then shards it over the ``model`` axis; the
    MHA head split applies automatically).  ``moe_experts=E`` replaces the
    dense MLP with a Switch :class:`~bigdl_tpu.nn.MixtureOfExperts` of E
    expert MLPs (expert-parallel over an ``expert`` axis via
    ``parallel.expert_parallel``); ``moe_capacity_factor`` /
    ``moe_top_k`` pass through (capacity_factor >= E/top_k makes routing
    drop-free and thus microbatch-invariant — see the MoE class
    docstring)."""
    from bigdl_tpu.parallel.tensor_parallel import (column_parallel,
                                                    row_parallel)
    if moe_experts:
        if tp:
            raise ValueError("pick one of tp / moe_experts per block")
        expert = (nn.Sequential()
                  .add(nn.Linear(d_model, ff_mult * d_model))
                  .add(nn.ReLU())
                  .add(nn.Linear(ff_mult * d_model, d_model)))
        ffn = nn.MixtureOfExperts(d_model, expert, moe_experts,
                                  capacity_factor=moe_capacity_factor,
                                  top_k=moe_top_k)
    else:
        up = nn.Linear(d_model, ff_mult * d_model)
        down = nn.Linear(ff_mult * d_model, d_model)
        if tp:
            column_parallel(up)
            row_parallel(down)
        ffn = nn.Sequential().add(up).add(nn.ReLU()).add(down)
    return (nn.Sequential()
            .add(_Residual(d_model,
                           nn.MultiHeadAttention(d_model, n_head,
                                                 causal=True)))
            .add(_Residual(d_model, ffn)))


def _default_remat(remat):
    """Resolve a builder's ``remat`` argument against the
    ``bigdl.remat.policy`` config preset: an explicit argument wins; with
    the default (``False``) the preset applies — ``"nothing"`` (save
    nothing per block), ``"dots"``, ``"save_attn"`` (:class:`nn.Remat`'s
    vocabulary, where a typo fails at construction), ``None``/``"off"``
    keeps remat off.  This is what lets the MFU bench A/B remat policies
    against collective overlap without threading a new argument through
    every model builder."""
    if remat is not False:
        return remat
    from bigdl_tpu.utils import config
    v = config.get_property("bigdl.remat.policy", None)
    if v in (None, False, ""):
        return False
    v = str(v).lower()
    if v in ("none", "off", "false"):
        return False
    if v in ("nothing", "true"):
        return True
    return v


def transformer_lm(vocab_size: int, d_model: int = 128, n_head: int = 4,
                   n_layers: int = 2, max_len: int = 4096,
                   tp: bool = False, moe_experts: int = 0,
                   moe_top_k: int = 1, remat=False) -> nn.Sequential:
    """Token ids (B, T), 1-based -> log-probs (B, T, vocab).

    ``moe_experts=E`` makes every block's FFN a MoE (train on a
    ``("data", "expert")`` mesh for expert parallelism — the driver's
    ``--expert-parallel``); ``moe_top_k`` selects the routing: 1 = Switch,
    2 = the GShard configuration (driver ``--moe-top-k``).  ``tp=True``
    tags Megatron splits (train on a ``("data", "model")`` mesh —
    ``--tensor-parallel``).  ``remat`` wraps every decoder block in
    :class:`~bigdl_tpu.nn.Remat` activation checkpointing — ``True`` saves
    nothing per block, ``"dots"`` saves matmul outputs, ``"save_attn"``
    saves only the tagged attention context (driver ``--remat``);
    identical numerics, O(layers) less activation memory.  When the
    argument is left at its default, the ``bigdl.remat.policy`` config
    preset applies (see :func:`_default_remat`)."""
    remat = _default_remat(remat)
    m = (nn.Sequential()
         .add(nn.LookupTable(vocab_size, d_model))
         .add(PositionalEncoding(d_model, max_len)))
    for _ in range(n_layers):
        block = transformer_block(d_model, n_head, tp=tp,
                                  moe_experts=moe_experts,
                                  moe_top_k=moe_top_k)
        if remat:
            block = nn.Remat(block,
                             policy=None if remat is True else remat)
        m.add(block)
    m.add(LayerNorm(d_model))
    m.add(nn.Linear(d_model, vocab_size))
    m.add(nn.LogSoftMax())
    return m


def transformer_lm_pipeline(vocab_size: int, d_model: int = 128,
                            n_head: int = 4, n_layers: int = 2,
                            max_len: int = 4096, moe_experts: int = 0,
                            moe_top_k: int = 1, remat=False,
                            tp: bool = False):
    """``(embed, blocks, head)`` for
    :class:`~bigdl_tpu.parallel.pipeline.PipelineOptimizer`: the embedding
    and LM head run replicated, the ``n_layers`` homogeneous decoder
    blocks pipeline over a ``stage`` mesh axis (one block per stage
    device — the driver's ``--pipeline``).  ``moe_experts=E`` gives every
    block a Switch-MoE FFN; the pipeline trainer folds the collected
    ``aux_loss`` into its objective (``pipeline_apply(return_aux=True)``).
    ``tp=True`` Megatron-tags each block for the 3-D
    ``('data','stage','model')`` composition (driver
    ``--pipeline --tensor-parallel``)."""
    remat = _default_remat(remat)
    embed = (nn.Sequential()
             .add(nn.LookupTable(vocab_size, d_model))
             .add(PositionalEncoding(d_model, max_len)))
    blocks = [transformer_block(d_model, n_head, moe_experts=moe_experts,
                                moe_top_k=moe_top_k, tp=tp)
              for _ in range(n_layers)]
    if remat:
        policy = None if remat is True else remat
        blocks = [nn.Remat(b, policy=policy) for b in blocks]
    head = (nn.Sequential()
            .add(LayerNorm(d_model))
            .add(nn.Linear(d_model, vocab_size))
            .add(nn.LogSoftMax()))
    return embed, blocks, head
