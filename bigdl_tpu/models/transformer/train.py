"""Transformer language-model Train driver (beyond-reference family).

Run::

    python -m bigdl_tpu.models.transformer.train --synthetic 256
    python -m bigdl_tpu.models.transformer.train -f corpus.txt --seq-len 128
    python -m bigdl_tpu.models.transformer.train --synthetic 256 \
        --partitions 4 --seq-parallel 2       # dp x sp mesh, ring attention
    python -m bigdl_tpu.models.transformer.train --synthetic 256 \
        --partitions 2 --tensor-parallel 4    # dp x tp GSPMD Megatron
    python -m bigdl_tpu.models.transformer.train --synthetic 256 \
        --moe-experts 8 --partitions 2 --expert-parallel 4   # dp x ep MoE
    python -m bigdl_tpu.models.transformer.train --synthetic 256 \
        --pipeline 4                          # GPipe over a stage mesh

Every parallelism mode trains through the public Optimizer API:
``--seq-parallel N`` shards time over a ``("data", "seq")`` mesh (ring
attention); ``--tensor-parallel N`` Megatron-splits MLPs/heads over
``("data", "model")`` (XLA GSPMD inserts the collectives);
``--expert-parallel N`` dispatches MoE FFNs with all_to_all over
``("data", "expert")`` and folds the load-balancing aux loss into the
objective; ``--pipeline S`` runs S decoder blocks as a GPipe scan over a
``stage`` mesh (optionally x dp with ``--partitions``).
"""

import numpy as np

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import Sample
from bigdl_tpu.dataset.text import Dictionary, SentenceTokenizer
from bigdl_tpu.models import driver_utils
from bigdl_tpu.models.transformer import (transformer_lm,
                                          transformer_lm_pipeline)

VOCAB = 64


def _synthetic(n: int, seq_len: int, seed: int = 1) -> list:
    """Learnable next-token structure: token_{t+1} = f(token_t) pattern."""
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        start = rng.randint(1, VOCAB + 1)
        step = rng.randint(1, 5)
        toks = (np.arange(seq_len + 1) * step + start) % VOCAB + 1
        out.append(Sample(toks[:-1].astype(np.float32),
                          toks[1:].astype(np.float32)))
    return out


def _load_corpus(path: str, seq_len: int):
    with open(path, errors="ignore") as f:
        words = next(SentenceTokenizer()(iter([f.read()])), [])
    d = Dictionary([words], vocab_size=VOCAB - 1)
    idx = np.asarray([d.get_index(w) + 1 for w in words], np.float32)
    out = []
    for i in range(0, len(idx) - seq_len - 1, seq_len):
        out.append(Sample(idx[i:i + seq_len], idx[i + 1:i + seq_len + 1]))
    return out


def _partial_mesh(Engine, shape, names):
    """Mesh over the first prod(shape) devices — a parallelism request
    smaller than the machine should run on a sub-mesh, not error."""
    import numpy as _np
    needed = int(_np.prod(shape))
    return Engine.create_mesh(shape, names,
                              devices=Engine.devices()[:needed])


def main(argv=None):
    p = driver_utils.base_parser("Train a decoder-only transformer LM")
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--seq-parallel", type=int, default=0,
                   help="N>1: shard time over a ('data','seq') mesh")
    p.add_argument("--tensor-parallel", type=int, default=0,
                   help="N>1: Megatron-split over a ('data','model') mesh")
    p.add_argument("--moe-experts", type=int, default=0,
                   help="E>0: Switch-MoE FFNs with E experts per block")
    p.add_argument("--expert-parallel", type=int, default=0,
                   help="N>1: all_to_all MoE dispatch over a "
                        "('data','expert') mesh (needs --moe-experts)")
    p.add_argument("--moe-top-k", type=int, default=1,
                   help="experts per token: 1 = Switch (default), "
                        "2 = the GShard configuration (needs --moe-experts)")
    p.add_argument("--pipeline", type=int, default=0,
                   help="S>1: GPipe the S decoder blocks over a 'stage' "
                        "mesh axis (sets --layers S)")
    p.add_argument("--n-micro", type=int, default=4,
                   help="GPipe microbatches per replica (with --pipeline)")
    p.add_argument("--remat", choices=["full", "dots", "save_attn"],
                   default=None,
                   help="activation-checkpoint every decoder block: 'full' "
                        "saves nothing per block, 'dots' keeps matmul "
                        "outputs, 'save_attn' keeps only the attention "
                        "context (trade FLOPs for HBM — how the >=1B "
                        "single-chip point fits)")
    args = p.parse_args(argv)
    driver_utils.init_logging()
    batch = args.batch_size or 32
    modes = [m for m, on in (("--seq-parallel", args.seq_parallel > 1),
                             ("--tensor-parallel", args.tensor_parallel > 1),
                             ("--expert-parallel", args.expert_parallel > 1),
                             ("--pipeline", args.pipeline > 1)) if on]
    if len(modes) > 1 and set(modes) != {"--pipeline", "--tensor-parallel"}:
        raise SystemExit(f"pick one parallelism mode, got {modes} "
                         "(--pipeline composes with --tensor-parallel "
                         "only)")
    if args.expert_parallel > 1 and not args.moe_experts:
        raise SystemExit("--expert-parallel needs --moe-experts")
    if args.moe_top_k != 1 and not args.moe_experts:
        raise SystemExit("--moe-top-k needs --moe-experts")

    remat = {"full": True, None: False}.get(args.remat, args.remat)

    if args.synthetic:
        records = _synthetic(args.synthetic, args.seq_len)
    else:
        records = _load_corpus(args.folder, args.seq_len)

    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                       size_average=True)
    lr = args.learning_rate or 1e-3
    from bigdl_tpu.dataset import SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import ShardedDataSet
    from bigdl_tpu.engine import Engine
    from bigdl_tpu.parallel import DistriOptimizer, PipelineOptimizer
    dp = max(1, args.partitions or 1)

    if args.pipeline > 1:
        # GPipe: S homogeneous decoder blocks over a stage mesh (x dp)
        if args.model or args.state:
            raise SystemExit("--pipeline does not support --model/--state "
                             "snapshot resume yet")
        tp_n = args.tensor_parallel if args.tensor_parallel > 1 else 0
        if tp_n and args.moe_experts:
            raise SystemExit("pick one of --tensor-parallel / "
                             "--moe-experts per block")
        embed, blocks, head = transformer_lm_pipeline(
            VOCAB, args.d_model, args.heads, n_layers=args.pipeline,
            max_len=max(4096, args.seq_len), moe_experts=args.moe_experts,
            moe_top_k=args.moe_top_k, remat=remat, tp=bool(tp_n))
        shape = (dp, args.pipeline) if dp > 1 else (args.pipeline,)
        names = ("data", "stage") if dp > 1 else ("stage",)
        if tp_n:
            # 3-D composition: ('data','stage','model') (or 2-D without dp)
            shape = shape + (tp_n,)
            names = names + ("model",)
        mesh = _partial_mesh(Engine, shape, names)
        ds = driver_utils.make_dataset(records, args, batch)
        opt = PipelineOptimizer(blocks, ds, crit, mesh=mesh,
                                n_micro=args.n_micro, embed=embed,
                                head=head)
        opt.set_optim_method(optim.Adam(learning_rate=lr))
        model = opt.model
    else:
        model, method = driver_utils.load_snapshots(
            args, lambda: transformer_lm(VOCAB, args.d_model, args.heads,
                                         args.layers,
                                         max_len=max(4096, args.seq_len),
                                         tp=args.tensor_parallel > 1,
                                         moe_experts=args.moe_experts,
                                         moe_top_k=args.moe_top_k,
                                         remat=remat),
            lambda: optim.Adam(learning_rate=lr))
        if args.seq_parallel > 1:
            mesh = _partial_mesh(Engine, (dp, args.seq_parallel),
                                 ("data", "seq"))
        elif args.tensor_parallel > 1:
            mesh = _partial_mesh(Engine, (dp, args.tensor_parallel),
                                 ("data", "model"))
        elif args.expert_parallel > 1:
            mesh = _partial_mesh(Engine, (dp, args.expert_parallel),
                                 ("data", "expert"))
        else:
            mesh = None
        if mesh is not None:
            ds = ShardedDataSet(records, dp).transform(
                SampleToMiniBatch(batch, dp))
            opt = DistriOptimizer(model, ds, crit, mesh=mesh)
        else:
            ds = driver_utils.make_dataset(records, args, batch)
            opt = optim.Optimizer.create(model, ds, crit)
        opt.set_optim_method(method)
    driver_utils.configure(opt, args, default_epochs=10,
                           app_name="transformer")
    trained = opt.optimize()

    # report next-token accuracy on the training set
    x = np.stack([s.feature for s in records[:64]])
    y = np.stack([s.label for s in records[:64]])
    pred = np.asarray(trained.forward(x)).argmax(-1) + 1
    acc = float((pred == y).mean())
    print(f"Final next-token accuracy: {acc:.4f}")
    return trained


if __name__ == "__main__":
    main()
