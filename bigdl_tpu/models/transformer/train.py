"""Transformer language-model Train driver (beyond-reference family).

Run::

    python -m bigdl_tpu.models.transformer.train --synthetic 256
    python -m bigdl_tpu.models.transformer.train -f corpus.txt --seq-len 128
    python -m bigdl_tpu.models.transformer.train --synthetic 256 \
        --partitions 4 --seq-parallel 2       # dp x sp mesh, ring attention

With ``--seq-parallel N`` the mesh is ``(partitions, N)`` over
``("data", "seq")``: attention runs as a ppermute ring and the time
dimension is sharded — the long-context training path.
"""

import numpy as np

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import Sample
from bigdl_tpu.dataset.text import Dictionary, SentenceTokenizer
from bigdl_tpu.models import driver_utils
from bigdl_tpu.models.transformer import transformer_lm

VOCAB = 64


def _synthetic(n: int, seq_len: int, seed: int = 1) -> list:
    """Learnable next-token structure: token_{t+1} = f(token_t) pattern."""
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        start = rng.randint(1, VOCAB + 1)
        step = rng.randint(1, 5)
        toks = (np.arange(seq_len + 1) * step + start) % VOCAB + 1
        out.append(Sample(toks[:-1].astype(np.float32),
                          toks[1:].astype(np.float32)))
    return out


def _load_corpus(path: str, seq_len: int):
    with open(path, errors="ignore") as f:
        words = next(SentenceTokenizer()(iter([f.read()])), [])
    d = Dictionary([words], vocab_size=VOCAB - 1)
    idx = np.asarray([d.get_index(w) + 1 for w in words], np.float32)
    out = []
    for i in range(0, len(idx) - seq_len - 1, seq_len):
        out.append(Sample(idx[i:i + seq_len], idx[i + 1:i + seq_len + 1]))
    return out


def main(argv=None):
    p = driver_utils.base_parser("Train a decoder-only transformer LM")
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--seq-parallel", type=int, default=0,
                   help="N>1: shard time over a ('data','seq') mesh")
    args = p.parse_args(argv)
    driver_utils.init_logging()
    batch = args.batch_size or 32

    if args.synthetic:
        records = _synthetic(args.synthetic, args.seq_len)
    else:
        records = _load_corpus(args.folder, args.seq_len)

    model, method = driver_utils.load_snapshots(
        args, lambda: transformer_lm(VOCAB, args.d_model, args.heads,
                                     args.layers,
                                     max_len=max(4096, args.seq_len)),
        lambda: optim.Adam(learning_rate=args.learning_rate or 1e-3))

    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                       size_average=True)
    if args.seq_parallel > 1:
        from bigdl_tpu.dataset import SampleToMiniBatch
        from bigdl_tpu.dataset.dataset import ShardedDataSet
        from bigdl_tpu.engine import Engine
        from bigdl_tpu.parallel import DistriOptimizer
        dp = max(1, args.partitions or 1)
        mesh = Engine.create_mesh((dp, args.seq_parallel), ("data", "seq"))
        ds = ShardedDataSet(records, dp).transform(
            SampleToMiniBatch(batch, dp))
        opt = DistriOptimizer(model, ds, crit, mesh=mesh)
    else:
        ds = driver_utils.make_dataset(records, args, batch)
        opt = optim.Optimizer.create(model, ds, crit)
    opt.set_optim_method(method)
    driver_utils.configure(opt, args, default_epochs=10,
                           app_name="transformer")
    trained = opt.optimize()

    # report next-token accuracy on the training set
    x = np.stack([s.feature for s in records[:64]])
    y = np.stack([s.label for s in records[:64]])
    pred = np.asarray(trained.forward(x)).argmax(-1) + 1
    acc = float((pred == y).mean())
    print(f"Final next-token accuracy: {acc:.4f}")
    return trained


if __name__ == "__main__":
    main()
