"""LeNet-5/MNIST Train driver — BASELINE config #1.

Reference equivalent: ``models/lenet/Train.scala:35`` — load MNIST idx
files, GreyImgNormalizer, SampleToMiniBatch, SGD, validate Top1 per epoch.

Run::

    python -m bigdl_tpu.models.lenet.train -f <mnist-folder> [-b 128]
    python -m bigdl_tpu.models.lenet.train --synthetic 2048   # no data needed
"""

import numpy as np

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import Sample
from bigdl_tpu.dataset.datasets import (MNIST_TRAIN_MEAN, MNIST_TRAIN_STD,
                                        load_mnist)
from bigdl_tpu.models import driver_utils
from bigdl_tpu.models.lenet import lenet5


def _to_samples(images) -> list:
    return [Sample((img.data.astype(np.float32) - MNIST_TRAIN_MEAN) /
                   MNIST_TRAIN_STD, np.float32(img.label))
            for img in images]


def _synthetic(n: int, seed: int = 1) -> list:
    rng = np.random.RandomState(seed)
    out = []
    for lab in rng.randint(0, 10, size=n):
        img = rng.normal(0, 0.3, size=(28, 28)).astype(np.float32)
        r, c = divmod(int(lab) % 4, 2)
        img[r * 14:(r + 1) * 14, c * 14:(c + 1) * 14] += 1.0 + 0.1 * lab
        out.append(Sample(img, np.float32(lab + 1)))
    return out


def main(argv=None):
    p = driver_utils.base_parser("Train LeNet-5 on MNIST")
    args = p.parse_args(argv)
    driver_utils.init_logging()
    batch = args.batch_size or 128

    if args.synthetic:
        train, val = _synthetic(args.synthetic), _synthetic(
            max(args.synthetic // 4, 10), seed=2)
    else:
        train = _to_samples(load_mnist(args.folder, "train"))
        val = _to_samples(load_mnist(args.folder, "test"))

    model, method = driver_utils.load_snapshots(
        args, lambda: lenet5(10),
        lambda: optim.SGD(learning_rate=args.learning_rate or 0.05,
                          learning_rate_decay=0.0))

    ds = driver_utils.make_dataset(train, args, batch)
    opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
    opt.set_optim_method(method)
    driver_utils.configure(opt, args, default_epochs=10, app_name="lenet")
    opt.set_validation(optim.every_epoch(), val,
                       [optim.Top1Accuracy(), optim.Top5Accuracy(),
                        optim.Loss(nn.ClassNLLCriterion())],
                       batch_size=batch)
    trained = opt.optimize()

    from bigdl_tpu.optim.evaluator import Evaluator
    results = Evaluator(trained).test(val, [optim.Top1Accuracy()], batch)
    print(f"Final Top1Accuracy: {results[0][1]}")
    return trained


if __name__ == "__main__":
    main()
