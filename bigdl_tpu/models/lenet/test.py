"""LeNet-5/MNIST Test (evaluation-only) driver.

Reference equivalent: ``models/lenet/Test.scala`` — load a trained snapshot,
evaluate Top1 on the test split.

Run::

    python -m bigdl_tpu.models.lenet.test -f <mnist> --model <model.N>
"""

import numpy as np

import bigdl_tpu.optim as optim
from bigdl_tpu.dataset.datasets import load_mnist
from bigdl_tpu.models import driver_utils
from bigdl_tpu.models.lenet.train import _synthetic, _to_samples
from bigdl_tpu.utils import file_io


def main(argv=None):
    p = driver_utils.base_parser("Evaluate a LeNet-5 snapshot on MNIST")
    args = p.parse_args(argv)
    driver_utils.init_logging()
    if not args.model:
        raise SystemExit("--model <snapshot> is required")
    batch = args.batch_size or 128

    samples = (_synthetic(args.synthetic, seed=2) if args.synthetic
               else _to_samples(load_mnist(args.folder, "test")))
    model = file_io.load(args.model)
    results = optim.Evaluator(model).test(
        samples, [optim.Top1Accuracy(), optim.Top5Accuracy()], batch)
    for method, res in results:
        print(f"{method.name} is {res}")
    return results


if __name__ == "__main__":
    main()
