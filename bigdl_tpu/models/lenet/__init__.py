"""LeNet-5 for MNIST (reference ``models/lenet/LeNet5.scala:25``).

Builds channels-last by default (``layout="NHWC"``, see ``nn/layout.py``);
the public input stays the flat/NCHW MNIST batch."""

from bigdl_tpu.nn import (Sequential, Reshape, SpatialConvolution, Tanh,
                          SpatialMaxPooling, Linear, LogSoftMax, apply_layout)


def lenet5(class_num: int = 10, layout: str = "NHWC") -> Sequential:
    """The classic 2-conv 2-fc LeNet: 28x28 grey image -> class_num logits."""
    m = Sequential()
    m.add(Reshape((1, 28, 28)))
    m.add(SpatialConvolution(1, 6, 5, 5, name="conv1_5x5"))
    m.add(Tanh())
    m.add(SpatialMaxPooling(2, 2, 2, 2))
    m.add(Tanh())
    m.add(SpatialConvolution(6, 12, 5, 5, name="conv2_5x5"))
    m.add(SpatialMaxPooling(2, 2, 2, 2))
    m.add(Reshape((12 * 4 * 4,)))
    m.add(Linear(12 * 4 * 4, 100, name="fc1"))
    m.add(Tanh())
    m.add(Linear(100, class_num, name="fc2"))
    m.add(LogSoftMax())
    return apply_layout(m, layout)
