"""Shared CLI plumbing for the model-zoo Train/Test drivers.

Reference equivalent: the per-model scopt ``OptionParser`` param objects
(``models/lenet/Utils.scala``, ``models/resnet/Train.scala:35-60``) — folder,
batch size, snapshot/resume, checkpoint, learning-rate, max-epoch flags —
plus the driver bootstrap every Train main performs (LoggerFilter + Engine
init).

TPU-native additions: ``--partitions`` selects the distributed trainer over
the device mesh, ``--log-dir`` wires TensorBoard summaries, and
``--synthetic`` substitutes generated records so every driver runs (and is
testable) without the real dataset on disk.
"""

from __future__ import annotations

import argparse
import logging
import os
from typing import Callable, List, Optional

from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.dataset.transformer import SampleToMiniBatch


def base_parser(description: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=description)
    p.add_argument("-f", "--folder", default="./",
                   help="dataset folder (reference -f)")
    p.add_argument("-b", "--batch-size", type=int, default=None,
                   help="global mini-batch size (reference -b)")
    p.add_argument("-e", "--max-epoch", type=int, default=None,
                   help="epochs to train (reference -e)")
    p.add_argument("-i", "--max-iteration", type=int, default=None,
                   help="iterations to train (overrides --max-epoch)")
    p.add_argument("-r", "--learning-rate", type=float, default=None,
                   help="learning rate (reference --learningRate)")
    p.add_argument("--model", default=None,
                   help="model snapshot to resume from (reference --model)")
    p.add_argument("--state", default=None,
                   help="optim-method snapshot to resume from "
                        "(reference --state)")
    p.add_argument("--checkpoint", default=None,
                   help="where to write model.N/optimMethod.N snapshots")
    p.add_argument("--overwrite", action="store_true",
                   help="overwrite existing checkpoint files")
    p.add_argument("--ckpt-keep-last", type=int, default=None,
                   metavar="N",
                   help="retain only the N newest committed snapshots "
                        "(default bigdl.checkpoint.keepLast; 0 keeps all)")
    p.add_argument("--ckpt-async", action="store_true",
                   help="write snapshots on a background thread (the "
                        "train step blocks only for capture; writer "
                        "errors surface at the next save and at exit)")
    p.add_argument("--partitions", type=int, default=1,
                   help="data-parallel partitions; >1 trains with the "
                        "DistriOptimizer over the device mesh")
    p.add_argument("--log-dir", default=None,
                   help="TensorBoard summary directory")
    p.add_argument("--app-name", default=None,
                   help="TensorBoard app name (defaults to the driver name)")
    p.add_argument("--synthetic", type=int, default=0, metavar="N",
                   help="train on N synthetic records instead of --folder")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace of 3 steady-state "
                        "iterations (from iteration 10) into this "
                        "directory — open with TensorBoard's profile "
                        "plugin or Perfetto")
    return p


def init_logging() -> None:
    """Driver logging bootstrap: console + ``bigdl.log`` via LoggerFilter
    (the reference calls ``LoggerFilter.redirectSparkInfoLogs`` at the top
    of every Train main).  Also honors an XLA_FLAGS virtual host-device
    request (``Engine.honor_virtual_devices``), so
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N python -m ...``
    gets the N-device CPU mesh it asked for."""
    from bigdl_tpu.engine import Engine
    Engine.honor_virtual_devices()
    from bigdl_tpu.utils.logger_filter import redirect_spark_info_logs
    redirect_spark_info_logs()


def load_snapshots(args, build_model: Callable, build_optim: Callable):
    """--model/--state resume protocol (reference Train.scala:48-60)."""
    from bigdl_tpu.utils import file_io
    from bigdl_tpu.optim.optim_method import OptimMethod

    model = file_io.load(args.model) if args.model else build_model()
    optim_method = (OptimMethod.load(args.state) if args.state
                    else build_optim())
    return model, optim_method


def make_dataset(records: List, args, batch_size: int):
    """DataSet.array sharded by --partitions + SampleToMiniBatch with the
    reference's global-batch/partition division."""
    ds = DataSet.array(records, args.partitions)
    return ds.transform(SampleToMiniBatch(batch_size, max(1, args.partitions)))


def configure(opt, args, default_epochs: int, app_name: str):
    """Apply end trigger, checkpoint, and summaries from common flags."""
    import bigdl_tpu.optim as optim

    if args.max_iteration:
        opt.set_end_when(optim.max_iteration(args.max_iteration))
    else:
        opt.set_end_when(optim.max_epoch(args.max_epoch or default_epochs))
    if args.checkpoint:
        opt.set_checkpoint(args.checkpoint, optim.every_epoch(),
                           isOverwrite=args.overwrite,
                           keep_last=getattr(args, "ckpt_keep_last", None),
                           async_write=(True if getattr(args, "ckpt_async",
                                                        False) else None))
    if args.log_dir:
        from bigdl_tpu.visualization import TrainSummary, ValidationSummary
        name = args.app_name or app_name
        opt.set_train_summary(TrainSummary(args.log_dir, name))
        opt.set_validation_summary(ValidationSummary(args.log_dir, name))
    if getattr(args, "profile_dir", None):
        opt.set_trace_profile(args.profile_dir)
    return opt
