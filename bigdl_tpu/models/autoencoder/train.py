"""Autoencoder/MNIST Train driver.

Reference equivalent: ``models/autoencoder/Train.scala`` — MNIST images
normalized to [0,1], trained against themselves with MSECriterion and
Adagrad.

Run::

    python -m bigdl_tpu.models.autoencoder.train -f <mnist-folder>
    python -m bigdl_tpu.models.autoencoder.train --synthetic 512
"""

import numpy as np

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import Sample
from bigdl_tpu.dataset.datasets import load_mnist
from bigdl_tpu.models import driver_utils
from bigdl_tpu.models.autoencoder import autoencoder


def _to_samples(images) -> list:
    out = []
    for img in images:
        x = (img.data.astype(np.float32) / 255.0).reshape(-1)
        out.append(Sample(x, x))        # target = input
    return out


def _synthetic(n: int, seed: int = 1) -> list:
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        x = (rng.uniform(0, 1, size=(28 * 28,)) ** 2).astype(np.float32)
        out.append(Sample(x, x))
    return out


def main(argv=None):
    p = driver_utils.base_parser("Train the MNIST autoencoder")
    p.add_argument("--bottleneck", type=int, default=32)
    args = p.parse_args(argv)
    driver_utils.init_logging()
    batch = args.batch_size or 150          # reference batchSize=150

    if args.synthetic:
        train = _synthetic(args.synthetic)
        val = _synthetic(max(args.synthetic // 4, 10), seed=2)
    else:
        train = _to_samples(load_mnist(args.folder, "train"))
        val = _to_samples(load_mnist(args.folder, "test"))

    model, method = driver_utils.load_snapshots(
        args, lambda: autoencoder(args.bottleneck),
        lambda: optim.Adagrad(learning_rate=args.learning_rate or 0.01,
                              learning_rate_decay=0.0))

    ds = driver_utils.make_dataset(train, args, batch)
    criterion = nn.MSECriterion()
    opt = optim.Optimizer.create(model, ds, criterion)
    opt.set_optim_method(method)
    driver_utils.configure(opt, args, default_epochs=10,
                           app_name="autoencoder")
    opt.set_validation(optim.every_epoch(), val, [optim.Loss(criterion)],
                       batch_size=batch)
    trained = opt.optimize()
    print("Training done.")
    return trained


if __name__ == "__main__":
    main()
