"""MNIST autoencoder (reference ``models/autoencoder/Autoencoder.scala``)."""

from bigdl_tpu.nn import Sequential, Reshape, Linear, ReLU, Sigmoid

ROW_N = 28
COL_N = 28
FEATURE_SIZE = ROW_N * COL_N


def autoencoder(class_num: int = 32) -> Sequential:
    """784 -> class_num -> 784 bottleneck autoencoder with sigmoid output."""
    m = Sequential()
    m.add(Reshape((FEATURE_SIZE,)))
    m.add(Linear(FEATURE_SIZE, class_num))
    m.add(ReLU())
    m.add(Linear(class_num, FEATURE_SIZE))
    m.add(Sigmoid())
    return m
