"""TreeLSTM sentiment classifier (reference ``example/treeLSTMSentiment``).

The BinaryTreeLSTM consumes ``[leaf embeddings, tree]`` (tree = (B, n, 2)
child indices, children-before-parents) and emits internal-node hiddens in
topological order; the ROOT is the last internal node, so the classifier
head selects it and projects to classes.
"""

from bigdl_tpu.nn import (BinaryTreeLSTM, Linear, LogSoftMax, Select,
                          Sequential)


def tree_lstm_sentiment(embed_dim: int, hidden_size: int,
                        class_num: int = 5) -> Sequential:
    m = Sequential()
    m.add(BinaryTreeLSTM(embed_dim, hidden_size))
    m.add(Select(2, -1))            # root = last internal node
    m.add(Linear(hidden_size, class_num))
    m.add(LogSoftMax())
    return m
