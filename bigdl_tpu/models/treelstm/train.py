"""TreeLSTM sentiment Train driver.

Reference equivalent: ``example/treeLSTMSentiment/Train.scala`` — SST-style
constituency trees with GloVe leaf embeddings, BinaryTreeLSTM, sentiment
classes.  ``-f`` would point at an SST-format tree corpus; ``--synthetic``
generates balanced binary trees over class-signal leaf embeddings (full
trees: L leaves, L-1 internal nodes, root last).

Run::

    python -m bigdl_tpu.models.treelstm.train --synthetic 256
"""

import numpy as np

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import Sample
from bigdl_tpu.models import driver_utils
from bigdl_tpu.models.treelstm import tree_lstm_sentiment

EMBED_DIM = 16
N_LEAVES = 8


def _full_tree(n_leaves: int) -> np.ndarray:
    """Left-leaning full binary tree: children-before-parents indices."""
    nodes = []
    cur = 0            # running subtree root (starts at leaf 0)
    next_id = n_leaves
    for leaf in range(1, n_leaves):
        nodes.append([cur, leaf])
        cur = next_id
        next_id += 1
    return np.asarray(nodes, np.int32)


def _synthetic(n: int, classes: int = 3, seed: int = 1) -> list:
    rng = np.random.RandomState(seed)
    # class signal fixed across splits (train/val must share the task)
    directions = np.random.RandomState(1234).normal(
        0, 1, size=(classes, EMBED_DIM)).astype(np.float32)
    tree = _full_tree(N_LEAVES)
    out = []
    for lab in rng.randint(0, classes, size=n):
        emb = rng.normal(0, 0.5, size=(N_LEAVES, EMBED_DIM)).astype(np.float32)
        emb += 0.6 * directions[lab]
        out.append(Sample([emb, tree.copy()], np.float32(lab + 1)))
    return out


def main(argv=None):
    p = driver_utils.base_parser("Train the TreeLSTM sentiment classifier")
    p.add_argument("--hidden", type=int, default=32)
    p.add_argument("--classes", type=int, default=3)
    args = p.parse_args(argv)
    driver_utils.init_logging()
    batch = args.batch_size or 32

    if not args.synthetic:
        raise SystemExit("SST corpus parsing is not wired yet; use "
                         "--synthetic N (the model/training path is real)")
    train = _synthetic(args.synthetic, args.classes)
    val = _synthetic(max(args.synthetic // 4, 8), args.classes, seed=2)

    model, method = driver_utils.load_snapshots(
        args, lambda: tree_lstm_sentiment(EMBED_DIM, args.hidden,
                                          args.classes),
        lambda: optim.Adagrad(learning_rate=args.learning_rate or 0.1))

    ds = driver_utils.make_dataset(train, args, batch)
    opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
    opt.set_optim_method(method)
    driver_utils.configure(opt, args, default_epochs=10, app_name="treelstm")
    opt.set_validation(optim.every_epoch(), val, [optim.Top1Accuracy()],
                       batch_size=batch)
    trained = opt.optimize()

    from bigdl_tpu.optim.evaluator import Evaluator
    results = Evaluator(trained).test(val, [optim.Top1Accuracy()], batch)
    print(f"Final Top1Accuracy: {results[0][1]}")
    return trained


if __name__ == "__main__":
    main()
