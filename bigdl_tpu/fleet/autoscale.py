"""Replica-count autoscaling policy — the serving-side sibling of the
ingest ``AutoscalePolicy``.

Pure deterministic hysteresis: no clocks, no randomness — a fixed
sequence of signal samples always produces the same action sequence, so
autoscaling can never make a fleet nondeterministic in anything but
wall-clock.  The supervisor samples two load signals per decision
interval (``bigdl.fleet.autoscale.intervalSec``):

* **queue fill fraction** — mean admission-queue depth across the
  service's replicas over their ``maxQueueDepth`` (the registry's
  queue-depth signal).  Sustained fill means admission control is about
  to shed; more replicas spread the arrival stream.
* **p99 latency vs deadline** — the ``Serving/latency_ms`` histogram's
  p99 against ``bigdl.fleet.autoscale.p99Factor`` x the service
  deadline.  A p99 brushing the deadline sheds next, even while queues
  look shallow.

``patience`` consecutive same-direction signals are required before
acting, and after an action the policy holds for ``cooldown`` intervals
so the new replica count's effect is measured before the next decision.
The host-memory governor is the upper-bound authority: under pressure
the policy never scales up and steps down toward the floor — replica
count yields to memory, not the other way around.
"""

from __future__ import annotations


class FleetAutoscalePolicy:
    """Deterministic hysteresis over (queue fill, p99 latency) producing
    +1 / -1 / 0 replica-count actions.  See the module docstring for the
    signal semantics."""

    def __init__(self, min_replicas: int, max_replicas: int,
                 up_queue_frac: float, down_queue_frac: float,
                 p99_factor: float, patience: int, cooldown: int):
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.up_queue_frac = float(up_queue_frac)
        self.down_queue_frac = float(down_queue_frac)
        self.p99_factor = float(p99_factor)
        self.patience = max(1, int(patience))
        self.cooldown = max(0, int(cooldown))
        self._up_streak = 0
        self._down_streak = 0
        self._hold = 0

    def decide(self, queue_frac: float, p99_ms: float, deadline_ms: float,
               replicas: int, under_pressure: bool = False) -> int:
        """One interval's decision: +1 add a replica, -1 retire one, 0
        hold.  ``p99_ms`` may be 0.0 when the latency histogram has no
        samples yet (an idle service never scales on latency)."""
        if self._hold > 0:
            self._hold -= 1
            return 0
        hot_p99 = (deadline_ms > 0 and p99_ms > 0 and
                   p99_ms >= self.p99_factor * deadline_ms)
        down = (replicas > self.min_replicas and
                (under_pressure or
                 (queue_frac <= self.down_queue_frac and not hot_p99)))
        up = (not down and not under_pressure and
              replicas < self.max_replicas and
              (queue_frac >= self.up_queue_frac or hot_p99))
        if up:
            self._up_streak += 1
            self._down_streak = 0
        elif down:
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = self._down_streak = 0
        if self._up_streak >= self.patience:
            self._up_streak = 0
            self._hold = self.cooldown
            return 1
        if self._down_streak >= self.patience:
            self._down_streak = 0
            self._hold = self.cooldown
            return -1
        return 0
