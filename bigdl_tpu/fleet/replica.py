"""One serving replica: a ``ServingEngine`` plus the fleet-side
lifecycle state the supervisor keys on.

A replica is DISPOSABLE by design: the engine's one-way stop contract
means a replica is never revived — a crashed or retired slot is
replaced by a freshly built replica whose executables warm-load from
the compile cache (~milliseconds, not a recompile).  The fleet
distinguishes two ends of life:

* **retired** — the fleet took it out of rotation deliberately (a
  rollout's old side, a scale-down, fleet stop).  Queued work drains
  within the grace window; nothing to repair.
* **crashed** — the batcher thread died without an orderly drain (an
  async kill, an escaped internal error).  The supervisor sweeps the
  replica's stranded in-flight requests into ``shed`` (the accounting
  identity survives the crash) and restarts the slot within its
  restart budget.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from bigdl_tpu import analysis, telemetry
from bigdl_tpu.serving.engine import ServingEngine
from bigdl_tpu.utils import elastic


class ReplicaKilled(BaseException):
    """Async-raised into a replica's batcher thread by the chaos
    harness (``bigdl.chaos.killReplicaAt``).  A ``BaseException`` on
    purpose: the batcher's internal ``except Exception`` recovery must
    NOT be able to absorb it — this models a hard crash (segfault,
    OOM-kill), not a handleable dispatch error."""


class Replica:
    """A supervised serving replica: engine + slot identity + lifecycle
    flags.  ``slot`` survives restarts (the restart budget is per slot,
    not per engine instance); ``version`` names the model generation the
    replica serves (rollouts bump it)."""

    def __init__(self, service: str, slot: int, version: str, model,
                 warm_row: Optional[np.ndarray] = None,
                 engine_kw: Optional[Dict[str, Any]] = None):
        self.service = service
        self.slot = slot
        self.version = version
        self._lock = analysis.make_lock("fleet.replica")
        self.retired = False         # guarded-by: _lock
        self.engine = ServingEngine(model, **(engine_kw or {}))
        if warm_row is not None:
            # AOT-warm every configured bucket BEFORE the replica takes
            # traffic: with the compile cache armed this is a warm load,
            # and the first routed request never pays a compile
            self.engine.warmup(warm_row)

    @property
    def name(self) -> str:
        return f"{self.service}/{self.version}#{self.slot}"

    def healthy(self) -> bool:
        """Routable: in rotation, batcher alive, admission open."""
        return (not self.retired and not self.engine.terminal and
                not self.engine.draining and self.engine.batcher_alive())

    def crashed(self) -> bool:
        """Died WITHOUT an orderly drain — the restart signal."""
        return not self.retired and self.engine.crashed()

    def retire(self, grace: Optional[float] = None  # thread-root: also entered from the fleet supervisor (check_restarts / autoscale down / drain_all)
               ) -> None:
        """Deliberate end of life: out of rotation first (the flag), then
        the engine's graceful drain.  Idempotent, like the stop contract
        it rides on.  Entered from BOTH the user thread (fleet stop,
        rollout drain) and the supervisor (crash replacement, autoscale
        down) — the lifecycle lock makes the flag flip a clean
        happens-before edge for ``healthy()`` routers."""
        with self._lock:
            self.retired = True
        self.engine.stop(grace)

    def kill(self) -> bool:
        """Chaos only: hard-kill the batcher thread with an async-raised
        :class:`ReplicaKilled`.  Returns True when the injection was
        delivered (the thread was alive to receive it).  The exception
        lands at the thread's next bytecode — the engine's ``finally``
        still closes the engine and sheds QUEUED requests, but a popped
        in-flight batch is stranded unaccounted, exactly the hole the
        supervisor's sweep (``RequestHandle.abandon``) exists to plug."""
        tid = self.engine.batcher_ident()
        if tid is None or not self.engine.batcher_alive():
            return False
        # deliberately NO completion re-check under the engine lock: this
        # injection MODELS the stray abort the async-abort-unguarded rule
        # exists to prevent — the supervisor's sweep is the system under
        # test
        delivered = elastic._async_raise(tid, ReplicaKilled)  # lint: allow(async-abort-unguarded)
        if delivered:
            telemetry.counter("Fleet/replica_kills",
                              labels={"service": self.service}).inc()
        return delivered
