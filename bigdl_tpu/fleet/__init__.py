"""Fleet control plane: N models x N replicas as one supervised fleet.

The original deployment story was "a plain Spark program" — the driver
owned model lifecycle and a restart meant a full job resubmission.  This
package is the layer that turns the rebuilt subsystems into
self-operating serving machinery:

* **zero-downtime hot swap** — a candidate model warm-loads its
  executables through the compile cache and warms the full
  ``bigdl.compile.buckets`` plan while the incumbent keeps serving; the
  router then shifts traffic atomically and the old replicas drain
  through ``ServingEngine.stop(grace)``.
* **blue/green rollout gated on correctness** — promotion requires the
  semantic state fingerprint captured at candidate-prepare time to
  re-verify immediately before cutover AND a shadow-traffic parity
  check (a sample of recently served live requests is mirrored to the
  candidate; outputs compare bit-wise for deterministic swaps, allclose
  otherwise) — any violation rolls back automatically and the incumbent
  never stops serving.
* **replica lifecycle supervision** — :class:`FleetSupervisor` restarts
  crashed replicas within a restart budget, autoscales the replica
  count from queue depth and the ``Serving/latency_ms`` p99 (a
  :class:`FleetAutoscalePolicy` hysteresis state machine, with the
  host-memory governor as upper-bound authority), and implements
  checkpoint-to-serving promotion as one verified step: the train loop
  publishes a snapshot, the fleet detects it via
  ``CheckpointManager.watch_latest()``, deep-verifies (checksums + the
  semantic fingerprint), warm-loads, and rolls.

Chaos-proven: ``bigdl.chaos.killReplicaAt`` (async hard-kill of a
batcher thread), ``bigdl.chaos.corruptCandidateAt`` (candidate weights
rot after fingerprint capture), and ``bigdl.chaos.sigtermFleetAt``
(fleet-wide preemption mid-rollout) — the per-request accounting
identity (completed + shed + rejected + quarantined == submitted) holds
exactly across every fault, and a clean rollout loses zero requests.

See ``docs/programming-guide/optimization.md`` ("Running a fleet") for
the rollout state diagram and the failure matrix.
"""

from bigdl_tpu.fleet.autoscale import FleetAutoscalePolicy
from bigdl_tpu.fleet.replica import Replica, ReplicaKilled
from bigdl_tpu.fleet.rollout import RolloutReport
from bigdl_tpu.fleet.supervisor import FleetSupervisor
from bigdl_tpu.fleet.fleet import Fleet

__all__ = [
    "Fleet",
    "FleetAutoscalePolicy",
    "FleetSupervisor",
    "Replica",
    "ReplicaKilled",
    "RolloutReport",
]
