"""The :class:`Fleet` facade and per-service state.

A fleet runs N named services (one model each) x N replicas (one
``ServingEngine`` each) under one :class:`FleetSupervisor`.  The fleet
owns the layer the engines deliberately do not: request accounting that
SURVIVES a replica crash, atomic traffic cutover between model
versions, replica restart/autoscale policy, and checkpoint-to-serving
promotion.

Fleet-level accounting: every handle the fleet returns is tracked until
terminal.  The engine's own identity (completed + shed + rejected +
quarantined == submitted) holds per engine only while the engine lives;
a hard-killed batcher strands its popped in-flight batch unaccounted.
The supervisor's sweep closes that hole with
``RequestHandle.abandon()`` — crashed-replica victims land in ``shed``,
retriable, and the FLEET identity holds exactly across every chaos
fault (asserted by tests/test_fleet.py).

Routing: round-robin over the healthy replicas of the requested
service, snapshotted under the service lock — the same lock a rollout's
cutover swaps the replica list under, so any submit routes entirely to
the old set or entirely to the new, never to a half-swapped router.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu import telemetry
from bigdl_tpu.telemetry import incident, request_trace
from bigdl_tpu.fleet.autoscale import FleetAutoscalePolicy
from bigdl_tpu.fleet.replica import Replica
from bigdl_tpu.fleet.rollout import RolloutReport, run_rollout
from bigdl_tpu.fleet.supervisor import FleetSupervisor
from bigdl_tpu import analysis
from bigdl_tpu.resources import GOVERNOR
from bigdl_tpu.serving.engine import (OUTCOMES, Overloaded, RequestHandle,
                                      ServingInfraError)
from bigdl_tpu.utils import config, elastic

logger = logging.getLogger("bigdl_tpu")


def _fleet_reject(service: str, reason: str) -> Overloaded:
    """Fleet-level rejection choke point: requests the fleet turns away
    never reach an engine's admission door, so the trace is minted AND
    terminated right here — a rejected submission still explains itself
    (``err.trace_id`` -> ``request_trace.get``)."""
    tid = request_trace.mint("fleet", service=service)
    err = Overloaded(reason)
    request_trace.verdict(tid, "rejected", error=err,
                          reason=reason.replace(" ", "_"))
    return err


class _Service:
    """One named model's serving state: active replicas, pending-handle
    ledger, shadow ring, restart budgets, promotion source."""

    def __init__(self, fleet: "Fleet", name: str, model,
                 replicas: int, warm_row: Optional[np.ndarray],
                 engine_kw: Optional[Dict[str, Any]]):
        self.fleet = fleet
        self.name = name
        self.model = model
        self.warm_row = warm_row
        self.engine_kw = dict(engine_kw or {})
        self._version_seq = 1        # guarded-by: _lock
        self.version = "v1"          # guarded-by: _lock
        self._lock = analysis.make_lock("fleet.service")
        self._rollout_lock = analysis.make_lock("fleet.rollout")
        self._slot_seq = 0           # guarded-by: _lock
        self._active: List[Replica] = []      # guarded-by: _lock
        #: (handle, replica) for every admitted request not yet tallied
        self._pending: List[Tuple[RequestHandle, Replica]] = []  # guarded-by: _lock
        self._counts: Dict[str, int] = dict.fromkeys(OUTCOMES, 0)  # guarded-by: _lock
        self._counts["submitted"] = 0
        self._rr = 0                 # guarded-by: _lock
        self._restarts: Dict[int, int] = {}   # guarded-by: _lock
        self.draining = False        # guarded-by: _lock
        shadow_n = max(1, config.get_int("bigdl.fleet.shadowSample", 8))
        #: recently COMPLETED (decoded payload, output) pairs — the
        #: rollout's shadow-traffic source.  Bounded: parity needs a
        #: sample, not a replay log.
        self.shadow: "deque[Tuple[Any, Any]]" = deque(maxlen=shadow_n)  # guarded-by: _lock
        self._cut_ns: Optional[int] = None            # guarded-by: _lock
        self._cut_version: Optional[str] = None       # guarded-by: _lock
        #: cutover -> first completed request on the new replica set
        self.last_swap_to_serve_ms: Optional[float] = None  # guarded-by: _lock
        self.last_promotion: Optional[RolloutReport] = None  # guarded-by: _lock
        self._watch_mgr = None
        self._promo_tick = 0         # guarded-by: _lock
        self._promo_interval = config.get_float(
            "bigdl.fleet.promotionPollSec", 0.2)
        self._last_promoted = -1     # guarded-by: _lock
        self._promo_attempted = -1   # guarded-by: _lock
        self._as_tick = 0            # guarded-by: _lock
        self._as_interval = config.get_float(
            "bigdl.fleet.autoscale.intervalSec", 0.25)
        self._policy = FleetAutoscalePolicy(
            config.get_int("bigdl.fleet.minReplicas", 1),
            config.get_int("bigdl.fleet.maxReplicas", 4),
            config.get_float("bigdl.fleet.autoscale.upQueueFrac", 0.5),
            config.get_float("bigdl.fleet.autoscale.downQueueFrac", 0.05),
            config.get_float("bigdl.fleet.autoscale.p99Factor", 0.8),
            config.get_int("bigdl.fleet.autoscale.patience", 2),
            config.get_int("bigdl.fleet.autoscale.cooldown", 3))
        for _ in range(max(1, replicas)):
            self._active.append(self.new_replica(model, self.version))
        self._publish_replica_gauge()

    # -- replica construction / router state ------------------------------

    def new_replica(self, model, version: str,
                    slot: Optional[int] = None) -> Replica:
        if slot is None:
            with self._lock:
                slot = self._slot_seq
                self._slot_seq += 1
        return Replica(self.name, slot, version, model,
                       warm_row=self.warm_row, engine_kw=self.engine_kw)

    def active_replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._active)

    def peek_next_version(self) -> str:
        return f"v{self._version_seq + 1}"

    def cutover(self, new: List[Replica], model, version: str,
                cut_ns: int) -> List[Replica]:
        """The atomic router swap: one pointer exchange under the
        service lock.  Returns the old replica set for the caller to
        drain."""
        with self._lock:
            old = self._active
            self._active = list(new)
            self.model = model
            self.version = version
            self._version_seq += 1
            self._cut_ns = cut_ns
            self._cut_version = version
            self.last_swap_to_serve_ms = None
        self._publish_replica_gauge()
        return old

    def shadow_sample(self, n: int) -> List[Tuple[Any, Any]]:
        with self._lock:
            return list(self.shadow)[-max(0, n):]

    def _publish_replica_gauge(self) -> None:
        telemetry.gauge("Fleet/replicas",
                        labels={"service": self.name}).set(
                            len(self._active))

    # -- request path ------------------------------------------------------

    def submit(self, payload, deadline_ms: Optional[float] = None
               ) -> RequestHandle:
        self.fleet._next_submit(self)
        with self._lock:
            self._counts["submitted"] += 1
            reps = [r for r in self._active if r.healthy()]
            if self.draining or not reps:
                self._counts["rejected"] += 1
                reason = ("fleet draining" if self.draining
                          else "no healthy replicas")
                telemetry.counter("Fleet/rejected",
                                  labels={"service": self.name}).inc()
                raise _fleet_reject(self.name, reason)
            self._rr += 1
            rep = reps[self._rr % len(reps)]
        try:
            h = rep.engine.submit(payload, deadline_ms)
        except Exception:
            # the engine said no (Overloaded) or escalated before
            # admission (e.g. a payload past the host-memory budget):
            # either way the request never entered a queue — it is a
            # fleet-level rejection and the identity stays closed
            with self._lock:
                self._counts["rejected"] += 1
            telemetry.counter("Fleet/rejected",
                              labels={"service": self.name}).inc()
            raise
        with self._lock:
            self._pending.append((h, rep))
        return h

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def sweep(self) -> None:
        """Tally terminal handles into the service counts; abandon the
        stranded in-flight requests of dead engines (the crash hole the
        engine itself cannot close).  Concurrent-safe by list swap: each
        sweeper owns the batch it swapped out."""
        with self._lock:
            batch, self._pending = self._pending, []
            cut_ns, cut_version = self._cut_ns, self._cut_version
        keep: List[Tuple[RequestHandle, Replica]] = []
        tally: Dict[str, int] = {}
        first_serve_ms = None
        abandoned = 0
        abandon_reason: Optional[str] = None
        abandon_tid: Optional[str] = None
        for h, rep in batch:
            if not h.done():
                eng = rep.engine
                if eng.terminal and not eng.batcher_alive():
                    # nothing can ever finish this handle: the batcher
                    # is dead and the leftover sweep already ran
                    crashed = rep.crashed()
                    h.abandon(ServingInfraError(
                        f"replica {rep.name} "
                        f"{'crashed' if crashed else 'went down'} with "
                        "this request in flight — retriable"),
                        reason="replica_crash" if crashed else
                        "replica_down")
                    abandoned += 1
                    abandon_reason = ("replica_crash" if crashed
                                      else "replica_down")
                    if abandon_tid is None:
                        abandon_tid = getattr(h, "trace_id", None)
                    if crashed:
                        telemetry.counter(
                            "Fleet/crash_sheds",
                            labels={"service": self.name}).inc()
                else:
                    keep.append((h, rep))
                    continue
            out = h.outcome or "shed"
            tally[out] = tally.get(out, 0) + 1
            if out == "completed":
                try:
                    result = h.result(timeout=0)
                except Exception:
                    result = None
                if result is not None:
                    with self._lock:
                        self.shadow.append((h.raw, result))
                if (cut_ns is not None and rep.version == cut_version
                        and h.finish_ns is not None
                        and h.finish_ns >= cut_ns):
                    ms = (h.finish_ns - cut_ns) / 1e6
                    if first_serve_ms is None or ms < first_serve_ms:
                        first_serve_ms = ms
        if abandoned:
            # the sweep just closed the crash hole — one flight-recorder
            # event (and at most one bundle per service+cause) for the
            # whole abandoned cohort, anchored on its first trace
            incident.record("fleet/abandon", service=self.name,
                            victims=abandoned, reason=abandon_reason)
            incident.maybe_dump(f"fleet/{self.name}/{abandon_reason}",
                                trace_id=abandon_tid,
                                reason=abandon_reason)
        with self._lock:
            for k, v in tally.items():
                self._counts[k] += v
            self._pending.extend(keep)
            if first_serve_ms is not None and self._cut_ns == cut_ns:
                self.last_swap_to_serve_ms = first_serve_ms
                self._cut_ns = None
                telemetry.gauge("Fleet/swap_to_serve_ms").set(
                    first_serve_ms)

    # -- supervision -------------------------------------------------------

    def check_restarts(self) -> None:
        """Replace crashed replicas within the per-slot restart budget;
        a slot past its budget is abandoned (better N-1 replicas than a
        crash loop soaking the supervisor)."""
        max_restarts = config.get_int("bigdl.fleet.maxReplicaRestarts", 2)
        for rep in self.active_replicas():
            if not rep.crashed():
                continue
            # zero-grace retire: out of the router either way, and the
            # engine's own stop path sweeps its leftovers
            rep.retire(0.0)
            with self._lock:
                try:
                    self._active.remove(rep)
                except ValueError:
                    continue            # a rollout already swapped it out
                used = self._restarts.get(rep.slot, 0)
            if used >= max_restarts:
                telemetry.counter("Fleet/replica_abandoned",
                                  labels={"service": self.name}).inc()
                incident.record("fleet/slot_abandoned",
                                service=self.name, replica=rep.name,
                                slot=rep.slot, restarts=used)
                logger.error(
                    "fleet %s: replica %s crashed past its restart "
                    "budget (%d) — slot abandoned", self.name, rep.name,
                    max_restarts)
                self._publish_replica_gauge()
                continue
            with self._lock:
                self._restarts[rep.slot] = used + 1
            telemetry.counter("Fleet/replica_restarts",
                              labels={"service": self.name}).inc()
            incident.record("fleet/replica_restart", service=self.name,
                            replica=rep.name, slot=rep.slot,
                            attempt=used + 1, budget=max_restarts)
            logger.warning(
                "fleet %s: replica %s crashed — restarting slot %d "
                "(restart %d/%d)", self.name, rep.name, rep.slot,
                used + 1, max_restarts)
            try:
                fresh = self.new_replica(self.model, self.version,
                                         slot=rep.slot)
            except Exception as e:
                telemetry.counter("Fleet/replica_abandoned",
                                  labels={"service": self.name}).inc()
                logger.error("fleet %s: slot %d restart failed: %r",
                             self.name, rep.slot, e)
                continue
            with self._lock:
                self._active.append(fresh)
            self._publish_replica_gauge()

    def kill_replica(self, index: int) -> bool:
        """Chaos entry: hard-kill the ``index``-th (mod count) active
        replica's batcher thread."""
        reps = self.active_replicas()
        if not reps:
            return False
        return reps[index % len(reps)].kill()

    def autoscale_tick(self, poll_interval: float) -> None:
        if not config.get_bool("bigdl.fleet.autoscale.enabled", False):
            return
        with self._lock:
            self._as_tick += 1
            tick = self._as_tick
        every = max(1, int(round(self._as_interval / poll_interval)))
        if tick % every:
            return
        reps = [r for r in self.active_replicas() if r.healthy()]
        if not reps:
            return
        queue_frac = sum(
            r.engine.queue_depth() / max(1, r.engine.max_queue_depth)
            for r in reps) / len(reps)
        p99 = telemetry.histogram("Serving/latency_ms").percentile(99)
        if not (isinstance(p99, (int, float)) and p99 == p99):  # NaN guard
            p99 = 0.0
        action = self._policy.decide(
            queue_frac, float(p99), reps[0].engine.deadline_ms,
            len(reps), GOVERNOR.under_pressure())
        if action > 0:
            fresh = self.new_replica(self.model, self.version)
            with self._lock:
                self._active.append(fresh)
            telemetry.counter("Fleet/autoscale_actions",
                              labels={"service": self.name,
                                      "direction": "up"}).inc()
            incident.record("fleet/autoscale", service=self.name,
                            direction="up", queue_frac=round(queue_frac, 3),
                            p99_ms=round(float(p99), 2))
            logger.info("fleet %s: autoscale +1 replica (queue %.2f, "
                        "p99 %.1f ms) -> %d", self.name, queue_frac,
                        p99, len(reps) + 1)
        elif action < 0:
            with self._lock:
                victim = self._active.pop() if len(self._active) > 1 \
                    else None
            if victim is not None:
                victim.retire(self.fleet.grace_period)
                telemetry.counter("Fleet/autoscale_actions",
                                  labels={"service": self.name,
                                          "direction": "down"}).inc()
                incident.record("fleet/autoscale", service=self.name,
                                direction="down",
                                queue_frac=round(queue_frac, 3))
                logger.info("fleet %s: autoscale -1 replica -> %d",
                            self.name, len(reps) - 1)
        self._publish_replica_gauge()

    def promotion_tick(self, poll_interval: float) -> None:
        """Checkpoint-to-serving promotion as ONE verified step: a new
        committed snapshot (cheap ``watch_latest`` poll) is deep-loaded
        — payload checksums AND the save-time semantic fingerprint
        verify inside ``load_latest`` — then rolled out through the full
        gated state machine.  A snapshot that fails any gate is recorded
        and never retried (the NEXT snapshot gets its chance); the
        incumbent keeps serving throughout."""
        if self._watch_mgr is None:
            return
        with self._lock:
            self._promo_tick += 1
            tick = self._promo_tick
        every = max(1, int(round(self._promo_interval / poll_interval)))
        if tick % every:
            return
        try:
            newest = self._watch_mgr.watch_latest()
        except Exception as e:
            logger.warning("fleet %s: promotion watch failed: %r",
                           self.name, e)
            return
        if (newest is None or newest <= self._last_promoted or
                newest == self._promo_attempted):
            return
        with self._lock:
            self._promo_attempted = newest
        loaded = None
        try:
            loaded = self._watch_mgr.load_latest()
        except Exception as e:
            logger.error("fleet %s: snapshot %d failed verified load: %r",
                         self.name, newest, e)
        if not loaded:
            telemetry.counter("Fleet/promotion_failures",
                              labels={"service": self.name}).inc()
            return
        model, _optim, n = loaded
        report = run_rollout(self, model)
        with self._lock:
            self.last_promotion = report
        if report.promoted:
            with self._lock:
                self._last_promoted = max(n, newest)
            telemetry.counter("Fleet/promotions",
                              labels={"service": self.name}).inc()
            incident.record("fleet/promotion", service=self.name,
                            snapshot=n, to_version=report.to_version)
            logger.info("fleet %s: snapshot %d promoted to %s",
                        self.name, n, report.to_version)
        else:
            telemetry.counter("Fleet/promotion_failures",
                              labels={"service": self.name}).inc()
            incident.record("fleet/promotion_failure",
                            service=self.name, snapshot=n,
                            reason=report.reason)

    # -- teardown / introspection -----------------------------------------

    def drain_all(self, grace: float) -> None:
        with self._lock:
            self.draining = True
        for rep in self.active_replicas():
            rep.retire(grace)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = dict(self._counts)
            pending = len(self._pending)
            replicas = len(self._active)
            version = self.version
            draining = self.draining
            restarts = sum(self._restarts.values())
            swap_ms = self.last_swap_to_serve_ms
        out["unaccounted"] = out["submitted"] - sum(
            out[o] for o in OUTCOMES)
        out["pending"] = pending
        out["replicas"] = replicas
        out["version"] = version
        out["draining"] = draining
        out["restarts"] = restarts
        out["last_swap_to_serve_ms"] = swap_ms
        return out


class Fleet:
    """The control-plane facade.  Typical shape::

        fleet = Fleet()
        fleet.add_model("ranker", model, replicas=2, warm_row=row)
        fleet.watch("ranker", CheckpointManager(ckpt_dir))  # promotion
        h = fleet.submit("ranker", payload)
        out = h.result(timeout=1.0)
        report = fleet.rollout("ranker", candidate)   # manual blue/green
        fleet.stop()

    All knobs default from ``bigdl.fleet.*`` (docs/configuration.md).
    ``stop()`` is one-way and idempotent, mirroring the engine
    contract."""

    def __init__(self, poll_interval: Optional[float] = None,
                 grace_period: Optional[float] = None,
                 start: bool = True):
        self.poll_interval = float(
            poll_interval if poll_interval is not None else
            config.get_float("bigdl.fleet.pollInterval", 0.05))
        self.grace_period = float(
            grace_period if grace_period is not None else
            config.get_float("bigdl.fleet.gracePeriod", 5.0))
        self._services: Dict[str, _Service] = {}
        self._seq_lock = analysis.make_lock("fleet.seq")
        self._submit_seq = 0
        self._closed = False
        self._preempt_seen = False
        self.supervisor = FleetSupervisor(self, self.poll_interval)
        if start:
            self.supervisor.start()

    # -- service management ------------------------------------------------

    def add_model(self, name: str, model,
                  replicas: Optional[int] = None,
                  warm_row: Optional[np.ndarray] = None,
                  engine_kw: Optional[Dict[str, Any]] = None) -> None:
        """Register ``name`` and bring up its replicas (each one
        warm-loads through the compile cache and — with ``warm_row`` —
        AOT-warms every bucket before taking traffic)."""
        if self._closed:
            raise ServingInfraError("fleet is stopped — build a new one")
        if name in self._services:
            raise ValueError(f"service {name!r} already registered")
        n = int(replicas if replicas is not None else
                config.get_int("bigdl.fleet.replicas", 1))
        self._services[name] = _Service(self, name, model, n, warm_row,
                                        engine_kw)
        logger.info("fleet: service %s up (%d replica(s))", name, n)

    def watch(self, name: str, checkpoint) -> None:
        """Arm checkpoint-to-serving promotion for ``name``:
        ``checkpoint`` is a ``CheckpointManager`` or a directory path.
        The supervisor polls ``watch_latest()`` every
        ``bigdl.fleet.promotionPollSec`` and promotes each NEW committed
        snapshot through the verified rollout path."""
        from bigdl_tpu.utils.checkpoint_manager import CheckpointManager
        svc = self._service(name)
        if isinstance(checkpoint, str):
            checkpoint = CheckpointManager(checkpoint)
        svc._watch_mgr = checkpoint

    def _service(self, name: str) -> _Service:
        try:
            return self._services[name]
        except KeyError:
            raise KeyError(f"unknown service {name!r}; registered: "
                           f"{sorted(self._services)}") from None

    # -- request path ------------------------------------------------------

    def submit(self, name: str, payload,
               deadline_ms: Optional[float] = None) -> RequestHandle:
        """Route one request to a healthy replica of ``name`` (or raise
        a structured retriable :class:`Overloaded`)."""
        if self._closed:
            raise _fleet_reject(name, "fleet stopped")
        return self._service(name).submit(payload, deadline_ms)

    def _next_submit(self, service: _Service) -> int:
        """Fleet-wide submit sequencing — also the chaos choke point:
        ``killReplicaAt`` and ``sigtermFleetAt`` count THESE."""
        from bigdl_tpu.utils import chaos
        with self._seq_lock:
            self._submit_seq += 1
            n = self._submit_seq
        victim = chaos.kill_replica(n)
        if victim is not None:
            service.kill_replica(victim)
        chaos.sigterm_fleet(n)
        return n

    # -- rollout -----------------------------------------------------------

    def rollout(self, name: str, candidate_model,
                expected_fingerprint: Optional[str] = None,
                replicas: Optional[int] = None,
                parity: Optional[str] = None,
                grace: Optional[float] = None) -> RolloutReport:
        """Blue/green swap ``name`` to ``candidate_model`` through the
        gated state machine (see :mod:`bigdl_tpu.fleet.rollout`).
        Returns the report; on any gate violation the candidate is
        rolled back and the incumbent never stopped serving."""
        return run_rollout(self._service(name), candidate_model,
                           expected_fingerprint=expected_fingerprint,
                           replicas=replicas, parity=parity,
                           grace=grace if grace is not None
                           else self.grace_period)

    # -- supervision tick --------------------------------------------------

    def _tick(self) -> None:    # thread-root: fleet-supervisor monitor
        preempted = elastic.preemption_requested()
        if preempted and not self._preempt_seen:
            self._preempt_seen = True
            logger.warning("fleet: preemption observed — all services "
                           "draining (replicas self-drain, rollouts "
                           "abort)")
            # the signal handler itself only appended the ring event
            # (async-signal-safe); the supervisor thread is where the
            # flight-recorder bundle is safe to write
            incident.record("fleet/preemption_drain",
                            services=sorted(self._services))
            incident.maybe_dump("preemption", reason="preemption")
            for svc in list(self._services.values()):
                with svc._lock:
                    svc.draining = True
        for svc in list(self._services.values()):
            svc.sweep()
            if not preempted and not svc.draining:
                svc.check_restarts()
                svc.autoscale_tick(self.poll_interval)
                svc.promotion_tick(self.poll_interval)

    # -- accounting / teardown --------------------------------------------

    def stats(self, name: Optional[str] = None) -> Dict[str, Any]:
        """Per-service outcome counters plus the fleet aggregate.  The
        identity (``completed + shed + rejected + quarantined ==
        submitted``; ``unaccounted == 0``) is exact after
        :meth:`quiesce` (or :meth:`stop`)."""
        if name is not None:
            return self._service(name).stats()
        services = {n: s.stats() for n, s in self._services.items()}
        total: Dict[str, int] = dict.fromkeys(
            ("submitted",) + OUTCOMES, 0)
        for s in services.values():
            for k in total:
                total[k] += s[k]
        total["unaccounted"] = total["submitted"] - sum(
            total[o] for o in OUTCOMES)
        return {"services": services, "fleet": total,
                "submit_seq": self._submit_seq}

    def quiesce(self, timeout: float = 30.0) -> bool:
        """Sweep until every issued handle is terminal (True) or the
        timeout lapses (False) — call before asserting the exact
        identity."""
        deadline = time.monotonic() + timeout
        while True:
            pending = 0
            for svc in list(self._services.values()):
                svc.sweep()
                pending += svc.pending_count()
            if pending == 0:
                return True
            if time.monotonic() > deadline:
                return False
            time.sleep(0.01)

    def stop(self, grace: Optional[float] = None) -> None:
        """Fleet-wide graceful shutdown: supervisor down, every replica
        drains via the engine stop contract, then a final sweep closes
        the accounting.  Idempotent and one-way."""
        if self._closed:
            return
        self._closed = True
        budget = grace if grace is not None else self.grace_period
        self.supervisor.stop()
        for svc in list(self._services.values()):
            svc.drain_all(budget)
        self.quiesce(timeout=budget + 10.0)

    def __enter__(self) -> "Fleet":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
