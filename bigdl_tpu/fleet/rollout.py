"""Blue/green rollout: the gated state machine behind every model swap.

::

    PREPARE ──► VERIFY ──► SHADOW ──► CUTOVER ──► DRAIN
       │           │          │          (atomic router swap; the
       │           │          │           incumbent replicas then drain
       │           │          │           via stop(grace) — zero
       │           │          │           requests lost)
       └───────────┴──────────┴──► ROLLBACK (candidate retired, the
                                   incumbent NEVER stopped serving)

* **PREPARE** — the candidate's semantic fingerprint (over its params
  tree) is captured FIRST; then replicas are built: executables
  warm-load through the compile cache and every ``bigdl.compile.
  buckets`` variant warms before the candidate sees one live request.
* **VERIFY** — the fingerprint recomputes immediately before cutover
  and must match the capture: weights that rotted anywhere between
  prepare and cutover (``bigdl.chaos.corruptCandidateAt`` models this)
  are refused.  Checkpoint-promotion flows get the save-time manifest
  fingerprint verified earlier, inside ``CheckpointManager.
  load_latest`` deep verification — this leg covers the load-to-cutover
  window on top.
* **SHADOW** — up to ``bigdl.fleet.shadowSample`` recently COMPLETED
  live requests are mirrored through the candidate and compared against
  the incumbent's answers: bit-wise when ``bigdl.fleet.parityMode`` is
  ``bitwise`` (an identical-weights infra swap must not change one
  bit), ``np.allclose(parityRtol, parityAtol)`` for ``allclose``, or
  skipped for ``off`` (a deliberately different model — a promoted
  checkpoint — legitimately diverges past any tolerance).
* **CUTOVER** — one pointer swap under the service lock: requests
  admitted before it complete on the old replicas, requests after it
  route to the new — no window where neither side serves.
* **DRAIN** — old replicas retire through the engine's graceful
  ``stop(grace)``; queued work completes (or sheds retriably past the
  grace window, still accounted).

A fleet-wide preemption (``elastic.preemption_requested``) observed at
any phase boundary aborts into ROLLBACK — mid-rollout SIGTERM never
leaves the router pointing at a half-warmed candidate.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

import numpy as np

from bigdl_tpu import telemetry
from bigdl_tpu.telemetry import incident
from bigdl_tpu.utils import config, elastic

logger = logging.getLogger("bigdl_tpu")


@dataclass
class RolloutReport:
    """What one rollout did and how long each phase took.  Returned for
    promoted AND rolled-back rollouts — the caller branches on
    :attr:`promoted`; a rollback is an answered question, not an
    exception."""

    service: str
    from_version: str
    to_version: str
    promoted: bool = False
    rolled_back: bool = False
    reason: str = ""
    fingerprint_expected: Optional[str] = None
    fingerprint_observed: Optional[str] = None
    parity_mode: str = "bitwise"
    parity_checked: int = 0
    parity_max_abs_diff: float = 0.0
    prepare_ms: float = 0.0
    verify_ms: float = 0.0
    shadow_ms: float = 0.0
    drain_ms: float = 0.0
    #: rollout-start -> traffic-on-candidate wall time (the hot-swap
    #: headline: with a warm compile cache this is a small fraction of
    #: one cold compile)
    swap_ms: float = 0.0
    cutover_ns: Optional[int] = None
    replicas: int = 0
    notes: List[str] = field(default_factory=list)


def _params_fingerprint(model) -> str:
    """Fingerprint key over the model's params tree.  Deliberately NOT
    over the module object: engine construction memoizes compiled
    callables onto the module (``_eval_jit``), which the object-graph
    walk would see — the params tree is the stable semantic identity
    across prepare/build/cutover."""
    from bigdl_tpu.integrity import fingerprint_key, host_fingerprint
    return fingerprint_key(host_fingerprint(model.parameters()[0]))


def _parity_compare(got, want, mode: str, rtol: float,
                    atol: float) -> Tuple[bool, float]:
    """(outputs agree, max abs elementwise diff seen)."""
    import jax
    la = jax.tree_util.tree_leaves(got)
    lb = jax.tree_util.tree_leaves(want)
    if len(la) != len(lb):
        return False, float("inf")
    worst = 0.0
    ok = True
    for x, y in zip(la, lb):
        x = np.asarray(x)
        y = np.asarray(y)
        if x.shape != y.shape or x.dtype != y.dtype:
            return False, float("inf")
        if x.size:
            with np.errstate(invalid="ignore"):
                worst = max(worst, float(np.max(np.abs(
                    x.astype(np.float64) - y.astype(np.float64)))))
        if mode == "bitwise":
            ok = ok and bool(np.array_equal(x, y))
        else:
            ok = ok and bool(np.allclose(x, y, rtol=rtol, atol=atol))
    return ok, worst


def run_rollout(service, candidate_model,
                expected_fingerprint: Optional[str] = None,
                replicas: Optional[int] = None,
                parity: Optional[str] = None,
                grace: Optional[float] = None) -> RolloutReport:
    """Drive one candidate through the full state machine against
    ``service`` (a ``fleet._Service``).  Serialized per service by the
    rollout lock — two concurrent rollouts of one service would race the
    router.  See the module docstring for the phases."""
    from bigdl_tpu.utils import chaos

    mode = (parity if parity is not None else
            str(config.get_property("bigdl.fleet.parityMode") or "bitwise"))
    if mode not in ("bitwise", "allclose", "off"):
        raise ValueError(f"unknown parity mode {mode!r} "
                         "(bitwise | allclose | off)")
    rtol = config.get_float("bigdl.fleet.parityRtol", 1e-5)
    atol = config.get_float("bigdl.fleet.parityAtol", 1e-6)
    shadow_n = config.get_int("bigdl.fleet.shadowSample", 8)
    grace = (grace if grace is not None else
             config.get_float("bigdl.fleet.gracePeriod", 5.0))

    with service._rollout_lock:
        t0 = telemetry.clock_ns()
        report = RolloutReport(
            service=service.name, from_version=service.version,
            to_version=service.peek_next_version(), parity_mode=mode)
        new: List[Any] = []

        def rollback(reason: str, slug: str) -> RolloutReport:
            for r in new:
                # the candidate never entered the router: nothing queued
                # beyond our own shadow mirrors, so a zero-grace retire
                # is clean
                r.retire(0.0)
            report.rolled_back = True
            report.reason = reason
            telemetry.counter("Fleet/rollbacks",
                              labels={"service": service.name,
                                      "reason": slug}).inc()
            incident.record("fleet/rollback", service=service.name,
                            from_version=report.from_version,
                            to_version=report.to_version, cause=slug,
                            reason=reason)
            logger.warning("fleet %s: rollout %s -> %s ROLLED BACK (%s) — "
                           "incumbent keeps serving", service.name,
                           report.from_version, report.to_version, reason)
            return report

        # ---- PREPARE ---------------------------------------------------
        params = candidate_model.parameters()[0]
        report.fingerprint_expected = (
            expected_fingerprint if expected_fingerprint is not None
            else _params_fingerprint(candidate_model))
        # chaos window: the candidate's weights rot AFTER the expected
        # fingerprint was captured — exactly what VERIFY must catch
        chaos.corrupt_candidate(params)
        if elastic.preemption_requested():
            return rollback("preempted before prepare", "preempted")
        n = int(replicas if replicas is not None
                else (len(service.active_replicas()) or 1))
        report.replicas = n
        try:
            for _ in range(n):
                new.append(service.new_replica(candidate_model,
                                               report.to_version))
        except Exception as e:
            return rollback(f"candidate prepare failed: {e!r}", "prepare")
        report.prepare_ms = (telemetry.clock_ns() - t0) / 1e6

        # ---- VERIFY ----------------------------------------------------
        tv = telemetry.clock_ns()
        report.fingerprint_observed = _params_fingerprint(candidate_model)
        report.verify_ms = (telemetry.clock_ns() - tv) / 1e6
        if report.fingerprint_observed != report.fingerprint_expected:
            return rollback(
                f"semantic fingerprint mismatch: expected "
                f"{report.fingerprint_expected}, observed "
                f"{report.fingerprint_observed} — candidate weights "
                "changed between prepare and cutover", "fingerprint")
        if elastic.preemption_requested():
            return rollback("preempted before shadow parity", "preempted")

        # ---- SHADOW ----------------------------------------------------
        ts = telemetry.clock_ns()
        if mode != "off":
            sample = service.shadow_sample(shadow_n)
            for payload, want in sample:
                try:
                    h = new[0].engine.submit(payload)
                    got = h.result(timeout=max(grace, 5.0))
                except Exception as e:
                    return rollback(
                        f"shadow mirror failed on the candidate: {e!r}",
                        "shadow")
                report.parity_checked += 1
                ok, diff = _parity_compare(got, want, mode, rtol, atol)
                report.parity_max_abs_diff = max(
                    report.parity_max_abs_diff, diff)
                if not ok:
                    telemetry.counter(
                        "Fleet/parity_failures",
                        labels={"service": service.name}).inc()
                    return rollback(
                        f"shadow parity violation ({mode}): candidate "
                        f"diverges from the incumbent by up to "
                        f"{diff:.3e} on mirrored live traffic",
                        "parity")
            if report.parity_checked:
                telemetry.counter(
                    "Fleet/shadow_mirrored",
                    labels={"service": service.name}).inc(
                        report.parity_checked)
            else:
                report.notes.append(
                    "no live traffic to mirror — parity vacuously clean")
        report.shadow_ms = (telemetry.clock_ns() - ts) / 1e6
        if elastic.preemption_requested():
            return rollback("preempted before cutover", "preempted")

        # ---- CUTOVER ---------------------------------------------------
        cut_ns = telemetry.clock_ns()
        old = service.cutover(new, candidate_model, report.to_version,
                              cut_ns)
        report.cutover_ns = cut_ns
        report.swap_ms = (cut_ns - t0) / 1e6
        report.promoted = True
        incident.record("fleet/cutover", service=service.name,
                        from_version=report.from_version,
                        to_version=report.to_version,
                        swap_ms=round(report.swap_ms, 2),
                        parity_checked=report.parity_checked)
        telemetry.counter("Fleet/rollouts",
                          labels={"service": service.name}).inc()
        telemetry.gauge("Fleet/swap_ms").set(report.swap_ms)
        logger.info("fleet %s: cutover %s -> %s after %.1f ms (%d "
                    "replica(s), parity %s x%d)", service.name,
                    report.from_version, report.to_version, report.swap_ms,
                    n, mode, report.parity_checked)

        # ---- DRAIN -----------------------------------------------------
        td = telemetry.clock_ns()
        for r in old:
            r.retire(grace)
        report.drain_ms = (telemetry.clock_ns() - td) / 1e6
        return report
