"""The fleet's thread-ownership authority and monitor loop.

Every thread in the fleet control plane is born HERE, through
:meth:`FleetSupervisor.spawn` — the ``unsupervised-thread-in-fleet``
lint rule makes raw ``threading.Thread`` construction anywhere else in
``bigdl_tpu/fleet/`` a finding, so a thread the supervisor cannot see
(cannot drain at fleet stop, cannot report in diagnostics) cannot be
written by accident.  The same discipline the ingest
``_StageSupervisor`` enforces dynamically for pipeline stages is
enforced statically for the control plane.

The monitor loop ticks the fleet every ``bigdl.fleet.pollInterval``
seconds: sweeps request accounting, detects and restarts crashed
replicas, runs autoscale decisions, polls checkpoint directories for
promotable snapshots, and notices fleet-wide preemption.  A tick that
raises is counted and logged but never kills the monitor — supervision
that dies of the fault it supervises is no supervision (same contract
as the ingest supervisor's self-disabling autoscale tick).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, List, Optional

from bigdl_tpu import analysis, telemetry
from bigdl_tpu.utils import config

logger = logging.getLogger("bigdl_tpu")


class FleetSupervisor:
    """Monitor thread + thread factory for one :class:`~bigdl_tpu.fleet.
    Fleet`.  See the module docstring for the contract."""

    def __init__(self, fleet, poll_interval: Optional[float] = None):
        self._fleet = fleet
        self.poll_interval = float(
            poll_interval if poll_interval is not None else
            config.get_float("bigdl.fleet.pollInterval", 0.05))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._spawned: List[threading.Thread] = []   # guarded-by: _lock
        self._lock = analysis.make_lock("fleet.supervisor")
        self.tick_errors = 0
        self.ticks = 0

    def spawn(self, name: str, target: Callable[[], None]
              ) -> threading.Thread:
        """The ONE place fleet threads are constructed: registers the
        thread with the supervisor (fleet stop joins what it spawned;
        diagnostics can enumerate it) and starts it daemonic — a fleet
        must never pin an interpreter open."""
        t = threading.Thread(  # lint: allow(unsupervised-thread-in-fleet)
            target=target, daemon=True, name=name)
        # the allow above IS the registration point the rule demands:
        # every other construction site in this package is a finding
        with self._lock:
            self._spawned.append(t)
        t.start()
        return t

    def threads(self) -> List[threading.Thread]:
        with self._lock:
            return list(self._spawned)

    def start(self) -> "FleetSupervisor":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = self.spawn("fleet-supervisor", self._monitor)
        return self

    def stop(self, timeout: float = 10.0) -> None:
        """Stop the monitor loop (idempotent).  Only the monitor is
        joined here — replica batcher threads belong to their engines
        and drain through the fleet's retire path."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout)

    def alive(self) -> bool:
        t = self._thread
        return bool(t is not None and t.is_alive())

    def _monitor(self) -> None:
        telemetry.name_thread("fleet-supervisor")
        while not self._stop.wait(self.poll_interval):
            self.ticks += 1
            try:
                self._fleet._tick()
            except Exception as e:
                # a failing tick must not kill supervision: count it,
                # log it, keep ticking (the NEXT tick may be the one
                # that restarts the crashed replica)
                self.tick_errors += 1
                telemetry.counter("Fleet/supervisor_errors").inc()
                logger.error("fleet supervisor tick failed: %r", e)
