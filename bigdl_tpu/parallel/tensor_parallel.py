"""Tensor parallelism via GSPMD sharding annotations (Megatron-style).

The reference has NO tensor parallelism (SURVEY §2.12: data parallelism
only) — this is a beyond-reference capability, expressed the TPU-native
way: instead of hand-written collectives, parameters carry
``NamedSharding`` annotations over a ``model`` mesh axis and XLA's SPMD
partitioner inserts the all-reduces (the scaling-book recipe: pick a mesh,
annotate shardings, let XLA insert collectives).

The Megatron split for an attention/MLP block:

- **column-parallel** (first of a pair): weight ``(in, out)`` sharded
  ``P(None, "model")`` — each device holds ``out/n`` columns, outputs stay
  feature-sharded, no communication;
- **row-parallel** (second of a pair): weight ``(in, out)`` sharded
  ``P("model", None)`` — feature-sharded input contracts locally, XLA
  inserts ONE psum per pair on the output.

MultiHeadAttention maps heads onto the column split: wq/wk/wv are
column-parallel (each device computes ``n_head/n`` heads), wo is
row-parallel.

Usage::

    mesh = Engine.create_mesh((n,), ("model",))
    specs = tp_specs(model, mesh=mesh)            # params-pytree of specs
    params = tp_shard_params(model.params, mesh, specs)
    step = jax.jit(train_step)                    # shardings propagate
"""

from __future__ import annotations

from typing import List, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.nn.attention import MultiHeadAttention
from bigdl_tpu.nn.linear import Linear
from bigdl_tpu.nn.module import Container, Module


def tp_specs(module: Module, axis: str = "model",
             mesh: Optional[Mesh] = None):
    """PartitionSpec pytree matching ``module``'s params.

    MultiHeadAttention gets the Megatron head split automatically; a
    ``Linear`` participates when tagged via :func:`column_parallel` /
    :func:`row_parallel`; everything else is replicated (``P()``).

    Pass ``mesh`` to validate the head split sizes up front
    (:func:`head_count_divisible` runs for you).
    """
    reached: List[Module] = []
    specs = _specs(module, axis, reached)
    # every TP participant found by tree walk must have been assigned a
    # split spec — an unknown non-Container composite hiding one would
    # silently replicate it (no memory/compute split, no error)
    participants = [m for m in module.find_modules((MultiHeadAttention,
                                                    Linear))
                    if isinstance(m, MultiHeadAttention)
                    or getattr(m, "_tp", None)]
    missed = [m for m in participants if not any(m is r for r in reached)]
    if missed:
        raise ValueError(
            "tensor-parallel modules are nested inside composites the "
            "spec walk cannot see through: "
            f"{sorted(type(m).__name__ for m in missed)} — restructure "
            "with Sequential/Container (or Bottle, which is supported)")
    if mesh is not None:
        head_count_divisible(module, mesh, axis)
    return specs


def _specs(module: Module, axis: str, reached: List[Module]):
    from bigdl_tpu.nn.structural import Bottle
    if isinstance(module, MultiHeadAttention):
        reached.append(module)
        _reject_flash(module)
        specs = {"wq": P(None, axis), "wk": P(None, axis),
                 "wv": P(None, axis), "wo": P(axis, None)}
        if module.with_bias:
            specs.update({"bq": P(axis), "bk": P(axis), "bv": P(axis),
                          "bo": P()})
        return specs
    if isinstance(module, Linear):
        tp = getattr(module, "_tp", None)
        if tp == "column":
            reached.append(module)
            s = {"weight": P(None, axis)}
            if module.with_bias:
                s["bias"] = P(axis)
            return s
        if tp == "row":
            reached.append(module)
            s = {"weight": P(axis, None)}
            if module.with_bias:
                s["bias"] = P()
            return s
    if isinstance(module, Bottle):
        return [_specs(module.module, axis, reached)]
    if isinstance(module, Container):
        return [_specs(c, axis, reached) for c in module.children]
    # replicated leaf: one spec per param array
    module._ensure_init()
    p = module._params if module._params is not None else {}
    return jax.tree_util.tree_map(lambda _: P(), p)


def column_parallel(linear: Linear) -> Linear:
    """Tag a Linear as the column-split half of a Megatron pair (its
    activation output becomes feature-sharded)."""
    linear._tp = "column"
    return linear


def row_parallel(linear: Linear) -> Linear:
    """Tag a Linear as the row-split half (consumes a feature-sharded
    activation; XLA inserts the pair's single psum here)."""
    linear._tp = "row"
    return linear


def zero1_slot_spec(shape, spec: P, dp: int, axis: str = "data") -> P:
    """Optimizer-slot spec for a parameter with tensor-parallel ``spec``:
    additionally sharded over the data axis (ZeRO-1).

    The tp split already divides a weight ``1/tp`` over ``model``; its
    Adam/momentum slots can further split ``1/dp`` over ``data`` because
    the optimizer update is elementwise — each data replica only needs the
    slot slice for the parameter shard it updates, and XLA's partitioner
    derives the reduce-scatter/all-gather around the update from the
    sharding annotations alone (the same ZeRO-1 the shard_map dp step
    implements explicitly with psum_scatter).  The first dimension that is
    unsharded in ``spec`` and divisible by ``dp`` carries the data axis;
    a parameter with no such dimension (tiny biases) keeps ``spec`` —
    replicating a vector costs nothing worth a ragged-shard lowering."""
    entries = list(spec) + [None] * (len(shape) - len(spec))
    for i, (dim, e) in enumerate(zip(shape, entries)):
        if e is None and dim % dp == 0 and dim >= dp:
            entries[i] = axis
            return P(*entries)
    return spec


def zero1_slot_specs(params, specs, dp: int, axis: str = "data"):
    """Per-parameter slot specs (:func:`zero1_slot_spec` over the tree)."""
    if dp <= 1:
        return specs
    return jax.tree_util.tree_map(
        lambda x, s: zero1_slot_spec(x.shape, s, dp, axis), params, specs)


def tp_shard_params(params, mesh: Mesh, specs):
    """Place a params pytree on the mesh with the given spec pytree —
    weights are physically split 1/n per device along the model axis."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)


def _reject_flash(mha: MultiHeadAttention) -> None:
    if mha.flash:
        raise ValueError("flash kernel is incompatible with the "
                         "GSPMD head split (pallas kernels do not "
                         "partition); use the default attention path")


def head_count_divisible(module: Module, mesh: Mesh,
                         axis: str = "model") -> None:
    """Validate the Megatron head split: every MHA's head count must divide
    by the model-axis size (each device computes whole heads)."""
    n = mesh.shape[axis]
    for m in module.find_modules(MultiHeadAttention):
        if m.n_head % n != 0:
            raise ValueError(
                f"tensor parallelism needs n_head divisible by the "
                f"'{axis}' axis size: {m.n_head} % {n} != 0")
        _reject_flash(m)
