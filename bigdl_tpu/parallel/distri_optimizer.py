"""DistriOptimizer: the distributed synchronous-SGD trainer.

Reference equivalent: ``optim/DistriOptimizer.scala:89-330`` — per-iteration:
weight all-gather, per-partition forward/backward, gradient scatter,
partition-sharded optimizer update, weight republish — over Spark's
BlockManager (``parameters/AllReduceParameter.scala:67-295``).

TPU-native redesign: the whole per-iteration exchange is ONE jitted
``shard_map`` over ``Engine.default_mesh()``'s ``data`` axis:

    per-shard forward/backward  (local minibatch, replicated params)
    → ``psum_scatter``          gradient reduce-scatter over ICI
    → sharded optimizer update  (each device updates its 1/N parameter slice
                                 and owns 1/N of the optimizer slots: ZeRO-1,
                                 the reference's partition-sharded update)
    → ``all_gather``            weight reassembly

There are no per-iteration host round-trips: params stay device-resident as
one replicated flat vector, slots stay sharded across the mesh, and the
driver only reads back the scalar loss.  fp16 wire compression maps to an
optional bf16 cast on the reduce-scatter (``compression='bf16'``).

Straggler mitigation (reference ``:192-216,302-330``) is structurally N/A:
XLA collectives over ICI are bulk-synchronous with no partial participation;
the API knob on :class:`Optimizer` is kept inert for parity.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.engine import Engine
from bigdl_tpu.dataset.dataset import ShardedDataSet
from bigdl_tpu.nn.module import Criterion, Module
from bigdl_tpu.optim.optimizer import (Optimizer, mixed_precision_forward,
                                       regularization_penalty)
from bigdl_tpu.parallel.all_reduce import AllReduceParameter

logger = logging.getLogger("bigdl_tpu")


def _pmean_float(tree, axis: str):
    """Average float leaves across the axis (keeps BatchNorm running stats
    consistent between replicas); non-float leaves pass through (they evolve
    identically on every shard)."""
    def f(x):
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            return lax.pmean(x, axis)
        return x
    return jax.tree_util.tree_map(f, tree)


class DistriOptimizer(Optimizer):
    """Data-parallel trainer over a device mesh
    (reference ``optim/DistriOptimizer.scala:689``).

    ``dataset`` must be a :class:`ShardedDataSet` whose ``partition_num``
    equals the mesh's ``data``-axis size (the reference enforces
    partition == node at ``DistriOptimizer.scala:492-494``).
    """

    def __init__(self, model: Module, dataset: ShardedDataSet,
                 criterion: Criterion, mesh: Optional[Mesh] = None,
                 compression: Optional[str] = None):
        super().__init__(model, dataset, criterion)
        self._mesh = mesh
        self.compression = compression
        self._arp: Optional[AllReduceParameter] = None

    @property
    def mesh(self) -> Mesh:
        if self._mesh is None:
            self._mesh = Engine.default_mesh()
        return self._mesh

    def set_mesh(self, mesh: Mesh) -> "DistriOptimizer":
        self._mesh = mesh
        self._step_fn = None
        return self

    # ---- the fused sharded step ----------------------------------------

    @property
    def seq_axis(self) -> Optional[str]:
        """Sequence-parallel axis: present when the mesh declares a ``seq``
        dimension (the long-context dp x sp layout)."""
        return "seq" if "seq" in self.mesh.shape else None

    def _build_step(self, arp: AllReduceParameter):
        from bigdl_tpu.parallel.all_reduce import shard_map

        model, criterion, optim = self.model, self.criterion, self.optim_method
        mesh, axis = self.mesh, "data"
        seq_axis = self.seq_axis
        n = mesh.shape[axis] * (mesh.shape[seq_axis] if seq_axis else 1)

        precision = self.precision

        def shard_step(flat_params, slots, mstate, inputs, targets, hyper, rng):
            # distinct dropout masks per shard, like the reference's
            # independently-seeded model replicas
            rng = jax.random.fold_in(rng, lax.axis_index(axis))
            if seq_axis:
                rng = jax.random.fold_in(rng, lax.axis_index(seq_axis))

            def loss_fn(flat):
                p = arp.unflatten(flat)
                out, new_mstate = mixed_precision_forward(
                    model, p, inputs, mstate, precision, True, rng)
                loss = criterion.apply(out, targets)
                loss = loss + regularization_penalty(model, p)
                return loss, new_mstate

            (loss, new_mstate), flat_grads = jax.value_and_grad(
                loss_fn, has_aux=True)(flat_params)

            if seq_axis:
                # sequence shards each saw a chunk of every sequence: their
                # gradient contributions sum (ring attention's backward is
                # already chunk-local)
                flat_grads = lax.psum(flat_grads, seq_axis)
            # reduce-scatter: own gradient slice, summed over shards
            grad_shard = arp.reduce_scatter_gradients(flat_grads, axis) / n
            # ZeRO-1: update only this device's parameter slice + slots
            param_shard = arp.local_shard(flat_params, axis)
            new_shard, new_slots = optim.pure_update(grad_shard, param_shard,
                                                     slots, hyper)
            # all-gather the updated weights for the next forward
            new_flat = arp.all_gather_weights(new_shard, axis)

            loss = lax.pmean(loss, axis)
            new_mstate = _pmean_float(new_mstate, axis)
            if seq_axis:
                loss = lax.pmean(loss, seq_axis)
                new_mstate = _pmean_float(new_mstate, seq_axis)
            return new_flat, new_slots, new_mstate, loss

        pspec_rep = P()
        # batch over data; with a seq axis, time (dim 1) over seq
        pspec_batch = P(axis, seq_axis) if seq_axis else P(axis)
        # slots are sharded over the data axis only (ZeRO-1); replicated
        # across seq shards
        pspec_slots = P(axis)
        sharded = shard_map(
            shard_step, mesh=mesh,
            in_specs=(pspec_rep,                          # flat params
                      pspec_slots,                        # slot shards
                      pspec_rep,                          # module state
                      pspec_batch, pspec_batch,           # inputs, targets
                      pspec_rep, pspec_rep),              # hyper, rng
            out_specs=(pspec_rep, pspec_slots, pspec_rep, pspec_rep),
            check_rep=False)
        return jax.jit(sharded, donate_argnums=(0, 1, 2))

    # ---- driver loop ----------------------------------------------------

    def _optimize(self) -> Module:
        model, mesh = self.model, self.mesh
        axis_size = mesh.shape["data"]
        if self.dataset.partition_num != axis_size:
            raise ValueError(
                f"dataset has {self.dataset.partition_num} partitions but the "
                f"mesh 'data' axis has {axis_size} devices — they must match "
                "(reference DistriOptimizer.scala:492)")

        model.training()
        model._ensure_init()
        if self.seq_axis:
            self._wire_sequence_parallel(model)

        arp = AllReduceParameter(model.params, axis_size, self.compression)
        self._arp = arp
        carry = {
            "flat": jax.device_put(arp.flatten(model.params),
                                   NamedSharding(mesh, P())),
            # slots live sharded across the mesh: each device owns 1/N (ZeRO-1)
            "slots": jax.device_put(self._flat_slots(arp),
                                    NamedSharding(mesh, P("data"))),
            "mstate": jax.device_put(model.state, NamedSharding(mesh, P())),
        }
        self.optim_method.state.setdefault("epoch", 1)

        if self._step_fn is None:
            self._step_fn = self._build_step(arp)

        if self.seq_axis:
            # time (dim 1) sharded over seq: per-timestep targets required
            batch_sharding = NamedSharding(mesh, P("data", "seq"))
            seq_size = mesh.shape["seq"]

            max_seq = getattr(self, "_max_seq_len", None)

            def _check(x):
                x = np.asarray(x)
                if x.ndim < 2 or x.shape[1] % seq_size != 0:
                    raise ValueError(
                        "sequence-parallel training needs (N, T, ...) inputs "
                        "and (N, T, ...) per-timestep targets with T "
                        f"divisible by the seq axis size {seq_size} "
                        f"(got shape {x.shape})")
                if max_seq is not None and x.shape[1] > max_seq:
                    raise ValueError(
                        f"sequence length {x.shape[1]} exceeds a module's "
                        f"position capacity {max_seq} — sharded offsets "
                        "would silently clamp; raise max_len")
                return x
        else:
            batch_sharding = NamedSharding(mesh, P("data"))
            _check = None
        it = {"shards": None}

        def reset_epoch():
            self.dataset.shuffle()
            it["shards"] = [self.dataset.shard_data(p, train=True)
                            for p in range(self.dataset.partition_num)]

        def fetch_batch():
            return _global_batch(it["shards"], batch_sharding, check=_check)

        def run_step(inputs, targets, hyper, rng):
            (carry["flat"], carry["slots"], carry["mstate"],
             loss) = self._step_fn(carry["flat"], carry["slots"],
                                   carry["mstate"], inputs, targets,
                                   hyper, rng)
            return loss

        def publish():
            # slots leave the device in the same per-parameter pytree format
            # every host-side consumer (checkpoint resume, OptimMethod.update,
            # a later LocalOptimizer) expects
            self._sharded_slots = carry["slots"]
            unflat_slots = jax.tree_util.tree_map(arp.unflatten,
                                                  carry["slots"])
            self._publish(arp.unflatten(carry["flat"]), unflat_slots,
                          carry["mstate"])

        reset_epoch()
        self._drive(fetch_batch, run_step, reset_epoch, publish,
                    epoch_size=self.dataset.size())
        return model

    def _wire_sequence_parallel(self, module) -> None:
        """Point every MultiHeadAttention at the mesh's seq axis.  The ring
        path only engages while that axis is bound (inside the shard_map
        training step), so validation/predict forwards — which run outside
        it — keep full-sequence attention.

        Other time-mixing modules have no sequence-parallel path: on a
        time-sharded input a recurrent unroll would restart its hidden
        state at every chunk edge and a temporal conv / time reverse would
        see artificial boundaries — silently wrong, so they are rejected.
        """
        import bigdl_tpu.nn as nn
        time_mixing = (nn.Recurrent, nn.BiRecurrent, nn.TemporalConvolution,
                       nn.Reverse)
        offenders = [type(m).__name__ for m in module.find_modules(time_mixing)]
        if offenders:
            raise ValueError(
                "sequence-parallel training (mesh with a 'seq' axis) shards "
                "the time dimension, but these modules mix information "
                f"across time with no ring path: {sorted(set(offenders))}; "
                "train them on a ('data',)-only mesh")
        # duck-typed: MultiHeadAttention (ring path), PositionalEncoding
        # (chunk offset), and any future seq-aware module
        self._max_seq_len = None
        for m in module.modules():
            if hasattr(m, "set_sequence_parallel"):
                m.set_sequence_parallel(self.seq_axis)
            cap = getattr(m, "max_seq_len", None)
            if cap is not None:
                self._max_seq_len = (cap if self._max_seq_len is None
                                     else min(self._max_seq_len, cap))

    def _eval_mesh(self):
        """Validation forwards run sharded over the training mesh (the
        reference evaluates inside the cluster, ``optim/Evaluator.scala``)."""
        return self.mesh

    def _flat_slots(self, arp: AllReduceParameter):
        """Optimizer slots as flat padded vectors.  Fresh runs start from
        zeros; a resumed/reused OptimMethod carries slots in the canonical
        per-parameter pytree format, which is re-flattened here."""
        cached = self.optim_method._slots
        if cached is None:
            return self.optim_method.init_slots(
                jnp.zeros((arp.padded_size,), arp.dtype))
        outer = jax.tree_util.tree_structure(
            self.optim_method.init_slots(jnp.zeros(())))
        subtrees = outer.flatten_up_to(cached)
        return jax.tree_util.tree_unflatten(
            outer, [arp.flatten(s) for s in subtrees])


def _global_batch(shard_iters, batch_sharding, check=None):
    """Pull one minibatch per shard, concatenate host-side into the global
    batch, and place it sharded over the mesh's data axis (each device gets
    exactly its shard's records — the reference's locality-preserving zip,
    ``ZippedPartitionsWithLocalityRDD.scala:28``).  ``check`` optionally
    validates each leaf (sequence-parallel shape requirements)."""
    batches = [next(it) for it in shard_iters]
    inputs = _cat([b.get_input() for b in batches])
    targets = _cat([b.get_target() for b in batches])
    bsz = sum(b.size() for b in batches)
    if check is not None:
        inputs = jax.tree_util.tree_map(check, inputs)
        targets = jax.tree_util.tree_map(check, targets)
    inputs = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, batch_sharding), inputs)
    targets = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, batch_sharding), targets)
    return inputs, targets, bsz


def _cat(parts):
    """Concatenate per-shard activities (arrays or nested lists of arrays)
    along the batch axis."""
    first = parts[0]
    if isinstance(first, (list, tuple)):
        return type(first)(_cat([p[i] for p in parts])
                           for i in range(len(first)))
    return np.concatenate([np.asarray(p) for p in parts], axis=0)
