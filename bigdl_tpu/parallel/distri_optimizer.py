"""DistriOptimizer: the distributed synchronous-SGD trainer.

Reference equivalent: ``optim/DistriOptimizer.scala:89-330`` — per-iteration:
weight all-gather, per-partition forward/backward, gradient scatter,
partition-sharded optimizer update, weight republish — over Spark's
BlockManager (``parameters/AllReduceParameter.scala:67-295``).

TPU-native redesign: the whole per-iteration exchange is ONE jitted
``shard_map`` over ``Engine.default_mesh()``'s ``data`` axis:

    per-shard forward/backward  (local minibatch, replicated params)
    → ``psum_scatter``          gradient reduce-scatter over ICI
    → sharded optimizer update  (each device updates its 1/N parameter slice
                                 and owns 1/N of the optimizer slots: ZeRO-1,
                                 the reference's partition-sharded update)
    → ``all_gather``            weight reassembly

There are no per-iteration host round-trips: params stay device-resident as
one replicated flat vector, slots stay sharded across the mesh, and the
driver only reads back the scalar loss.  fp16 wire compression maps to an
optional bf16 cast on the reduce-scatter (``compression='bf16'``).

Multi-host: under ``Engine.init_distributed`` every host process runs this
same driver loop (multi-controller SPMD).  Each process feeds ONLY the
data partitions its mesh positions own (:func:`local_data_partitions`;
the dataset is constructed per process with
``ShardedDataSet(..., local_partitions=...)``) and the global batch is
assembled with ``jax.make_array_from_process_local_data`` — the
reference's executor-local partition caching + locality zip
(``ZippedPartitionsWithLocalityRDD.scala:28-56``) without a driver-side
materialization.  Proven by ``tests/test_multihost.py`` (2 OS processes x
4 virtual devices == the single-process 8-device run).

Straggler mitigation (reference ``:192-216,302-330``) is structurally N/A:
XLA collectives over ICI are bulk-synchronous with no partial participation;
the API knob on :class:`Optimizer` is kept inert for parity.

Real-data ingest: feed the dataset through
:class:`~bigdl_tpu.dataset.ingest.StreamingIngest` (the stage-pipelined
decode/assemble engine) and the driver's ``Engine.BatchPrefetcher``
transfer-ahead stage keeps ``bigdl.ingest.batchesInFlight`` uploads in
flight — ``fetch_batch`` issues the ``make_array_from_process_local_data``
transfer, the transfer thread blocks it device-resident while the next
fetch's upload is already on the link, and the step consumes only
pre-transferred batches.  Epoch rollover/reshuffle stays owned by the
fetch producer and the ingest engine commits RNG draws on consumption, so
the pipelining changes latency, never the batch sequence.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu import integrity as _integrity
from bigdl_tpu.engine import Engine
from bigdl_tpu.dataset.dataset import ShardedDataSet
from bigdl_tpu.nn.module import Criterion, Module
from bigdl_tpu.optim.optimizer import (Optimizer, all_finite,
                                       mixed_precision_forward,
                                       moe_aux_penalty,
                                       regularization_penalty, select_tree)
from bigdl_tpu.parallel.all_reduce import (AllReduceParameter, axis_mean,
                                           axis_min, axis_sum,
                                           gather_fingerprints, pmean_floats)
from bigdl_tpu.utils import chaos as _chaos

logger = logging.getLogger("bigdl_tpu")


def _owned_coords_per_axis(mesh: Mesh):
    """{axis_name: sorted owned coordinates} for this process's devices,
    plus the owned-position count (for rectangularity checks)."""
    pid = jax.process_index()
    devs = np.asarray(mesh.devices)
    owned = [tuple(int(c) for c in coord)
             for coord in np.ndindex(devs.shape)
             if devs[coord].process_index == pid]
    per_axis = {a: sorted({c[i] for c in owned})
                for i, a in enumerate(mesh.axis_names)}
    return per_axis, len(owned)


def local_data_partitions(mesh: Mesh, axis: str = "data"):
    """Data-axis partition ids whose devices this process can address.

    In a multi-host job (``Engine.init_distributed``) each process owns
    ``jax.local_devices()``; the data-axis coordinate of each owned mesh
    position names a dataset partition this process must feed — the
    reference's partition→node locality (one Spark partition cached on
    the executor that trains it, ``ZippedPartitionsWithLocalityRDD.scala``
    + ``AllReduceParameter.scala:87-92`` rank-from-partition-id).
    Single-process this is simply ``range(axis_size)``."""
    return _owned_coords_per_axis(mesh)[0][axis]


def _local_axis_chunks(mesh: Mesh, axis: str):
    """Sorted owned coordinates along ``axis``, with a rectangularity
    check: per-process batch assembly slices the global batch as (owned
    data rows) x (owned seq columns), which is only well-defined when the
    owned device set is that cartesian product."""
    per_axis, n_owned = _owned_coords_per_axis(mesh)
    expect = 1
    for a in mesh.axis_names:
        expect *= len(per_axis[a])
    if n_owned != expect:
        raise ValueError(
            f"this process's mesh positions are not rectangular over axes "
            f"{mesh.axis_names} — per-process batch feeding cannot slice "
            "the global batch; arrange the mesh so each process owns a "
            "full block")
    return per_axis[axis]


def map_over_slots(optim_method, fn, slots, per_param_tree):
    """Apply ``fn(slot_leaf_tree_element, per_param_element)`` across
    every slot family (Adam's m/v, momentum's v, …): slot pytrees are
    {family: params-shaped tree}, so the per-parameter spec tree is
    zipped against each family's subtree.  Shared by the GSPMD dp x tp
    step and the pipeline trainer's ZeRO-1 slot placement."""
    outer = jax.tree_util.tree_structure(
        optim_method.init_slots(jnp.zeros(())))
    subtrees = outer.flatten_up_to(slots)
    return jax.tree_util.tree_unflatten(
        outer,
        [jax.tree_util.tree_map(fn, st, per_param_tree)
         for st in subtrees])


# the BatchNorm-state averaging helper now lives with the other declared
# collectives in all_reduce.py (pmean_floats); this alias keeps the old
# import path working
_pmean_float = pmean_floats


def _leaf_bucket_groups(params, n_buckets: int):
    """Partition the parameter leaves (flatten order) into at most
    ``n_buckets`` contiguous, size-balanced index groups — the GSPMD
    counterpart of :meth:`AllReduceParameter.bucket_edges`, operating on
    whole leaves because the partitioner owns each leaf's sharding.  A
    group closes once its leaves reach the next even-split boundary of
    the total element count."""
    leaves = jax.tree_util.tree_leaves(params)
    sizes = [int(np.prod(np.shape(x))) for x in leaves]
    total = sum(sizes)
    n = max(1, min(int(n_buckets), len(leaves)))
    groups, cur, acc = [], [], 0
    for i, s in enumerate(sizes):
        cur.append(i)
        acc += s
        if len(groups) < n - 1 and acc >= (len(groups) + 1) * total / n:
            groups.append(cur)
            cur = []
    if cur:
        groups.append(cur)
    return groups


def _bucketed_leaf_update(optim_method, groups, grads, params, slots, hyper):
    """Run the optimizer update as one independent chain per leaf group.
    Each group's gradient leaves pass through a ``lax.optimization_barrier``
    so XLA treats the group as its own scheduling unit (its
    partitioner-inserted gradient reductions can overlap other groups'
    update compute); the update itself is the same elementwise
    ``pure_update`` on the group's sub-pytree, so numerics are identical
    to the whole-tree call."""
    p_leaves, pdef = jax.tree_util.tree_flatten(params)
    g_leaves = pdef.flatten_up_to(grads)
    outer = jax.tree_util.tree_structure(
        optim_method.init_slots(jnp.zeros(())))
    fam_leaves = [pdef.flatten_up_to(f) for f in outer.flatten_up_to(slots)]
    new_p = [None] * len(p_leaves)
    new_f = [[None] * len(p_leaves) for _ in fam_leaves]
    for idxs in groups:
        gg = list(lax.optimization_barrier(
            tuple(g_leaves[i] for i in idxs)))
        sg = jax.tree_util.tree_unflatten(
            outer, [[fl[i] for i in idxs] for fl in fam_leaves])
        pp, ss = optim_method.pure_update(
            gg, [p_leaves[i] for i in idxs], sg, hyper)
        ss_f = outer.flatten_up_to(ss)
        for j, i in enumerate(idxs):
            new_p[i] = pp[j]
            for fi in range(len(fam_leaves)):
                new_f[fi][i] = ss_f[fi][j]
    new_params = jax.tree_util.tree_unflatten(pdef, new_p)
    new_slots = jax.tree_util.tree_unflatten(
        outer, [jax.tree_util.tree_unflatten(pdef, nf) for nf in new_f])
    return new_params, new_slots


class DistriOptimizer(Optimizer):
    """Data-parallel trainer over a device mesh
    (reference ``optim/DistriOptimizer.scala:689``).

    ``dataset`` must be a :class:`ShardedDataSet` whose ``partition_num``
    equals the mesh's ``data``-axis size (the reference enforces
    partition == node at ``DistriOptimizer.scala:492-494``).
    """

    def __init__(self, model: Module, dataset: ShardedDataSet,
                 criterion: Criterion, mesh: Optional[Mesh] = None,
                 compression: Optional[str] = None):
        super().__init__(model, dataset, criterion)
        self._mesh = mesh
        self.compression = compression
        self._arp: Optional[AllReduceParameter] = None

    @property
    def mesh(self) -> Mesh:
        if self._mesh is None:
            self._mesh = Engine.default_mesh()
        return self._mesh

    def set_mesh(self, mesh: Mesh) -> "DistriOptimizer":
        self._mesh = mesh
        self._step_fn = None
        return self

    def _topology_meta(self):
        """Saving topology for snapshot manifests: the mesh axes, the
        ZeRO-1 slot axis, and which fused step owns the layout — what a
        restore onto a different device count needs in order to reshard
        (or to refuse with the mismatch named)."""
        from bigdl_tpu.utils import elastic
        return elastic.describe_topology(
            self.mesh, step="gspmd" if self.model_axis else "shard_map",
            slot_axis="data")

    # ---- the fused sharded step ----------------------------------------

    @property
    def seq_axis(self) -> Optional[str]:
        """Sequence-parallel axis: present when the mesh declares a ``seq``
        dimension (the long-context dp x sp layout)."""
        return "seq" if "seq" in self.mesh.shape else None

    @property
    def expert_axis(self) -> Optional[str]:
        """Expert-parallel axis: present when the mesh declares an
        ``expert`` dimension (the dp x ep MoE layout — tokens co-shard
        over it, MixtureOfExperts layers dispatch with all_to_all)."""
        return "expert" if "expert" in self.mesh.shape else None

    @property
    def model_axis(self) -> Optional[str]:
        """Tensor-parallel axis: present when the mesh declares a
        ``model`` dimension (the dp x tp Megatron layout — handled by the
        GSPMD step, not the collective shard_map step)."""
        return "model" if "model" in self.mesh.shape else None

    def _build_step(self, arp: AllReduceParameter):
        from bigdl_tpu.parallel.all_reduce import shard_map

        model, criterion, optim = self.model, self.criterion, self.optim_method
        mesh, axis = self.mesh, "data"
        seq_axis = self.seq_axis
        expert_axis = self.expert_axis
        n = (mesh.shape[axis] *
             (mesh.shape[seq_axis] if seq_axis else 1) *
             (mesh.shape[expert_axis] if expert_axis else 1))

        precision = self.precision
        aux_weight = self.moe_aux_weight
        from bigdl_tpu.utils import config
        guard = config.get_bool("bigdl.divergence.guard", True)
        every_n = config.get_int("bigdl.integrity.everyN", 0)
        fp_seed = config.get_int("bigdl.integrity.seed",
                                 _integrity.DEFAULT_SEED)
        # chaos: in-step replica desync — at tick ``desync_at`` replica
        # ``desync_rep``'s updated parameter copy drifts AFTER the update
        # and BEFORE the output fingerprint (build-time constants; (0, 0)
        # = disarmed, and tick 0 never occurs)
        desync_at, desync_rep = _chaos.desync_replica()
        # audit fault injection: duplicate the weight all-gather so the
        # step's program breaks its declared all-gather op ceiling
        extra_ag = config.get_bool("bigdl.chaos.extraAllGather", False)
        # the latency-hiding overlap schedule: the ZeRO-1 exchange runs as
        # N independent per-bucket reduce-scatter -> update -> all-gather
        # chains (same wire bytes, element-identical numerics) so XLA's
        # scheduler can overlap bucket k's collective with bucket k±1's
        # compute; bigdl.parallel.overlap=false keeps the monolithic
        # baseline program
        overlap = config.get_bool("bigdl.parallel.overlap", True)
        edges = (arp.bucket_edges(
                     config.get_int("bigdl.parallel.overlapBuckets", 4))
                 if overlap else [(0, arp.shard_size)])
        # audit fault injection: bucket k's reduce-scatter silently
        # replaced by the device's own unreduced rows — the
        # missing-per-bucket-collective case the auditor's min_ops floor
        # exists to catch
        drop_bucket = config.get_property("bigdl.chaos.dropBucketCollective",
                                          None)
        drop_bucket = (int(drop_bucket) % len(edges)
                       if drop_bucket not in (None, "", False) else None)
        from bigdl_tpu import telemetry
        telemetry.REGISTRY.gauge(
            "Parallel/overlap_buckets", summary=True,
            help="per-step collective buckets (1 = monolithic schedule)"
        ).set(float(len(edges)))

        def shard_step(flat_params, slots, mstate, inputs, targets, hyper,
                       rng, fpc=None, tick=None):
            # distinct dropout masks per shard, like the reference's
            # independently-seeded model replicas
            rng = jax.random.fold_in(rng, lax.axis_index(axis))
            for extra in (seq_axis, expert_axis):
                if extra:
                    rng = jax.random.fold_in(rng, lax.axis_index(extra))

            def loss_fn(flat):
                p = arp.unflatten(flat)
                out, new_mstate = mixed_precision_forward(
                    model, p, inputs, mstate, precision, True, rng)
                loss = criterion.apply(out, targets)
                loss = loss + regularization_penalty(model, p)
                loss = loss + moe_aux_penalty(model, new_mstate, aux_weight)
                return loss, new_mstate

            (loss, new_mstate), flat_grads = jax.value_and_grad(
                loss_fn, has_aux=True)(flat_params)

            if seq_axis:
                # sequence shards each saw a chunk of every sequence: their
                # gradient contributions sum (ring attention's backward is
                # already chunk-local)
                flat_grads = axis_sum(flat_grads, seq_axis)
            if expert_axis:
                # expert shards saw disjoint tokens AND ran disjoint expert
                # blocks: contributions sum over the axis
                flat_grads = axis_sum(flat_grads, expert_axis)
            aux = {}
            intact = None
            if fpc is not None:
                # training-state integrity, per replica: fingerprint the
                # INPUT copies — each device hashes its OWN HBM copy of
                # the replicated parameter vector and its own ZeRO-1 slot
                # shard — all-gather the parameter fingerprints into the
                # agreement table, and check continuity against this
                # replica's carry row from the previous step.  The
                # combined verdict latches and freezes the update below,
                # so a corrupted replica can never contaminate healthy
                # state: the run freezes (restorable/healable) instead of
                # training on rotten weights.
                fpc_row = fpc[0]
                fp_p_in = _integrity.fingerprint_flat(flat_params, fp_seed)
                fp_s_in = _integrity.fingerprint_tree(
                    slots, fp_seed + _integrity.SLOT_SEED_OFF)
                fps_table = gather_fingerprints(fp_p_in, axis)
                agree_ok = jnp.all(fps_table == fps_table[0])
                cont_ok, latch, bad_iter = _integrity.continuity_check(
                    fpc_row, fp_p_in, fp_s_in, tick, extra_ok=agree_ok)
                # the freeze verdict must be GLOBAL (pmin over every mesh
                # axis): one latched replica freezing alone would
                # silently fork the model
                intact = axis_min((latch == 0).astype(jnp.int32), axis)
                for extra in (seq_axis, expert_axis):
                    if extra:
                        intact = axis_min(intact, extra)
                intact = intact.astype(bool)
            if overlap:
                # bucketed schedule: the padded flat vector viewed as an
                # (n_shards, shard_size) matrix, each bucket a contiguous
                # column range — per bucket, reduce-scatter its columns,
                # update this device's piece, and all-gather it back.
                # The chains share no data flow until the divergence
                # verdict, so the scheduler is free to run bucket k's
                # collective under bucket k±1's update compute; summed
                # over buckets the wire bytes equal the monolithic
                # schedule and every element sees the same reduction
                # order (parity is exact, not approximate).
                gmat = flat_grads.reshape(arp.n_shards, arp.shard_size)
                param_row = arp.local_shard(flat_params, axis)
                grad_b, new_p, new_s = [], [], []
                for k, (a, b) in enumerate(edges):
                    if drop_bucket == k:
                        # chaos: this bucket's collective is GONE — the
                        # device's own unreduced gradient rows stand in
                        g_k = jnp.take(gmat[:, a:b], lax.axis_index(axis),
                                       axis=0).astype(arp.dtype) / n
                    else:
                        g_k = arp.reduce_scatter_bucket(gmat[:, a:b],
                                                        axis) / n
                    s_k = jax.tree_util.tree_map(lambda v: v[a:b], slots)
                    p_k, ns_k = optim.pure_update(g_k, param_row[a:b],
                                                  s_k, hyper)
                    grad_b.append(g_k)
                    new_p.append(p_k)
                    new_s.append(ns_k)
                okf = None
                if guard:
                    # the verdict stays GLOBAL over the whole vector: all
                    # buckets' gradients feed one pmin (the one sync point
                    # the baseline schedule has too).  The pmin is widened
                    # to a stacked [ok, nf] pair so the first-non-finite
                    # leaf index rides the same collective for the
                    # driver's diagnosed divergence line.
                    okf, nf = _integrity.first_nonfinite(loss, *grad_b)
                    verdict = axis_min(
                        jnp.stack([okf.astype(jnp.int32), nf]), axis)
                    for extra in (seq_axis, expert_axis):
                        if extra:
                            verdict = axis_min(verdict, extra)
                    okf, nf = verdict[0].astype(bool), verdict[1]
                    aux["nf"] = nf
                ok = okf
                if intact is not None:
                    ok = (intact if ok is None
                          else jnp.logical_and(ok, intact))
                if ok is not None:
                    new_p = [select_tree(ok, p_k, param_row[a:b])
                             for p_k, (a, b) in zip(new_p, edges)]
                    new_s = [select_tree(
                                 ok, s_k,
                                 jax.tree_util.tree_map(
                                     lambda v, a=a, b=b: v[a:b], slots))
                             for s_k, (a, b) in zip(new_s, edges)]
                    new_mstate = select_tree(ok, new_mstate, mstate)
                if guard:
                    # only the FINITENESS verdict poisons the loss (an
                    # integrity freeze reports through aux, not NaN)
                    loss = jnp.where(okf, loss, jnp.nan)
                if fpc is not None:
                    g_sq = _integrity.sq_norm(grad_b)
                # per-bucket gathers: each depends only on its own
                # bucket's selected shard (plus the shared verdict)
                blocks = [arp.all_gather_bucket(p_k, axis) for p_k in new_p]
                if extra_ag:
                    blocks[0] = (blocks[0] + arp.all_gather_bucket(
                        new_p[0], axis)) / 2
                new_flat = jnp.concatenate(blocks, axis=1).reshape(-1)
                new_slots = (jax.tree_util.tree_map(
                                 lambda *xs: jnp.concatenate(xs), *new_s)
                             if jax.tree_util.tree_leaves(slots)
                             else slots)
            else:
                # monolithic baseline: one reduce-scatter, one update,
                # one all-gather
                grad_shard = arp.reduce_scatter_gradients(flat_grads,
                                                          axis) / n
                # ZeRO-1: update only this device's parameter slice + slots
                param_shard = arp.local_shard(flat_params, axis)
                new_shard, new_slots = optim.pure_update(
                    grad_shard, param_shard, slots, hyper)
                okf = None
                if guard:
                    # divergence guard: non-finite loss/grad → every shard
                    # keeps its pre-step slice.  The verdict must be GLOBAL
                    # (pmin over the data axis): each device only sees 1/N
                    # of the gradient vector, and replicas applying
                    # different verdicts would silently fork the model.
                    # The pmin is widened to a stacked [ok, nf] pair so
                    # the first-non-finite leaf index rides the same
                    # collective for the diagnosed divergence line.
                    okf, nf = _integrity.first_nonfinite(loss, grad_shard)
                    verdict = axis_min(
                        jnp.stack([okf.astype(jnp.int32), nf]), axis)
                    for extra in (seq_axis, expert_axis):
                        if extra:   # seq/expert replicas must agree too
                            verdict = axis_min(verdict, extra)
                    okf, nf = verdict[0].astype(bool), verdict[1]
                    aux["nf"] = nf
                ok = okf
                if intact is not None:
                    ok = (intact if ok is None
                          else jnp.logical_and(ok, intact))
                if ok is not None:
                    new_shard = select_tree(ok, new_shard, param_shard)
                    new_slots = select_tree(ok, new_slots, slots)
                    new_mstate = select_tree(ok, new_mstate, mstate)
                if guard:
                    # a skipped step must report non-finite to the
                    # driver's bad-step counter even when only the GRADS
                    # overflowed; an integrity freeze does NOT poison the
                    # loss — its verdict reaches the driver through aux
                    loss = jnp.where(okf, loss, jnp.nan)
                if fpc is not None:
                    g_sq = _integrity.sq_norm(grad_shard)
                # all-gather the updated weights for the next forward
                new_flat = arp.all_gather_weights(new_shard, axis)
                if extra_ag:
                    # the redundant gather returns the identical vector,
                    # so (x + x) / 2 is bit-exact — but the program now
                    # carries a second all-gather for the auditor to catch
                    new_flat = (new_flat
                                + arp.all_gather_weights(new_shard,
                                                         axis)) / 2

            if fpc is not None:
                # a frozen step must keep each replica's INPUT copy
                # bit-for-bit: the all-gather above rebuilds every copy
                # from per-shard contributions, which would wash a
                # diverged copy back into agreement (or spread its
                # corrupted rows to every replica) and destroy the
                # evidence the heal's majority vote needs
                if ok is not None:
                    new_flat = select_tree(ok, new_flat, flat_params)
                if desync_at:
                    # chaos: the injected replica stays SELF-consistent
                    # (its output fingerprint hashes the drifted copy),
                    # so only the next step's agreement table can see it
                    inj = jnp.logical_and(
                        jnp.asarray(tick) == desync_at,
                        lax.axis_index(axis) == desync_rep)
                    new_flat = new_flat.at[0].add(
                        jnp.where(inj, jnp.asarray(1.0, new_flat.dtype),
                                  jnp.asarray(0.0, new_flat.dtype)))
                fp_p_out = _integrity.fingerprint_flat(new_flat, fp_seed)
                fp_s_out = _integrity.fingerprint_tree(
                    new_slots, fp_seed + _integrity.SLOT_SEED_OFF)
                accd = _integrity.acc_dtype()
                new_row = arp.local_shard(new_flat, axis)
                old_row = arp.local_shard(flat_params, axis)
                pb = jnp.stack([
                    jnp.sum(jnp.square(new_row[a:b].astype(accd)))
                    for a, b in edges])
                ub = jnp.stack([
                    jnp.sum(jnp.square(new_row[a:b].astype(accd)
                                       - old_row[a:b].astype(accd)))
                    for a, b in edges])
                # ONE psum carries every diagnostic scalar — per-bucket
                # param/update norms plus the gradient norm (the shards
                # partition the vector, so the axis sum IS the global
                # square norm)
                nb = len(edges)
                stats = axis_sum(
                    jnp.concatenate([pb, ub, g_sq[None]]), axis)
                aux.update(
                    cont=jnp.logical_not(intact).astype(jnp.int32),
                    bad_iter=-axis_min(-bad_iter, axis),
                    fps_all=fps_table,
                    pn=jnp.sum(stats[:nb]), un=jnp.sum(stats[nb:2 * nb]),
                    gn=stats[2 * nb], pb=stats[:nb],
                    ub=stats[nb:2 * nb],
                    fpc=_integrity.pack_carry(latch, bad_iter, fp_p_out,
                                              fp_s_out)[None, :])
            loss = axis_mean(loss, axis)
            new_mstate = pmean_floats(new_mstate, axis)
            for extra in (seq_axis, expert_axis):
                if extra:
                    loss = axis_mean(loss, extra)
                    new_mstate = pmean_floats(new_mstate, extra)
            if guard or every_n > 0:
                return new_flat, new_slots, new_mstate, loss, aux
            return new_flat, new_slots, new_mstate, loss

        pspec_rep = P()
        # batch over data (co-sharded with expert when present); with a
        # seq axis, time (dim 1) over seq
        dim0 = (axis, expert_axis) if expert_axis else axis
        pspec_batch = P(dim0, seq_axis) if seq_axis else P(dim0)
        # slots are sharded over the data axis only (ZeRO-1); replicated
        # across seq/expert shards
        pspec_slots = P(axis)
        in_specs = (pspec_rep,                          # flat params
                    pspec_slots,                        # slot shards
                    pspec_rep,                          # module state
                    pspec_batch, pspec_batch,           # inputs, targets
                    pspec_rep, pspec_rep)               # hyper, rng
        # the diagnostics aux rides replicated (the verdicts and the
        # gathered fingerprint table are identical on every device after
        # their reductions); the integrity carry keeps one row per data
        # replica
        aux_specs = {}
        if guard:
            aux_specs["nf"] = pspec_rep
        if every_n > 0:
            in_specs += (pspec_slots, pspec_rep)        # fpc rows, tick
            aux_specs.update(
                cont=pspec_rep, bad_iter=pspec_rep, fps_all=pspec_rep,
                pn=pspec_rep, un=pspec_rep, gn=pspec_rep, pb=pspec_rep,
                ub=pspec_rep, fpc=pspec_slots)
        out_specs = (pspec_rep, pspec_slots, pspec_rep, pspec_rep)
        if aux_specs:
            out_specs += (aux_specs,)
        sharded = shard_map(
            shard_step, mesh=mesh,
            in_specs=in_specs, out_specs=out_specs,
            check_rep=False)
        # verdict index space of the widened guard pmin, for the
        # driver's diagnosed divergence suffix
        self._nf_names = (["loss"]
                          + [f"grad:flat[{a}:{b})" for a, b in edges])
        from bigdl_tpu.analysis import program_contracts
        from bigdl_tpu.utils import compile_cache
        # byte budgets from the live model: the padded flat parameter
        # vector bounds the reduce-scatter/all-gather wire, the float
        # module-state leaves (BatchNorm stats, MoE diagnostics) bound
        # the mstate pmean all-reduces
        param_bytes = arp.padded_size * jnp.dtype(arp.dtype).itemsize
        state_bytes = sum(
            x.size * jnp.dtype(x.dtype).itemsize
            for x in map(jnp.asarray,
                         jax.tree_util.tree_leaves(model.state))
            if jnp.issubdtype(x.dtype, jnp.floating))
        contract = program_contracts.shard_map_contract(
            precision, param_bytes, state_bytes,
            seq_axis=bool(seq_axis), expert_axis=bool(expert_axis),
            n_buckets=len(edges), integrity=every_n > 0)
        return compile_cache.tracked_jit(sharded, label="shard_map",
                                         topology=self._topology_meta(),
                                         contract=contract,
                                         donate_argnums=(0, 1, 2))

    # ---- driver loop ----------------------------------------------------

    def _optimize(self) -> Module:
        model, mesh = self.model, self.mesh
        axis_size = mesh.shape["data"]
        if self.dataset.partition_num != axis_size:
            raise ValueError(
                f"dataset has {self.dataset.partition_num} partitions but the "
                f"mesh 'data' axis has {axis_size} devices — they must match "
                "(reference DistriOptimizer.scala:492)")

        model.training()
        model._ensure_init()
        if self.model_axis:
            if self.seq_axis or self.expert_axis:
                raise ValueError(
                    "the GSPMD tensor-parallel step composes with 'data' "
                    "only — a mesh mixing 'model' with 'seq'/'expert' is "
                    "not supported")
            if self.compression:
                # NOT silently ignorable: on the GSPMD path the gradient
                # all-reduces are inserted by XLA's partitioner, which
                # accumulates and reduces in f32 even for bf16 compute
                # (verified from compiled HLO: f32 all-reduce(dot) then
                # convert) — there is no program point "before the psum"
                # to cast at.  The explicit shard_map dp step is where
                # the wire dtype is controllable.
                raise ValueError(
                    "compression='bf16' controls the explicit reduce-"
                    "scatter wire of the data-parallel shard_map step; "
                    "on a tensor-parallel ('model') mesh the gradient "
                    "collectives are XLA-partitioner-inserted and their "
                    "wire dtype is not controllable — drop compression "
                    "for this mesh (set_precision('bf16') already keeps "
                    "activations/backward matmuls in bf16)")
            return self._optimize_gspmd()
        if self.seq_axis:
            self._wire_sequence_parallel(model)
        if self.expert_axis:
            self._wire_expert_parallel(model)

        arp = AllReduceParameter(model.params, axis_size, self.compression)
        self._arp = arp
        # a resumed run re-partitions the restored CANONICAL host slots
        # for THIS mesh: _flat_slots re-ravels and re-pads each family
        # for the current shard count, and the device_put places the new
        # 1/N shards — the topology-elastic reshard (timed when resuming;
        # a fresh run's zeros take the identical path untimed)
        from bigdl_tpu.utils import elastic
        slot_shards = elastic.place_slots(
            lambda: jax.device_put(self._flat_slots(arp),
                                   NamedSharding(mesh, P("data"))),
            self._consume_elastic_resumed())
        carry = {
            "flat": jax.device_put(arp.flatten(model.params),
                                   NamedSharding(mesh, P())),
            # slots live sharded across the mesh: each device owns 1/N (ZeRO-1)
            "slots": slot_shards,
            "mstate": jax.device_put(model.state, NamedSharding(mesh, P())),
        }
        self.optim_method.state.setdefault("epoch", 1)

        if self._step_fn is None:
            self._step_fn = self._arm_retrace(self._build_step(arp),
                                              "shard_map")

        from bigdl_tpu.utils import config as _config
        guard = _config.get_bool("bigdl.divergence.guard", True)
        every_n = _config.get_int("bigdl.integrity.everyN", 0)
        integ = None
        if guard or every_n > 0:
            integ = _integrity.DriverIntegrity(
                "shard_map",
                getattr(self, "_nf_names", ["loss", "grad:flat"]),
                every_n=every_n,
                health=_integrity.WeightHealthMonitor(
                    _config.get_float("bigdl.integrity.healthFactor", 0.0),
                    warmup=_config.get_int(
                        "bigdl.integrity.healthWarmup", 5),
                    cooldown=_config.get_int(
                        "bigdl.integrity.healthCooldown", 50)))
        if every_n > 0:
            # one carry row per data replica (seen/latch/bad_iter + the
            # previous step's params/slots output fingerprints)
            carry["fpc"] = jax.device_put(
                np.stack([_integrity.init_carry()] * axis_size),
                NamedSharding(mesh, P("data")))

        # batch dim co-shards over expert when present (tokens follow the
        # all_to_all dispatch axis); time (dim 1) over seq
        dim0 = ("data", "expert") if self.expert_axis else "data"
        if self.seq_axis:
            # time (dim 1) sharded over seq: per-timestep targets required
            batch_sharding = NamedSharding(mesh, P(dim0, "seq"))
            seq_size = mesh.shape["seq"]

            max_seq = getattr(self, "_max_seq_len", None)

            def _check(x):
                x = np.asarray(x)
                if x.ndim < 2 or x.shape[1] % seq_size != 0:
                    raise ValueError(
                        "sequence-parallel training needs (N, T, ...) inputs "
                        "and (N, T, ...) per-timestep targets with T "
                        f"divisible by the seq axis size {seq_size} "
                        f"(got shape {x.shape})")
                if max_seq is not None and x.shape[1] > max_seq:
                    raise ValueError(
                        f"sequence length {x.shape[1]} exceeds a module's "
                        f"position capacity {max_seq} — sharded offsets "
                        "would silently clamp; raise max_len")
                return x
        else:
            batch_sharding = NamedSharding(mesh, P(dim0))
            _check = None
        # per-process shard feeding: this process pulls ONLY the partitions
        # its mesh positions own (single-process: all of them) and the
        # global batch is assembled from every process's local block
        local_ids = local_data_partitions(mesh)
        missing = [p for p in local_ids
                   if p not in getattr(self.dataset, "local_partitions",
                                       local_ids)]
        if missing:
            raise ValueError(
                f"this process's mesh positions own data partitions "
                f"{missing} but the dataset does not hold them locally — "
                f"construct ShardedDataSet(..., local_partitions="
                f"{local_ids}) on this process")
        seq_chunks = (_local_axis_chunks(mesh, "seq") if self.seq_axis
                      else None)
        expert_chunks = (_local_axis_chunks(mesh, "expert")
                         if self.expert_axis else None)
        it = {"shards": None}

        def reset_epoch():
            self.dataset.shuffle()
            it["shards"] = {p: self.dataset.shard_data(p, train=True)
                            for p in local_ids}

        def fetch_batch():
            return _global_batch(it["shards"], batch_sharding, mesh,
                                 self.dataset.partition_num,
                                 seq_chunks=seq_chunks,
                                 expert_chunks=expert_chunks, check=_check)

        def run_step(inputs, targets, hyper, rng):
            flip = _chaos.take_bitflip() if _chaos.active() else None
            if flip is not None:
                # injected SDC: one replica's HBM copy of the replicated
                # parameter vector flips a mid-mantissa bit between steps
                # — the logical array still looks healthy and every value
                # stays finite; only fingerprint agreement can see it
                carry["flat"] = _integrity.bitflip_one_replica(
                    carry["flat"], flip)
            args = [carry["flat"], carry["slots"], carry["mstate"],
                    inputs, targets, hyper, rng]
            if every_n > 0:
                tick = self.optim_method.state.get("evalCounter", 0) + 1
                args += [carry["fpc"], np.int32(tick)]
            out = self._step_fn(*args)
            if len(out) == 5:
                (carry["flat"], carry["slots"], carry["mstate"],
                 loss, aux) = out
                if "fpc" in aux:
                    carry["fpc"] = aux["fpc"]
                return loss, aux
            (carry["flat"], carry["slots"], carry["mstate"], loss) = out
            return loss

        # telemetry MFU probe (bigdl.telemetry.mfu): the fused sharded
        # step's argument tuple for the one-shot cost_analysis lowering
        def _cost_args(inputs, targets, hyper, rng):
            args = (carry["flat"], carry["slots"], carry["mstate"],
                    inputs, targets, hyper, rng)
            if every_n > 0:
                args += (carry["fpc"], np.int32(1))
            return args
        self._cost_args_fn = _cost_args

        def publish():
            # slots leave the device in the same per-parameter pytree format
            # every host-side consumer (checkpoint resume, OptimMethod.update,
            # a later LocalOptimizer) expects.  Single-process the unflatten
            # runs lazily on device (serialization fetches leaves only when a
            # checkpoint actually pickles them — no publish-time transfers on
            # a tunneled chip).  Multi-host the ZeRO shards mostly live on
            # devices this process cannot address, so each flat slot vector
            # is regathered and fetched host-side one at a time
            # (``gather_to_host`` bounds the transient device footprint to
            # one vector); every process joins the collective, only the
            # writer process later serializes.
            self._sharded_slots = carry["slots"]
            if jax.process_count() > 1:
                from bigdl_tpu.parallel.all_reduce import gather_to_host
                host_flat = gather_to_host(carry["slots"], mesh)
                unflat_slots = jax.tree_util.tree_map(
                    lambda v: jax.tree_util.tree_map(
                        np.asarray, arp.unflatten(jnp.asarray(v))),
                    host_flat)
            else:
                unflat_slots = jax.tree_util.tree_map(arp.unflatten,
                                                      carry["slots"])
            self._publish(arp.unflatten(carry["flat"]), unflat_slots,
                          carry["mstate"])

        self._sync_dataset_epoch()
        reset_epoch()
        try:
            self._drive(fetch_batch, run_step, reset_epoch, publish,
                        epoch_size=self.dataset.size(), integrity=integ)
        except _integrity.ReplicaDesyncError as e:
            # heal in place from the agreeing majority, then re-raise:
            # the retry loop sees ``healed`` and re-enters training
            # without a checkpoint restore
            self._heal_desync(e, carry, mesh)
            raise
        return model

    def _heal_desync(self, err, carry, mesh) -> None:
        """Self-heal a data-parallel replica desync: re-broadcast the
        agreeing majority's parameter copy as the canonical state,
        rewind the eval counter to just before the first frozen tick
        (the corrupted replica applied no updates — the in-step verdict
        froze every replica the moment the copies diverged, so the
        majority copy IS the last healthy state), publish, and mark the
        error healed.  The ZeRO-1 slot shards never diverged (each
        device owns disjoint rows, verified per-shard by continuity) and
        are re-placed for the mesh via ``elastic.place_slots`` on
        re-entry."""
        import time
        from bigdl_tpu import telemetry
        from bigdl_tpu.analysis.hostsync import host_pull
        t0 = time.monotonic()
        minority, _ = _integrity.replicated_shard_disagreement(
            carry["flat"], what="desync heal majority vote")
        shards = sorted(carry["flat"].addressable_shards,
                        key=lambda s: s.device.id)
        major = next(i for i in range(len(shards)) if i not in minority)
        canonical = np.asarray(host_pull(
            shards[major].data, what="desync heal canonical copy"))
        carry["flat"] = jax.device_put(canonical,
                                       NamedSharding(mesh, P()))
        self.optim_method.state["evalCounter"] = max(err.iteration - 1, 0)
        # publish the healed canonical state: re-entry rebuilds the
        # device carries (and a fresh integrity carry) from the shells
        self._publish(self._arp.unflatten(carry["flat"]),
                      jax.tree_util.tree_map(self._arp.unflatten,
                                             carry["slots"]),
                      carry["mstate"])
        # re-entry re-partitions the canonical slots for the mesh — time
        # it as the elastic reshard it is
        self._elastic_resumed = True
        telemetry.gauge(
            "Integrity/heal_ms",
            help="detection-to-heal latency of the last integrity fault "
                 "(restore or re-broadcast)").set(
            (time.monotonic() - t0) * 1000.0)
        logger.warning(
            "Healed replica desync at iteration %d: re-broadcast the "
            "majority copy over minority replica(s) %s and rewound to "
            "iteration %d", err.iteration, err.replicas,
            max(err.iteration - 1, 0))
        err.healed = True

    def _wire_expert_parallel(self, module) -> None:
        """Point every MixtureOfExperts at the mesh's ``expert`` axis
        (duck-typed like the seq wiring): inside the shard_map step each
        layer dispatches with all_to_all and runs only its expert slice;
        outside the axis the dense path serves validation/predict.
        A dp x ep mesh with no MoE layer would silently be plain dp at
        double the mesh — reject it."""
        from bigdl_tpu.nn.moe import MixtureOfExperts
        n = self.mesh.shape["expert"]
        moes = module.find_modules(MixtureOfExperts)
        if not moes:
            raise ValueError(
                "mesh declares an 'expert' axis but the model has no "
                "MixtureOfExperts layer — use a ('data',) mesh")
        for m in moes:
            m.set_expert_parallel("expert", n)

    def _optimize_gspmd(self) -> Module:
        """dp x tp trainer: the Megatron tensor-parallel step in the
        TPU-native idiom — NO hand-written collectives.  Parameters carry
        ``tp_specs`` NamedShardings over the ``model`` axis (column/row
        Linear splits, MHA head splits), the batch shards over ``data``,
        and ONE ordinary jitted step (identical in shape to
        LocalOptimizer's) lets XLA's SPMD partitioner insert the
        all-reduces: the per-pair psum on row-parallel outputs and the
        data-axis gradient reduction (the scaling-book recipe: pick a
        mesh, annotate shardings, let XLA insert collectives).  Optimizer
        slots inherit each parameter's sharding, so Adam m/v for a split
        weight are split the same way — the memory win tensor parallelism
        exists for."""
        from bigdl_tpu.parallel.tensor_parallel import (tp_shard_params,
                                                        tp_specs,
                                                        zero1_slot_specs)

        model, mesh = self.model, self.mesh
        specs = tp_specs(model, axis="model", mesh=mesh)
        rep = NamedSharding(mesh, P())
        carry = {
            "params": tp_shard_params(model.params, mesh, specs),
            "mstate": jax.device_put(model.state, rep),
        }
        # slots shard over BOTH axes: the tp split from the parameter spec
        # plus ZeRO-1 over 'data' (a dp x tp run must not pay dp-fold
        # optimizer-state memory); fresh zeros and resumed host snapshots
        # alike are placed onto the slot specs
        slot_specs = zero1_slot_specs(carry["params"], specs,
                                      mesh.shape["data"])
        resumed = self.optim_method._slots is not None
        slots0 = (self.optim_method._slots if resumed
                  else self.optim_method.init_slots(carry["params"]))
        # resumed CANONICAL host slots re-place onto the data x model slot
        # specs of THIS mesh — the GSPMD leg of the topology-elastic
        # reshard (map_over_slots is the pivot: each family's tree zips
        # against the per-parameter spec tree)
        from bigdl_tpu.utils import elastic
        carry["slots"] = elastic.place_slots(
            lambda: self._map_over_slots(
                lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
                slots0, slot_specs),
            self._consume_elastic_resumed())
        self.optim_method.set_slots(carry["slots"])
        self.optim_method.state.setdefault("epoch", 1)

        from bigdl_tpu.utils import config as _config
        guard = _config.get_bool("bigdl.divergence.guard", True)
        every_n = _config.get_int("bigdl.integrity.everyN", 0)
        integ = None
        if guard or every_n > 0:
            integ = _integrity.DriverIntegrity(
                "gspmd",
                _integrity.nonfinite_names(
                    ("loss", 0.0), ("grad", carry["params"])),
                every_n=every_n,
                health=_integrity.WeightHealthMonitor(
                    _config.get_float("bigdl.integrity.healthFactor", 0.0),
                    warmup=_config.get_int(
                        "bigdl.integrity.healthWarmup", 5),
                    cooldown=_config.get_int(
                        "bigdl.integrity.healthCooldown", 50)))
        if every_n > 0:
            carry["fpc"] = jax.device_put(
                jnp.asarray(_integrity.init_carry()), rep)

        if self._step_fn is None:
            # pin the step's output shardings: params come back in their tp
            # placement (replicated over 'data' — XLA schedules the ZeRO
            # all-gather after the update), slots stay data x model sharded
            param_sh = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), specs,
                is_leaf=lambda s: isinstance(s, P))
            slot_sh = self._map_over_slots(
                lambda x, s: NamedSharding(mesh, s), carry["slots"],
                slot_specs)
            out_sh = (param_sh, slot_sh, rep, rep)
            if guard or every_n > 0:
                # the 5th output is the small aux diagnostics dict —
                # replicated (a prefix sharding covers every entry)
                out_sh += (rep,)
            self._step_fn = self._arm_retrace(
                self._build_gspmd_step(out_shardings=out_sh),
                "gspmd")

        batch_sharding = NamedSharding(mesh, P("data"))
        local_ids = local_data_partitions(mesh)
        it = {"shards": None}

        def reset_epoch():
            self.dataset.shuffle()
            it["shards"] = {p: self.dataset.shard_data(p, train=True)
                            for p in local_ids}

        def fetch_batch():
            return _global_batch(it["shards"], batch_sharding, mesh,
                                 self.dataset.partition_num)

        def run_step(inputs, targets, hyper, rng):
            flip = _chaos.take_bitflip() if _chaos.active() else None
            if flip is not None:
                # injected SDC: one mantissa bit of a live (sharded)
                # parameter leaf flips between steps — every value stays
                # finite; only the continuity fingerprint can see it
                carry["params"] = _integrity.bitflip_tree(
                    carry["params"], flip)
            args = [carry["params"], carry["slots"], carry["mstate"],
                    inputs, targets, hyper, rng]
            if every_n > 0:
                tick = self.optim_method.state.get("evalCounter", 0) + 1
                args += [carry["fpc"], np.int32(tick)]
            out = self._step_fn(*args)
            if len(out) == 5:
                (carry["params"], carry["slots"], carry["mstate"],
                 loss, aux) = out
                if "fpc" in aux:
                    carry["fpc"] = aux["fpc"]
                return loss, aux
            (carry["params"], carry["slots"], carry["mstate"],
             loss) = out
            return loss

        # telemetry MFU probe (bigdl.telemetry.mfu): the GSPMD step's
        # argument tuple for the one-shot cost_analysis lowering
        def _cost_args(inputs, targets, hyper, rng):
            args = (carry["params"], carry["slots"], carry["mstate"],
                    inputs, targets, hyper, rng)
            if every_n > 0:
                args += (carry["fpc"], np.int32(1))
            return args
        self._cost_args_fn = _cost_args

        from bigdl_tpu.parallel.all_reduce import (gather_to_host,
                                                   replicate_tree)
        gather_rep = replicate_tree(mesh)

        def publish():
            # single-process the published model keeps its Megatron split —
            # params stay physically sharded over 'model' (the memory win),
            # and host consumers can still read any shard.  Multi-host the
            # remote shards are not addressable, so params regather to
            # replicated on device (validation forwards read them) and
            # slots go per-leaf to host numpy (bounds the transient device
            # footprint; serialization wants numpy anyway).
            if jax.process_count() > 1:
                self._publish(gather_rep(carry["params"]),
                              gather_to_host(carry["slots"], mesh),
                              carry["mstate"])
            else:
                self._publish(carry["params"], carry["slots"],
                              carry["mstate"])

        self._sync_dataset_epoch()
        reset_epoch()
        self._drive(fetch_batch, run_step, reset_epoch, publish,
                    epoch_size=self.dataset.size(), integrity=integ)
        return model

    def _map_over_slots(self, fn, slots, per_param_tree):
        return map_over_slots(self.optim_method, fn, slots, per_param_tree)

    def _build_gspmd_step(self, out_shardings=None):
        model, criterion = self.model, self.criterion
        optim = self.optim_method
        precision = self.precision
        aux_weight = self.moe_aux_weight
        from bigdl_tpu.utils import config
        guard = config.get_bool("bigdl.divergence.guard", True)
        every_n = config.get_int("bigdl.integrity.everyN", 0)
        fp_seed = config.get_int("bigdl.integrity.seed",
                                 _integrity.DEFAULT_SEED)
        # GSPMD overlap: the collectives here are partitioner-inserted,
        # so bucketing means partitioning the PARAMETER LEAVES into ~N
        # contiguous size-balanced groups and running each group's
        # optimizer update as its own scheduling unit (an
        # optimization_barrier pins the group boundary) — the
        # partitioner's collective combiner then emits per-group
        # gradient reductions the scheduler can overlap with other
        # groups' update compute.  Identical elementwise numerics; the
        # traced program stays collective-free, so the gspmd contract is
        # unchanged.
        overlap = config.get_bool("bigdl.parallel.overlap", True)
        groups = (_leaf_bucket_groups(
                      model.params,
                      config.get_int("bigdl.parallel.overlapBuckets", 4))
                  if overlap else None)
        from bigdl_tpu import telemetry
        telemetry.REGISTRY.gauge(
            "Parallel/overlap_buckets", summary=True,
            help="per-step collective buckets (1 = monolithic schedule)"
        ).set(float(len(groups) if groups else 1))

        def step(params, slots, mstate, inputs, targets, hyper, rng,
                 fpc=None, tick=None):
            def loss_fn(p):
                out, new_mstate = mixed_precision_forward(
                    model, p, inputs, mstate, precision, True, rng)
                loss = criterion.apply(out, targets)
                loss = loss + regularization_penalty(model, p)
                loss = loss + moe_aux_penalty(model, new_mstate, aux_weight)
                return loss, new_mstate

            (loss, new_mstate), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            if groups is not None and len(groups) > 1:
                new_params, new_slots = _bucketed_leaf_update(
                    optim, groups, grads, params, slots, hyper)
            else:
                new_params, new_slots = optim.pure_update(grads, params,
                                                          slots, hyper)
            aux = {}
            ok = None
            if guard:
                # divergence guard (logically-global arrays: XLA's
                # partitioner makes the finiteness verdict consistent
                # across every shard without explicit collectives); ``nf``
                # names the first non-finite leaf for the driver's
                # diagnosed divergence line
                ok, nf = _integrity.first_nonfinite(loss, grads)
                aux["nf"] = nf
            if fpc is not None:
                # training-state integrity: the fingerprints are LOGICAL
                # values — the partitioner reduces across shards without
                # explicit collectives, so the traced program stays
                # collective-free (the gspmd contract is unchanged) and
                # cross-copy agreement is verified driver-side by
                # bitwise-comparing the replicated output's device copies
                fp_p_in = _integrity.fingerprint_tree(params, fp_seed)
                fp_s_in = _integrity.fingerprint_tree(
                    slots, fp_seed + _integrity.SLOT_SEED_OFF)
                cont_ok, latch, bad_iter = _integrity.continuity_check(
                    fpc, fp_p_in, fp_s_in, tick)
                intact = latch == 0
                ok = intact if ok is None else jnp.logical_and(ok, intact)
            if ok is not None and ok is not True:
                new_params = select_tree(ok, new_params, params)
                new_slots = select_tree(ok, new_slots, slots)
                new_mstate = select_tree(ok, new_mstate, mstate)
            if guard:
                # a skipped step must report non-finite to the driver's
                # bad-step counter even when only the GRADS overflowed;
                # an integrity freeze does NOT poison the loss
                loss = jnp.where(aux["nf"] == _integrity.NF_SENTINEL,
                                 loss, jnp.nan)
            if fpc is not None:
                fp_p_out = _integrity.fingerprint_tree(new_params, fp_seed)
                fp_s_out = _integrity.fingerprint_tree(
                    new_slots, fp_seed + _integrity.SLOT_SEED_OFF)
                aux.update(
                    cont=latch, bad_iter=bad_iter, fp_p=fp_p_out,
                    pn=_integrity.sq_norm(new_params),
                    un=_integrity.sq_norm_diff(new_params, params),
                    gn=_integrity.sq_norm(grads),
                    fpc=_integrity.pack_carry(latch, bad_iter, fp_p_out,
                                              fp_s_out))
            if guard or every_n > 0:
                return new_params, new_slots, new_mstate, loss, aux
            return new_params, new_slots, new_mstate, loss

        from bigdl_tpu.analysis import program_contracts
        from bigdl_tpu.utils import compile_cache
        return compile_cache.tracked_jit(
            step, label="gspmd", topology=self._topology_meta(),
            contract=program_contracts.gspmd_contract(precision),
            donate_argnums=(0, 1, 2), out_shardings=out_shardings)

    def _wire_sequence_parallel(self, module) -> None:
        """Point every MultiHeadAttention at the mesh's seq axis.  The ring
        path only engages while that axis is bound (inside the shard_map
        training step), so validation/predict forwards — which run outside
        it — keep full-sequence attention.

        Other time-mixing modules have no sequence-parallel path: on a
        time-sharded input a recurrent unroll would restart its hidden
        state at every chunk edge and a temporal conv / time reverse would
        see artificial boundaries — silently wrong, so they are rejected.
        """
        import bigdl_tpu.nn as nn
        time_mixing = (nn.Recurrent, nn.BiRecurrent, nn.TemporalConvolution,
                       nn.Reverse)
        offenders = [type(m).__name__ for m in module.find_modules(time_mixing)]
        if offenders:
            raise ValueError(
                "sequence-parallel training (mesh with a 'seq' axis) shards "
                "the time dimension, but these modules mix information "
                f"across time with no ring path: {sorted(set(offenders))}; "
                "train them on a ('data',)-only mesh")
        # duck-typed: MultiHeadAttention (ring path), PositionalEncoding
        # (chunk offset), and any future seq-aware module
        self._max_seq_len = None
        for m in module.modules():
            if hasattr(m, "set_sequence_parallel"):
                m.set_sequence_parallel(self.seq_axis)
            cap = getattr(m, "max_seq_len", None)
            if cap is not None:
                self._max_seq_len = (cap if self._max_seq_len is None
                                     else min(self._max_seq_len, cap))

    def _eval_mesh(self):
        """Validation forwards run sharded over the training mesh (the
        reference evaluates inside the cluster, ``optim/Evaluator.scala``)."""
        return self.mesh

    def _flat_slots(self, arp: AllReduceParameter):
        """Optimizer slots as flat padded vectors.  Fresh runs start from
        zeros; a resumed/reused OptimMethod carries slots in the canonical
        per-parameter pytree format, which is re-flattened here."""
        cached = self.optim_method._slots
        if cached is None:
            return self.optim_method.init_slots(
                jnp.zeros((arp.padded_size,), arp.dtype))
        outer = jax.tree_util.tree_structure(
            self.optim_method.init_slots(jnp.zeros(())))
        subtrees = outer.flatten_up_to(cached)
        return jax.tree_util.tree_unflatten(
            outer, [arp.flatten(s) for s in subtrees])


def _global_batch(shard_iters, batch_sharding, mesh, partition_num,
                  seq_chunks=None, expert_chunks=None, check=None):
    """Pull one minibatch per LOCALLY-OWNED shard, concatenate host-side
    into this process's block of the global batch, and assemble the global
    sharded array with ``jax.make_array_from_process_local_data`` (each
    device gets exactly its shard's records — the reference's
    locality-preserving zip, ``ZippedPartitionsWithLocalityRDD.scala:28``,
    with per-node feeding like the reference's executor-cached
    partitions).  Single-process, where every partition is local, this
    reduces to placing the whole global batch.

    ``shard_iters``: {partition_id: iterator} for the owned partitions
    (ordered ascending when iterated).  ``seq_chunks``: owned seq-axis
    coordinates — when a seq axis exists and this process owns only some
    time chunks, the time dimension is sliced to the owned (contiguous)
    chunk range before assembly.  ``expert_chunks``: same for the
    ``expert`` axis, which co-shards the batch dim — each data
    partition's rows are sliced to the owned expert chunk range.
    ``check`` optionally validates each local leaf (sequence-parallel
    shape requirements).  Returns the GLOBAL batch record count (driver
    epoch accounting is global)."""
    batches = [next(shard_iters[p]) for p in sorted(shard_iters)]
    inputs = _cat([b.get_input() for b in batches])
    targets = _cat([b.get_target() for b in batches])
    sizes = {b.size() for b in batches}
    if len(sizes) != 1:
        # the global record count is derived as per-partition size x
        # partition_num; uneven local minibatches would silently miscount
        # epoch boundaries on both the producer rollover and the driver
        raise ValueError(
            f"locally-owned partitions yielded unequal minibatch sizes "
            f"{sorted(sizes)} — SampleToMiniBatch(batch, partition_num) "
            "must split evenly across partitions")
    bsz = sizes.pop() * partition_num
    if check is not None:
        inputs = jax.tree_util.tree_map(check, inputs)
        targets = jax.tree_util.tree_map(check, targets)
    if expert_chunks is not None:
        ep_size = mesh.shape["expert"]
        if len(expert_chunks) < ep_size:
            lo, hi = expert_chunks[0], expert_chunks[-1]
            if list(expert_chunks) != list(range(lo, hi + 1)):
                raise ValueError(
                    f"owned expert chunks {expert_chunks} are not "
                    "contiguous — cannot slice batch rows for this process")
            n_parts = len(batches)

            def _slice_rows(x):
                x = np.asarray(x)
                per = x.shape[0] // n_parts        # rows per data partition
                sub = per // ep_size               # rows per expert chunk
                blocks = x.reshape((n_parts, per) + x.shape[1:])
                return blocks[:, lo * sub:(hi + 1) * sub].reshape(
                    (-1,) + x.shape[1:])

            inputs = jax.tree_util.tree_map(_slice_rows, inputs)
            targets = jax.tree_util.tree_map(_slice_rows, targets)
    if seq_chunks is not None:
        seq_size = mesh.shape["seq"]
        if len(seq_chunks) < seq_size:
            lo, hi = seq_chunks[0], seq_chunks[-1]
            if list(seq_chunks) != list(range(lo, hi + 1)):
                raise ValueError(
                    f"owned seq chunks {seq_chunks} are not contiguous — "
                    "cannot slice the time dimension for this process")

            def _slice_t(x):
                x = np.asarray(x)
                chunk = x.shape[1] // seq_size
                return x[:, lo * chunk:(hi + 1) * chunk]

            inputs = jax.tree_util.tree_map(_slice_t, inputs)
            targets = jax.tree_util.tree_map(_slice_t, targets)

    def _assemble(x):
        return jax.make_array_from_process_local_data(
            batch_sharding, np.asarray(x))

    inputs = jax.tree_util.tree_map(_assemble, inputs)
    targets = jax.tree_util.tree_map(_assemble, targets)
    return inputs, targets, bsz


def _cat(parts):
    """Concatenate per-shard activities (arrays or nested lists of arrays)
    along the batch axis.  Single-shard (the 1-partition streaming-ingest
    case) passes through without the concatenate copy — at b128 ImageNet
    that is ~19 MB of uint8 per batch saved on the fetch thread."""
    first = parts[0]
    if isinstance(first, (list, tuple)):
        return type(first)(_cat([p[i] for p in parts])
                           for i in range(len(first)))
    if len(parts) == 1:
        return np.asarray(first)
    return np.concatenate([np.asarray(p) for p in parts], axis=0)
