"""AllReduceParameter: the TPU-native parameter-synchronization backend.

Reference equivalent: ``parameters/AllReduceParameter.scala:67`` — the model's
flattened parameter vector is sliced into ``partitionNum`` chunks; gradients
are exchanged as fp16-compressed blocks through Spark's BlockManager in a
reduce-scatter → sharded-optimizer-update → all-gather cycle.

TPU-native redesign: the whole pull-based block exchange collapses into two
XLA collectives over ICI —

- ``lax.psum_scatter(flat_grads, 'data', tiled=True)``  = reduce-scatter
  (each device ends up owning the summed gradient for its 1/N slice);
- ``lax.all_gather(new_shard, 'data', tiled=True)``     = weight all-gather.

The optimizer update between them runs on each device's shard only — the
reference's partition-sharded update (ZeRO-1, ``optim/DistriOptimizer.scala:
265-280``) expressed under ``shard_map``.  fp16 wire compression
(``parameters/FP16CompressedTensor.scala:30-90``) maps to an optional bf16
cast on the gradient just before the reduce-scatter.

This class owns the host-side geometry: ravel/unravel of the parameter
pytree, zero-padding so the flat length divides the shard count, and the
collective helpers used inside the sharded step.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.flatten_util import ravel_pytree

try:  # jax >= 0.8
    from jax import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs, check_rep=False):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_rep)
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _old_shard_map

    def shard_map(f, mesh, in_specs, out_specs, check_rep=False):
        return _old_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_rep)

Params = Any


def replicate_tree(mesh):
    """One jitted identity pinned replicated over ``mesh`` — the publish-time
    regather every trainer uses to turn sharded carries back into
    host-readable arrays (the reference's getModel pull,
    ``optim/DistriOptimizer.scala:818``).  All processes must call it
    together: XLA lowers the resharding to collectives."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    # a resharding identity, not a fused step: XLA lowers it to one
    # all-gather with no compute worth caching
    return jax.jit(  # lint: allow(untracked-jit)
        lambda t: t, out_shardings=NamedSharding(mesh, P()))


def gather_to_host(tree, mesh):
    """Replicate each leaf over ``mesh`` and fetch it to host numpy ONE
    LEAF AT A TIME.

    Used for publishing optimizer slots: a whole-tree replicated gather
    would transiently materialize the complete slot set on every device —
    for Adam that is 2x the parameter bytes on top of the live sharded
    carries, exactly the allocation ZeRO-1 sharding exists to avoid.
    Per-leaf gathering bounds the transient device footprint to the
    largest single leaf, and the result lands host-side where checkpoint
    serialization (which converts to numpy anyway) wants it.  Collective:
    every process must participate."""
    import numpy as np
    gather = replicate_tree(mesh)

    def one(leaf):
        # the replicated intermediate goes out of scope immediately after
        # the host copy, so at most one leaf is replicated at a time
        return np.asarray(gather(leaf))

    return jax.tree_util.tree_map(one, tree)


class AllReduceParameter:
    """Flat-vector geometry + collectives for one parameter pytree."""

    def __init__(self, params: Params, n_shards: int,
                 compression: Optional[str] = None):
        flat, unravel = ravel_pytree(params)
        if flat.size == 0:
            raise ValueError("model has no trainable parameters")
        self.size = int(flat.size)
        self.dtype = flat.dtype
        self.n_shards = n_shards
        self.padded_size = -(-self.size // n_shards) * n_shards
        self.shard_size = self.padded_size // n_shards
        self._unravel = unravel
        if compression not in (None, "bf16"):
            raise ValueError(f"unknown compression {compression!r} "
                             "(only 'bf16' is supported on TPU)")
        self.compression = compression

    # ---- host/trace-side geometry --------------------------------------

    def flatten(self, tree: Params) -> jnp.ndarray:
        """Pytree -> zero-padded flat vector (works inside jit)."""
        flat, _ = ravel_pytree(tree)
        pad = self.padded_size - self.size
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        return flat

    def unflatten(self, flat: jnp.ndarray) -> Params:
        """Padded flat vector -> pytree (works inside jit)."""
        return self._unravel(flat[:self.size])

    # ---- collectives (call inside shard_map over ``axis``) --------------

    def reduce_scatter_gradients(self, flat_grads: jnp.ndarray,
                                 axis: str) -> jnp.ndarray:
        """Sum gradients across the axis; each device keeps its own slice
        (reference ``putGradients`` + ``aggregateGradientPartition``)."""
        if self.compression == "bf16":
            flat_grads = flat_grads.astype(jnp.bfloat16)
        shard = lax.psum_scatter(flat_grads, axis, scatter_dimension=0,
                                 tiled=True)
        return shard.astype(self.dtype)

    def local_shard(self, flat: jnp.ndarray, axis: str) -> jnp.ndarray:
        """This device's slice of a replicated flat vector."""
        idx = lax.axis_index(axis)
        return lax.dynamic_slice(flat, (idx * self.shard_size,),
                                 (self.shard_size,))

    def all_gather_weights(self, shard: jnp.ndarray, axis: str) -> jnp.ndarray:
        """Reassemble the full flat vector from per-device shards
        (reference ``getWeights`` / ``sendWeightPartition``)."""
        return lax.all_gather(shard, axis, tiled=True)

    # ---- bucketed collectives (the latency-hiding overlap schedule) ------
    #
    # The padded flat vector is a logical (n_shards, shard_size) matrix:
    # row i is device i's ZeRO-1 slice.  A bucket is a contiguous COLUMN
    # range [a, b) of that matrix — so bucket k of every device's shard
    # lines up, per-bucket reduce-scatter/all-gather over the column block
    # is element-identical to the monolithic collective (same per-element
    # reduction order, same placement), and summed over buckets the wire
    # bytes are exactly the monolithic param_bytes.  N independent
    # RS->update->AG chains is what lets XLA's latency-hiding scheduler
    # overlap bucket k's collective with bucket k+1's compute.

    def bucket_edges(self, n_buckets: int):
        """~Equal contiguous [start, stop) column ranges over
        ``shard_size``.  Clamped to at most one column per bucket; the
        rounding spreads a non-divisible remainder one column at a time
        (every column appears in exactly one bucket)."""
        n = max(1, min(int(n_buckets), self.shard_size))
        edges = [round(i * self.shard_size / n) for i in range(n + 1)]
        return [(a, b) for a, b in zip(edges, edges[1:]) if b > a]

    def reduce_scatter_bucket(self, columns: jnp.ndarray,
                              axis: str) -> jnp.ndarray:
        """Reduce-scatter one column block.  ``columns``: the
        (n_shards, b-a) slice of the local gradient matrix view; returns
        this device's summed (b-a,) piece of it."""
        if self.compression == "bf16":
            columns = columns.astype(jnp.bfloat16)
        shard = lax.psum_scatter(columns, axis, scatter_dimension=0,
                                 tiled=True)
        return shard.reshape(-1).astype(self.dtype)

    def all_gather_bucket(self, bucket_shard: jnp.ndarray,
                          axis: str) -> jnp.ndarray:
        """Gather one updated column block back from every device:
        (b-a,) per device -> the (n_shards, b-a) column block of the new
        flat matrix view."""
        return lax.all_gather(bucket_shard, axis, tiled=False)


# ---- declared-contract collective helpers -----------------------------------
#
# Every collective a trainer STEP BODY performs goes through this module
# (or :class:`AllReduceParameter` above): each helper corresponds to a
# collective kind the step's program contract declares, so the HLO
# auditor's census and the source are reconcilable by grep.  The
# ``undeclared-collective`` lint rule flags raw ``lax.psum``/``pmean``/
# ``pmin``/``ppermute``/``all_gather``/``all_to_all`` calls in trainer
# step constructors — route them here instead.


def axis_sum(tree, axis: str):
    """psum over ``axis`` → one all-reduce per leaf (gradient
    contributions summed over a seq/expert axis)."""
    return lax.psum(tree, axis)


def axis_mean(tree, axis: str):
    """pmean over ``axis`` → all-reduce (loss averaging)."""
    return lax.pmean(tree, axis)


def axis_min(tree, axis: str):
    """pmin over ``axis`` → all-reduce (the global divergence verdict:
    every shard must agree to apply or skip a step)."""
    return lax.pmin(tree, axis)


def ring_permute(x, axis: str, perm):
    """ppermute over ``axis`` → collective-permute (pipeline stage ring,
    ring-attention rotation)."""
    return lax.ppermute(x, axis, perm)


def gather_fingerprints(fp, axis: str):
    """all_gather (tiled=False) over ``axis`` → every replica receives
    the full (n_replicas, k) table of per-replica integrity fingerprints
    — the cross-replica agreement verdict is then computable locally on
    each replica with no further collective."""
    return lax.all_gather(fp, axis, tiled=False)


def pmean_floats(tree, axis: str):
    """Average float leaves across the axis (keeps BatchNorm running
    stats consistent between replicas); non-float leaves pass through
    (they evolve identically on every shard)."""
    def f(x):
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            return lax.pmean(x, axis)
        return x
    return jax.tree_util.tree_map(f, tree)
