"""Expert parallelism: MoE dispatch over a mesh ``expert`` axis.

Beyond-reference capability (the reference has no MoE at all): experts are
sharded 1/n per device, tokens are sharded over the same axis, and the
dispatch/combine round-trip is two ``lax.all_to_all`` collectives — the
GShard/Switch layout on ICI.  Each device: route its local tokens against
the full (replicated) gate, all_to_all the per-expert queues so every
device receives the tokens bound for ITS experts from all peers, run the
local experts as one vmapped batch, and all_to_all the outputs back.

Usage::

    mesh = Engine.create_mesh((n,), ("expert",))
    moe = MixtureOfExperts(d, expert_template, n_experts)
    params = ep_shard_params(moe.params, mesh)
    y = expert_parallel_apply(moe, params, x, mesh)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.nn.moe import MixtureOfExperts


def ep_shard_params(params, mesh: Mesh, axis: str = "expert"):
    """Gate replicated, stacked expert weights split along the expert dim."""
    return {
        "gate": jax.device_put(params["gate"], NamedSharding(mesh, P())),
        "experts": jax.tree_util.tree_map(
            lambda x: jax.device_put(x, NamedSharding(mesh, P(axis))),
            params["experts"]),
    }


def expert_parallel_apply(moe: MixtureOfExperts, params, x: jnp.ndarray,
                          mesh: Mesh, axis: str = "expert",
                          training: bool = False, rng=None,
                          return_aux: bool = False):
    """MoE forward with experts AND tokens sharded over ``axis``.

    ``x``: (batch, ..., d_model) with batch divisible by the axis size.
    Differentiable; gradient layouts mirror the inputs (expert grads stay
    expert-sharded).  ``return_aux=True`` additionally returns the Switch
    load-balancing scalar averaged over token shards (under EP — the one
    setting where balance really matters — the per-shard diagnostic must
    be pmeant, or it would be silently dropped)."""
    from bigdl_tpu.parallel.all_reduce import shard_map

    n = mesh.shape[axis]
    if moe.n_experts % n != 0:
        raise ValueError(f"n_experts {moe.n_experts} must divide by the "
                         f"'{axis}' axis size {n}")
    if x.shape[0] % n != 0:
        raise ValueError(f"batch {x.shape[0]} must divide by the "
                         f"'{axis}' axis size {n} (tokens are co-sharded)")
    state = moe.state

    def shard_fn(p, xs):
        flat = jnp.reshape(xs, (-1, moe.d_model))          # local tokens
        grouped = moe._impl() == "grouped"
        if grouped:
            # grouped materialization (bigdl.moe.impl=grouped): scatter /
            # gather instead of the (t, E, C) one-hot einsums — the
            # exchange geometry and capacity semantics are identical
            eid, slot, wgt, keep, aux = moe.route_compact(p, flat)
            cap = moe.capacity(flat.shape[0])
            expert_in = moe.grouped_dispatch(flat, eid, slot, keep, cap)
        else:
            dispatch, combine, aux = moe.route(p, flat)    # (t, E, C)
            expert_in = jnp.einsum("tec,td->ecd", dispatch, flat)
        # exchange queues: split the expert dim across devices, gather the
        # capacity dim — each device ends up with (E/n, n*C, d): every
        # peer's tokens for the experts this device owns
        expert_in = lax.all_to_all(expert_in, axis, split_axis=0,
                                   concat_axis=1, tiled=True)
        out = moe.expert_forward(p, expert_in, state, training, rng)
        # route results back to the devices whose tokens they are
        out = lax.all_to_all(out, axis, split_axis=1, concat_axis=0,
                             tiled=True)                   # (E, C, d)
        if grouped:
            y = moe.grouped_combine(out, eid, slot, wgt, keep, cap)
        else:
            y = jnp.einsum("tec,ecd->td", combine, out)
        y = jnp.reshape(y, xs.shape)
        if return_aux:
            return y, lax.pmean(aux, axis)
        return y

    out_specs = (P(axis), P()) if return_aux else P(axis)
    fn = shard_map(shard_fn, mesh=mesh,
                   in_specs=({"gate": P(), "experts": P(axis)}, P(axis)),
                   out_specs=out_specs, check_rep=False)
    return fn(params, x)
