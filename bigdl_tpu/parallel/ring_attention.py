"""Ring attention: sequence/context parallelism over the mesh's ``seq`` axis.

No reference equivalent — the reference caps sequence length at single-node
memory (SURVEY §5.7); this is the TPU-native long-context path the rebuild
adds as a first-class capability.

Design (Liu et al., Ring Attention with Blockwise Transformers): each device
holds a T/N slice of q, k, v.  N steps of a ring: compute blockwise
attention of the local queries against the currently-held k/v block with an
online (streaming) softmax, then ``lax.ppermute`` the k/v block to the next
device over ICI.  Peak memory is O(T/N) per device and the k/v transfer
overlaps with the block matmuls.

The online-softmax accumulators are the flash-attention triple (running max
``m``, normalizer ``l``, unnormalized output ``o``).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.parallel.all_reduce import shard_map


def _ring_attention_shard(q, k, v, axis_name: str, causal: bool):
    """Per-shard body.  q/k/v: (B, T_local, H, Dh) — the local sequence
    slice; runs inside shard_map over ``axis_name``."""
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    bsz, t, h, dh = q.shape
    scale = 1.0 / math.sqrt(dh)
    q_pos = my * t + jnp.arange(t)

    perm = [(i, (i + 1) % n) for i in range(n)]
    neg_big = jnp.asarray(jnp.finfo(q.dtype).min, q.dtype)

    def step(i, carry):
        k_cur, v_cur, m, l, o = carry
        # the block that started on device (my - i) is now on my
        src = (my - i) % n
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_cur) * scale
        if causal:
            k_pos = src * t + jnp.arange(t)
            allowed = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(allowed[None, None], scores, neg_big)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])
        l = l * alpha + p.sum(axis=-1)
        o = o * alpha[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v_cur)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, m_new, l, o

    m0 = jnp.full((bsz, h, t), neg_big, q.dtype)
    l0 = jnp.zeros((bsz, h, t), q.dtype)
    o0 = jnp.zeros((bsz, h, t, dh), q.dtype)
    _, _, m, l, o = lax.fori_loop(0, n, step, (k, v, m0, l0, o0))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3))      # -> (B, T_local, H, Dh)


def ring_attention(q, k, v, mesh: Mesh, axis: str = "seq",
                   causal: bool = False):
    """Full-sequence attention with q/k/v sharded on dim 1 over ``axis``.

    Inputs are global (B, T, H, Dh) arrays (or already-sharded); output is
    sharded the same way.  Numerically matches
    :func:`bigdl_tpu.nn.attention.scaled_dot_product_attention`.
    """
    spec = P(None, axis)
    fn = shard_map(
        partial(_ring_attention_shard, axis_name=axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)
    return fn(q, k, v)


def ring_self_attention(mha, params, x, mesh: Mesh, axis: str = "seq"):
    """Run a :class:`~bigdl_tpu.nn.attention.MultiHeadAttention` layer's
    forward with the sequence dim sharded over ``axis``.

    The q/k/v/out projections are per-position (shard-local); only the
    attention itself communicates, via the ring.
    """
    def shard_fn(p, xs):
        q = mha._project(p, xs, "wq", "bq")
        k = mha._project(p, xs, "wk", "bk")
        v = mha._project(p, xs, "wv", "bv")
        out = _ring_attention_shard(q, k, v, axis_name=axis,
                                    causal=mha.causal)
        bsz, t = out.shape[0], out.shape[1]
        out = out.reshape(bsz, t, mha.hidden_size) @ p["wo"]
        if mha.with_bias:
            out = out + p["bo"]
        return out

    fn = shard_map(shard_fn, mesh=mesh,
                   in_specs=(P(), P(None, axis)),
                   out_specs=P(None, axis), check_rep=False)
    return fn(params, x)
