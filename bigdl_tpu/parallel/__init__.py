"""bigdl_tpu.parallel — the distributed training half of the framework.

Reference equivalents: ``parameters/`` (AllReduceParameter over Spark's
BlockManager) and ``optim/DistriOptimizer.scala`` — rebuilt TPU-first as XLA
collectives (``psum_scatter`` / ``all_gather``) under ``shard_map`` over a
``jax.sharding.Mesh`` (SURVEY §2.4, §2.12).
"""

from bigdl_tpu.parallel.all_reduce import AllReduceParameter
from bigdl_tpu.parallel.distri_optimizer import DistriOptimizer
from bigdl_tpu.parallel.ring_attention import (ring_attention,
                                               ring_self_attention)
from bigdl_tpu.parallel.expert_parallel import (ep_shard_params,
                                                expert_parallel_apply)
from bigdl_tpu.parallel.pipeline import (PipelineOptimizer,
                                         pipeline_apply,
                                         pipeline_shard_params,
                                         stack_stage_params,
                                         unstack_stage_params)
from bigdl_tpu.parallel.tensor_parallel import (column_parallel,
                                                head_count_divisible,
                                                row_parallel,
                                                tp_shard_params, tp_specs)

__all__ = ["AllReduceParameter", "DistriOptimizer", "ring_attention",
           "ring_self_attention", "column_parallel", "row_parallel",
           "tp_shard_params", "tp_specs", "head_count_divisible",
           "PipelineOptimizer", "pipeline_apply", "pipeline_shard_params",
           "stack_stage_params", "unstack_stage_params", "ep_shard_params",
           "expert_parallel_apply"]
