"""Pipeline parallelism: GPipe-style microbatching over a ``stage`` axis.

The reference has NO pipeline parallelism (SURVEY §2.12: data parallelism
only) — this is a beyond-reference capability in the TPU-native idiom: the
pipeline schedule is a ``lax.scan`` whose carry rotates activations around
the mesh's ``stage`` axis with ``lax.ppermute``; each device applies its
own stage's parameters (a leading stage dimension sharded over the axis).
Because the whole schedule is one differentiable scan, ``jax.grad`` derives
the reverse (backward) pipeline automatically — no hand-written 1F1B.

Scope: homogeneous pipelines — S repetitions of the same block structure
with matching input/output shapes (the transformer-stack case).  Blocks
must be stateless (no BatchNorm running statistics inside the scan).

Usage::

    mesh = Engine.create_mesh((S,), ("stage",))
    block = make_block()                       # one stage's Module
    stacked = stack_stage_params([p0, ..., pS-1])
    stacked = pipeline_shard_params(stacked, mesh)
    y = pipeline_apply(block, stacked, x, n_micro=M, mesh=mesh)
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.nn.module import Module


def stack_stage_params(per_stage: List):
    """Stack S per-stage param pytrees leaf-wise into a (S, ...) tree."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage)


def unstack_stage_params(stacked, n_stages: int) -> List:
    """Inverse of :func:`stack_stage_params`."""
    return [jax.tree_util.tree_map(lambda a, i=i: a[i], stacked)
            for i in range(n_stages)]


def pipeline_shard_params(stacked, mesh: Mesh, axis: str = "stage"):
    """Place stacked params with the stage dimension split across the mesh:
    each device physically holds only its own stage's weights."""
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P(axis))), stacked)


def _check_block(block: Module) -> None:
    from bigdl_tpu.nn.module import semantic_state_leaves
    state_leaves = semantic_state_leaves(block.state)
    if state_leaves:
        raise ValueError(
            "pipeline blocks must be stateless (no BatchNorm running "
            "statistics) — the scanned schedule cannot thread per-stage "
            "module state")


def pipeline_apply(block: Module, stacked_params, x: jnp.ndarray,
                   n_micro: int, mesh: Mesh, axis: str = "stage",
                   data_axis: Optional[str] = None):
    """Run the S-stage pipeline over ``x`` (batch, ...) and return the
    final-stage output for the whole batch, replicated over stages.

    ``x`` is split into ``n_micro`` microbatches along dim 0; at steady
    state all S stages work on different microbatches concurrently.
    Differentiable end-to-end: wrap in a loss and ``jax.grad`` — per-stage
    weight gradients come back with the same (S, ...) stage-sharded layout.

    ``data_axis``: pp x dp composition on a 2-D mesh (e.g.
    ``("data", "stage")``): the batch additionally shards over
    ``data_axis`` (each data replica runs its own pipeline over its batch
    shard; ``n_micro`` applies per shard), stage params replicate across
    data replicas, and autodiff inserts the gradient psum over ``data``
    via the replicated-in transpose — one jax.grad covers both axes.
    """
    from bigdl_tpu.parallel.all_reduce import shard_map

    n_stages = mesh.shape[axis]
    if data_axis is not None:
        n_data = mesh.shape[data_axis]
        if x.shape[0] % n_data != 0:
            raise ValueError(f"batch {x.shape[0]} must divide by the "
                             f"'{data_axis}' axis size {n_data}")
    _check_block(block)
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stacked params carry {leaf.shape[0]} stages but the "
                f"'{axis}' axis has {n_stages} devices — with a mismatch "
                "each device would silently run only its first local stage")
    local_batch = x.shape[0] // (mesh.shape[data_axis]
                                 if data_axis is not None else 1)
    if n_micro < 1 or local_batch % n_micro != 0:
        raise ValueError(f"per-replica batch {local_batch} not divisible "
                         f"into {n_micro} microbatches")
    mb = local_batch // n_micro
    state = block.state
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    def shard_fn(stage_p, xs):
        # xs is this data replica's batch shard; microbatch it locally
        xs = xs.reshape((n_micro, mb) + xs.shape[1:])
        sp = jax.tree_util.tree_map(lambda a: a[0], stage_p)  # my stage
        idx = lax.axis_index(axis)

        def step(buf, i):
            # stage 0 ingests a fresh microbatch; later stages take the
            # activation handed over by ppermute on the previous tick
            fresh = xs[jnp.minimum(i, n_micro - 1)]
            inp = jnp.where(idx == 0, fresh, buf)
            y, _ = block.apply(sp, inp, state, training=False)
            nxt = lax.ppermute(y, axis, perm)
            return nxt, y

        _, ys = lax.scan(step, jnp.zeros_like(xs[0]),
                         jnp.arange(n_micro + n_stages - 1))
        # the last stage emits microbatch m at tick m + S - 1
        outs = ys[n_stages - 1:]
        # broadcast the last stage's outputs to every device
        outs = lax.psum(
            jnp.where(idx == n_stages - 1, outs, jnp.zeros_like(outs)),
            axis)
        return outs.reshape((n_micro * mb,) + outs.shape[2:])

    x_spec = P(data_axis) if data_axis is not None else P()
    fn = shard_map(shard_fn, mesh=mesh,
                   in_specs=(P(axis), x_spec), out_specs=x_spec,
                   check_rep=False)
    return fn(stacked_params, x)
