"""Pipeline parallelism: GPipe-style microbatching over a ``stage`` axis.

The reference has NO pipeline parallelism (SURVEY §2.12: data parallelism
only) — this is a beyond-reference capability in the TPU-native idiom: the
pipeline schedule is a ``lax.scan`` whose carry rotates activations around
the mesh's ``stage`` axis with ``lax.ppermute``; each device applies its
own stage's parameters (a leading stage dimension sharded over the axis).
Because the whole schedule is one differentiable scan, ``jax.grad`` derives
the reverse (backward) pipeline automatically — no hand-written 1F1B.

Scope: homogeneous pipelines — S repetitions of the same block structure
with matching input/output shapes (the transformer-stack case).  Blocks
must be stateless (no BatchNorm running statistics inside the scan).

Microbatching caveat: blocks whose numerics depend on which samples share
a forward — notably MixtureOfExperts capacity-overflow dropping — see
each *microbatch* as an independent forward here.  The pipeline equals
running the stages sequentially per microbatch and concatenating; it
equals the monolithic full-batch forward only when the block is
batch-split-invariant (for MoE: whenever no token drops — see
``nn/moe.py``'s batch-split-semantics note).

Usage::

    mesh = Engine.create_mesh((S,), ("stage",))
    block = make_block()                       # one stage's Module
    stacked = stack_stage_params([p0, ..., pS-1])
    stacked = pipeline_shard_params(stacked, mesh)
    y = pipeline_apply(block, stacked, x, n_micro=M, mesh=mesh)
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim.optimizer import Optimizer
from bigdl_tpu.parallel.all_reduce import axis_mean, axis_sum, ring_permute


def stack_stage_params(per_stage: List):
    """Stack S per-stage param pytrees leaf-wise into a (S, ...) tree."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage)


def unstack_stage_params(stacked, n_stages: int) -> List:
    """Inverse of :func:`stack_stage_params`."""
    return [jax.tree_util.tree_map(lambda a, i=i: a[i], stacked)
            for i in range(n_stages)]


def pipeline_shard_params(stacked, mesh: Mesh, axis: str = "stage",
                          specs=None):
    """Place stacked params with the stage dimension split across the mesh:
    each device physically holds only its own stage's weights.  ``specs``
    (a per-leaf PartitionSpec tree, e.g. from :func:`stage_tp_specs`)
    additionally splits each stage's weights over a ``model`` axis — the
    pipeline x tensor-parallel composition."""
    if specs is None:
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, NamedSharding(mesh, P(axis))),
            stacked)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        stacked, specs)


def stage_tp_specs(block: Module, tp_axis: str = "model",
                   axis: str = "stage"):
    """Per-leaf PartitionSpecs for STACKED stage params of a tp-tagged
    block: dim 0 (the stage dimension) splits over ``axis``, and each
    leaf's Megatron split (``parallel.tp_specs``) shifts right by one —
    a column weight (S, in, out) becomes P(stage, None, model)."""
    from bigdl_tpu.parallel.tensor_parallel import tp_specs
    specs = tp_specs(block, axis=tp_axis)
    return jax.tree_util.tree_map(
        lambda s: P(axis, *s), specs,
        is_leaf=lambda s: isinstance(s, P))


def wire_model_parallel(block: Module, axis: Optional[str],
                        mesh: Optional[Mesh] = None) -> None:
    """Point every tp-capable module (tagged Linear, MultiHeadAttention)
    at the named mesh axis for the EXPLICIT Megatron path (duck-typed,
    like the seq/expert wiring).  Rejects stochastic blocks: a dropout
    mask drawn per model-axis device would decorrelate across feature
    shards, silently changing the layer's semantics."""
    if axis and block.is_stochastic():
        raise ValueError(
            "tensor-parallel pipeline stages must be deterministic "
            "(Dropout & co. would draw per-shard masks over a "
            "feature-sharded activation)")
    if axis and mesh is not None:
        from bigdl_tpu.parallel.tensor_parallel import head_count_divisible
        head_count_divisible(block, mesh, axis)
    for m in block.modules():
        if hasattr(m, "set_model_parallel"):
            m.set_model_parallel(axis)


def _check_block(block: Module) -> None:
    from bigdl_tpu.nn.module import semantic_state_leaves
    state_leaves = semantic_state_leaves(block)
    if state_leaves:
        raise ValueError(
            "pipeline blocks must be stateless (no BatchNorm running "
            "statistics) — the scanned schedule cannot thread per-stage "
            "module state")


def pipeline_apply(block: Module, stacked_params, x: jnp.ndarray,
                   n_micro: int, mesh: Mesh, axis: str = "stage",
                   data_axis: Optional[str] = None,
                   training: bool = False, rng=None,
                   return_aux: bool = False, param_specs=None):
    """Run the S-stage pipeline over ``x`` (batch, ...) and return the
    final-stage output for the whole batch, replicated over stages.

    ``x`` is split into ``n_micro`` microbatches along dim 0; at steady
    state all S stages work on different microbatches concurrently.
    Differentiable end-to-end: wrap in a loss and ``jax.grad`` — per-stage
    weight gradients come back with the same (S, ...) stage-sharded layout.

    ``data_axis``: pp x dp composition on a 2-D mesh (e.g.
    ``("data", "stage")``): the batch additionally shards over
    ``data_axis`` (each data replica runs its own pipeline over its batch
    shard; ``n_micro`` applies per shard), stage params replicate across
    data replicas, and autodiff inserts the gradient psum over ``data``
    via the replicated-in transpose — one jax.grad covers both axes.

    ``training``/``rng``: train-mode stochastic blocks (Dropout) draw a
    distinct stream per (stage, tick) — training with a stochastic block
    and no ``rng`` is rejected rather than silently running without
    dropout.

    ``return_aux=True`` additionally returns the mean of the blocks'
    declared per-forward diagnostics named ``aux_loss`` (MoE load
    balancing) over all real (non-drain) microbatch executions and all
    stages — the term a trainer must fold into its objective, since the
    scanned schedule otherwise discards per-forward state.

    ``param_specs``: the pipeline x tensor-parallel composition on a
    ``('data','stage','model')`` mesh — each stage's Megatron-tagged
    weights additionally split over ``model`` (per-leaf PartitionSpec
    tree from :func:`stage_tp_specs`), and the block must be wired with
    :func:`wire_model_parallel` so its Linears/MHA run the explicit
    split (local matmuls + the pair's one psum) inside this shard_map.
    No custom gradient bookkeeping is needed: shard_map's transpose
    handles the replicated/split accounting (verified by grad-parity
    tests against the unsplit stack).
    """
    from bigdl_tpu.parallel.all_reduce import shard_map

    n_stages = mesh.shape[axis]
    if training and rng is None and block.is_stochastic():
        raise ValueError(
            "training a stochastic pipeline block (Dropout & co.) needs "
            "an rng — without one the block would silently train "
            "without its noise")
    if data_axis is not None:
        n_data = mesh.shape[data_axis]
        if x.shape[0] % n_data != 0:
            raise ValueError(f"batch {x.shape[0]} must divide by the "
                             f"'{data_axis}' axis size {n_data}")
    _check_block(block)
    if param_specs is not None and not any(
            getattr(m, "model_parallel", None) for m in block.modules()):
        # split weights with an unwired block would run row-parallel
        # matmuls WITHOUT their pair psum: finite loss, garbage numbers
        raise ValueError(
            "param_specs splits stage weights over a model axis but no "
            "module in the block is wired for the explicit Megatron "
            "split — call wire_model_parallel(block, axis, mesh) first")
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stacked params carry {leaf.shape[0]} stages but the "
                f"'{axis}' axis has {n_stages} devices — with a mismatch "
                "each device would silently run only its first local stage")
    local_batch = x.shape[0] // (mesh.shape[data_axis]
                                 if data_axis is not None else 1)
    if n_micro < 1 or local_batch % n_micro != 0:
        raise ValueError(f"per-replica batch {local_batch} not divisible "
                         f"into {n_micro} microbatches")
    mb = local_batch // n_micro
    state = block.state
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    def shard_fn(stage_p, xs):
        from bigdl_tpu.nn.module import collect_diagnostics

        # xs is this data replica's batch shard; microbatch it locally
        xs = xs.reshape((n_micro, mb) + xs.shape[1:])
        sp = jax.tree_util.tree_map(lambda a: a[0], stage_p)  # my stage
        idx = lax.axis_index(axis)

        def step(buf, i):
            # stage 0 ingests a fresh microbatch; later stages take the
            # activation handed over by ppermute on the previous tick.
            # During the S-1 drain ticks (i >= n_micro) stage 0 re-runs
            # the last microbatch purely to keep the scan shape uniform
            # (its output is never selected); that redundant forward costs
            # S-1 extra stage-0 block executions per call — the SPMD scan
            # cannot skip per-device work, and masking the apply would
            # still execute both cond branches under vmap-less shard_map,
            # so the uniform re-run is the cheapest correct schedule
            fresh = xs[jnp.minimum(i, n_micro - 1)]
            inp = jnp.where(idx == 0, fresh, buf)
            step_rng = (None if rng is None else
                        jax.random.fold_in(jax.random.fold_in(rng, idx), i))
            y, new_state = block.apply(sp, inp, state, training=training,
                                       rng=step_rng)
            # per-forward diagnostics (MoE aux), masked to the ticks where
            # this stage processes a REAL microbatch: stage s works on
            # microbatch i - s, valid while 0 <= i - s < n_micro
            diags = collect_diagnostics(block, new_state, "aux_loss")
            aux = sum(diags) if diags else jnp.zeros(())
            valid = ((i >= idx) & (i < idx + n_micro)).astype(aux.dtype)
            nxt = ring_permute(y, axis, perm)
            return nxt, (y, aux * valid)

        _, (ys, auxs) = lax.scan(step, jnp.zeros_like(xs[0]),
                                 jnp.arange(n_micro + n_stages - 1))
        # the last stage emits microbatch m at tick m + S - 1
        outs = ys[n_stages - 1:]
        # broadcast the last stage's outputs to every device
        outs = axis_sum(
            jnp.where(idx == n_stages - 1, outs, jnp.zeros_like(outs)),
            axis)
        outs = outs.reshape((n_micro * mb,) + outs.shape[2:])
        # mean aux over the n_micro real executions per stage and over
        # the S stages (psum over the stage axis); data replicas each
        # routed different samples, so mean across them too
        aux_mean = axis_sum(jnp.sum(auxs) / n_micro, axis) / n_stages
        if data_axis is not None:
            aux_mean = axis_mean(aux_mean, data_axis)
        return outs, aux_mean

    x_spec = P(data_axis) if data_axis is not None else P()
    fn = shard_map(shard_fn, mesh=mesh,
                   in_specs=(param_specs if param_specs is not None
                             else P(axis), x_spec),
                   out_specs=(x_spec, P()),
                   check_rep=False)
    out, aux = fn(stacked_params, x)
    if return_aux:
        return out, aux
    return out


class PipelineOptimizer(Optimizer):
    """GPipe trainer: owns the training loop over a ``("stage",)`` or
    ``("data", "stage")`` mesh through the public Optimizer API.

    Beyond-reference (the reference is data-parallel only, SURVEY §2.12).
    ``blocks``: the S homogeneous stages (matching structure, matching
    in/out shapes — the transformer-stack case).  ``embed``/``head``:
    optional replicated modules running before/after the pipelined stack
    (token embedding / LM head), so a full LM trains through one
    differentiable jitted step: embed -> scan+ppermute schedule -> head
    -> criterion, with per-stage weights physically stage-sharded and
    optimizer slots inheriting that sharding (each stage device holds
    only its stage's Adam m/v).

    Implemented as an :class:`~bigdl_tpu.optim.optimizer.Optimizer`
    subclass: triggers, checkpointing, TrainSummary, and the dispatch
    pipeline all apply unchanged — the hand-rolled loops the tests used
    to carry now live behind ``optimize()``.
    """

    def __init__(self, blocks, dataset, criterion, mesh=None,
                 n_micro: int = 4, embed: Optional[Module] = None,
                 head: Optional[Module] = None):
        import bigdl_tpu.nn as nn
        from bigdl_tpu.engine import Engine

        model = nn.Sequential()
        if embed is not None:
            model.add(embed)
        for b in blocks:
            model.add(b)
        if head is not None:
            model.add(head)
        super().__init__(model, dataset, criterion)
        self.blocks = list(blocks)
        self.embed = embed
        self.head = head
        self.n_micro = n_micro
        self._mesh = mesh if mesh is not None else Engine.default_mesh()
        if "stage" not in self._mesh.shape:
            raise ValueError("PipelineOptimizer needs a mesh with a "
                             "'stage' axis")
        if len(self.blocks) != self._mesh.shape["stage"]:
            raise ValueError(
                f"{len(self.blocks)} blocks vs 'stage' axis size "
                f"{self._mesh.shape['stage']} — one stage per device")
        self.data_axis = "data" if "data" in self._mesh.shape else None
        # 3-D composition: a 'model' axis Megatron-splits each stage's
        # tagged weights INSIDE the ppermute schedule (explicit
        # collectives — wire_model_parallel), ZeRO-1 shards optimizer
        # slots over 'data' on top
        self.model_axis = "model" if "model" in self._mesh.shape else None
        self._stage_specs = None
        for m in (embed, head):
            if m is not None:
                m._ensure_init()
                from bigdl_tpu.nn.module import semantic_state_leaves
                if semantic_state_leaves(m):
                    raise ValueError(
                        "embed/head modules must be stateless (their "
                        "state is held fixed through the jitted step)")

    @property
    def mesh(self):
        return self._mesh

    def _topology_meta(self):
        """Saving topology for snapshot manifests: stage/data(/model)
        axes plus the slot axis (ZeRO-1 over 'data' when present) — what
        a restore onto a different data-parallel width needs to reshard
        the stage slots (the stage count itself is model structure, not
        elastic topology)."""
        from bigdl_tpu.utils import elastic
        return elastic.describe_topology(self._mesh, step="pipeline",
                                         slot_axis=self.data_axis)

    def _build_step(self):
        from bigdl_tpu.optim.optimizer import regularization_penalty

        block = self.blocks[0]
        criterion, optim = self.criterion, self.optim_method
        mesh, n_micro, data_axis = self._mesh, self.n_micro, self.data_axis
        embed, head = self.embed, self.head
        if self.precision is not None:
            raise ValueError("PipelineOptimizer is fp32-only for now; "
                             "unset set_precision")

        aux_weight = self.moe_aux_weight

        def step(params, slots, inputs, targets, hyper, rng):
            def loss_fn(p):
                h = inputs
                r = (None if rng is None else
                     jax.random.fold_in(rng, 0))
                if embed is not None:
                    h, _ = embed.apply(p["embed"], h, embed.state,
                                       training=True, rng=r)
                h, aux = pipeline_apply(
                    block, p["stages"], h, n_micro, mesh,
                    data_axis=data_axis, training=True,
                    rng=None if rng is None else jax.random.fold_in(rng, 1),
                    return_aux=True, param_specs=self._stage_specs)
                if head is not None:
                    h, _ = head.apply(p["head"], h, head.state,
                                      training=True,
                                      rng=None if rng is None else
                                      jax.random.fold_in(rng, 2))
                loss = criterion.apply(h, targets)
                # MoE blocks: load-balancing pressure, same weight
                # convention as the Local/Distri trainers
                loss = loss + aux_weight * aux
                # per-stage regularizers: penalty over each stage's slice
                for i in range(len(self.blocks)):
                    sp = jax.tree_util.tree_map(lambda a, i=i: a[i],
                                                p["stages"])
                    loss = loss + regularization_penalty(self.blocks[i], sp)
                if embed is not None:
                    loss = loss + regularization_penalty(embed, p["embed"])
                if head is not None:
                    loss = loss + regularization_penalty(head, p["head"])
                return loss

            loss, grads = jax.value_and_grad(loss_fn)(params)
            new_params, new_slots = optim.pure_update(grads, params, slots,
                                                      hyper)
            return new_params, new_slots, loss

        out_shardings = None
        if getattr(self, "_slot_specs", None) is not None:
            # pin the composed-mesh placements: params come back in their
            # stage(+model) split, slots keep the ZeRO-1 data shard —
            # otherwise the partitioner may silently regather them
            ns = lambda s: NamedSharding(mesh, s)  # noqa: E731
            param_sh = jax.tree_util.tree_map(
                ns, self._param_specs_tree,
                is_leaf=lambda s: isinstance(s, P))
            from bigdl_tpu.parallel.distri_optimizer import map_over_slots
            slot_sh = map_over_slots(optim, lambda x, s: ns(s),
                                     self.optim_method._slots,
                                     self._slot_specs)
            out_shardings = (param_sh, slot_sh, None)

        from bigdl_tpu.analysis import program_contracts
        from bigdl_tpu.utils import compile_cache
        return compile_cache.tracked_jit(
            step, label="pipeline", topology=self._topology_meta(),
            contract=program_contracts.pipeline_contract(),
            donate_argnums=(0, 1), out_shardings=out_shardings)

    def _optimize(self):
        import numpy as np

        model, mesh = self.model, self._mesh
        model.training()
        for b in self.blocks:
            b._ensure_init()
            # every stage must pass the statelessness guard, not just the
            # first: a BatchNorm at stage 3 would silently lose its state
            # updates in the scanned schedule just as surely as at stage 0
            _check_block(b)

        if self.model_axis:
            # explicit Megatron split inside the schedule: wire every
            # stage's tagged modules at the axis and validate head counts
            for b in self.blocks:
                wire_model_parallel(b, self.model_axis, mesh)
            self._stage_specs = stage_tp_specs(self.blocks[0],
                                               tp_axis=self.model_axis)
        stacked = stack_stage_params([b.params for b in self.blocks])
        params = {"stages": pipeline_shard_params(
            stacked, mesh, specs=self._stage_specs)}
        rep = NamedSharding(mesh, P())
        if self.embed is not None:
            params["embed"] = jax.device_put(self.embed.params, rep)
        if self.head is not None:
            params["head"] = jax.device_put(self.head.params, rep)
        resumed = self._consume_elastic_resumed()
        carry = {"params": params,
                 "slots": self.optim_method.slots(params)}
        self._slot_specs = None
        if self.model_axis:
            # per-param spec tree over the whole params dict; stage slots
            # additionally ZeRO-1 shard over 'data' (each data replica
            # holds 1/dp of every stage-shard's Adam m/v — elementwise
            # updates need only the slice XLA scatters to it)
            from bigdl_tpu.parallel.tensor_parallel import zero1_slot_specs
            per_param = {"stages": self._stage_specs}
            for key in ("embed", "head"):
                if key in params:
                    per_param[key] = jax.tree_util.tree_map(
                        lambda _: P(), params[key])
            slot_per_param = dict(per_param)
            if self.data_axis:
                slot_per_param["stages"] = zero1_slot_specs(
                    params["stages"], self._stage_specs,
                    mesh.shape[self.data_axis])
            # resumed canonical host slots re-place onto this mesh's
            # stage(+model) x ZeRO-1 specs — the pipeline leg of the
            # topology-elastic reshard, map_over_slots again the pivot
            from bigdl_tpu.utils import elastic
            from bigdl_tpu.parallel.distri_optimizer import map_over_slots
            carry["slots"] = elastic.place_slots(
                lambda: map_over_slots(
                    self.optim_method,
                    lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
                    carry["slots"], slot_per_param),
                resumed)
            self.optim_method.set_slots(carry["slots"])
            self._param_specs_tree = per_param
            self._slot_specs = slot_per_param
        self.optim_method.state.setdefault("epoch", 1)
        if self._step_fn is None:
            self._step_fn = self._arm_retrace(self._build_step(), "pipeline")

        batch_sharding = NamedSharding(
            mesh, P(self.data_axis) if self.data_axis else P())
        from bigdl_tpu.dataset.dataset import ShardedDataSet
        sharded = isinstance(self.dataset, ShardedDataSet)
        it = {"data": None}

        if sharded:
            # the dp trainers' per-process feeding: each process pulls one
            # minibatch per OWNED partition and the global batch assembles
            # from every process's block — multi-host-capable like
            # DistriOptimizer (the interleaved data() stream would
            # silently train on 1/partition_num of the batch per step)
            from bigdl_tpu.parallel.distri_optimizer import (
                _global_batch, local_data_partitions)
            if self.data_axis is not None:
                if self.dataset.partition_num != mesh.shape[self.data_axis]:
                    raise ValueError(
                        f"dataset has {self.dataset.partition_num} "
                        f"partitions but the '{self.data_axis}' axis has "
                        f"{mesh.shape[self.data_axis]} devices — they "
                        "must match")
                local_ids = local_data_partitions(mesh, self.data_axis)
            else:
                local_ids = list(range(self.dataset.partition_num))
            missing = [p for p in local_ids
                       if p not in self.dataset.local_partitions]
            if missing:
                raise ValueError(
                    f"this process's mesh positions own data partitions "
                    f"{missing} but the dataset does not hold them "
                    "locally — construct ShardedDataSet(..., "
                    f"local_partitions={local_ids}) on this process")

        def reset_epoch():
            self.dataset.shuffle()
            if sharded:
                it["data"] = {p: self.dataset.shard_data(p, train=True)
                              for p in local_ids}
            else:
                it["data"] = self.dataset.data(train=True)

        def put(x):
            return jax.device_put(np.asarray(x), batch_sharding)

        def fetch_batch():
            if sharded:
                return _global_batch(it["data"], batch_sharding, mesh,
                                     self.dataset.partition_num)
            batch = next(it["data"])
            return (jax.tree_util.tree_map(put, batch.get_input()),
                    jax.tree_util.tree_map(put, batch.get_target()),
                    batch.size())

        def run_step(inputs, targets, hyper, rng):
            (carry["params"], carry["slots"],
             loss) = self._step_fn(carry["params"], carry["slots"],
                                   inputs, targets, hyper, rng)
            return loss

        # AOT warmup + telemetry MFU probe: the pipeline step's full
        # argument tuple for the driver's pre-step-1 compile phase
        self._cost_args_fn = lambda inputs, targets, hyper, rng: (
            carry["params"], carry["slots"], inputs, targets, hyper, rng)

        from bigdl_tpu.parallel.all_reduce import (gather_to_host,
                                                   replicate_tree)
        gather_rep = replicate_tree(mesh)

        def publish():
            # under multi-host pp x dp a remote stage's slice is not
            # addressable from this process and checkpoint pickling needs
            # host-complete arrays, so params regather to replicated and
            # slots go per-leaf to host numpy (bounds the transient device
            # footprint); all processes join the gathers, only the writer
            # process serializes (optim.optimizer.is_writer_process).
            # Single-process the stacked stage params unstack lazily as
            # before — no publish-time collectives.
            if jax.process_count() > 1:
                p = gather_rep(carry["params"])
                slots = gather_to_host(carry["slots"], mesh)
            else:
                p, slots = carry["params"], carry["slots"]
            stage_list = unstack_stage_params(p["stages"], len(self.blocks))
            model_params = []
            if self.embed is not None:
                model_params.append(p["embed"])
            model_params.extend(stage_list)
            if self.head is not None:
                model_params.append(p["head"])
            self._publish(model_params, slots, self.model.state)

        self._sync_dataset_epoch()
        reset_epoch()
        self._drive(fetch_batch, run_step, reset_epoch, publish,
                    epoch_size=self.dataset.size())
        return model
