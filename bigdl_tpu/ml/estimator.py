"""DLEstimator / DLClassifier: pipeline-style fit/transform wrappers.

Reference equivalents: ``org.apache.spark.ml.DLEstimator`` (generic
feature/label sizes) and ``DLClassifier`` (classification sugar: scalar
DoubleType label, argmax prediction column) —
``spark/dl/src/main/scala/org/apache/spark/ml/DLClassifier.scala:32``.

The TPU-native analog follows scikit-learn's protocol: ``fit(X, y)``
returns a fitted model object exposing ``transform``/``predict``.  Inputs
are arrays (or lists of per-record arrays) instead of DataFrame columns;
``feature_size`` plays the same per-record reshape role as the reference's
``featureSize`` param.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.dataset import LocalDataSet
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import SampleToMiniBatch


class DLEstimator:
    """fit(X, y) -> DLModel (reference ``DLEstimator``)."""

    def __init__(self, model, criterion, feature_size: Sequence[int],
                 label_size: Sequence[int] = (1,)):
        self.model = model
        self.criterion = criterion
        self.feature_size = tuple(int(s) for s in feature_size)
        self.label_size = tuple(int(s) for s in label_size)
        self.batch_size = 32
        self.max_epoch = 10
        self.learning_rate = 1e-3
        self.optim_method = None

    # fluent config (the reference's Params surface)
    def set_batch_size(self, b: int) -> "DLEstimator":
        self.batch_size = b
        return self

    def set_max_epoch(self, e: int) -> "DLEstimator":
        self.max_epoch = e
        return self

    def set_learning_rate(self, lr: float) -> "DLEstimator":
        self.learning_rate = lr
        return self

    def set_optim_method(self, method) -> "DLEstimator":
        self.optim_method = method
        return self

    @staticmethod
    def _check_lengths(X, y) -> None:
        if len(X) != len(y):
            raise ValueError(f"X has {len(X)} records but y has {len(y)}")

    def _samples(self, X, y) -> List[Sample]:
        self._check_lengths(X, y)
        out = []
        for feat, lab in zip(X, y):
            f = np.asarray(feat, np.float32).reshape(self.feature_size)
            l = np.asarray(lab, np.float32).reshape(self.label_size)
            out.append(Sample(f, l))
        return out

    def fit(self, X, y) -> "DLModel":
        import bigdl_tpu.optim as optim

        ds = LocalDataSet(self._samples(X, y)).transform(
            SampleToMiniBatch(self.batch_size))
        opt = optim.Optimizer.create(self.model, ds, self.criterion)
        opt.set_optim_method(self.optim_method or
                             optim.SGD(learning_rate=self.learning_rate))
        opt.set_end_when(optim.max_epoch(self.max_epoch))
        trained = opt.optimize()
        return self._wrap(trained)

    def _wrap(self, model) -> "DLModel":
        return DLModel(model, self.feature_size).set_batch_size(
            self.batch_size)


class DLModel:
    """Fitted model: transform(X) appends raw model outputs
    (reference ``DLModel``)."""

    def __init__(self, model, feature_size: Sequence[int]):
        self.model = model
        self.feature_size = tuple(int(s) for s in feature_size)
        self.batch_size = 32

    def set_batch_size(self, b: int) -> "DLModel":
        self.batch_size = b
        return self

    def _forward(self, X) -> np.ndarray:
        import jax
        import jax.numpy as jnp
        from bigdl_tpu.optim.evaluator import _eval_forward

        self.model.evaluate()
        # host-detached params under multi-host: the transform input is
        # process-local, and a globally-placed replicated param tree
        # cannot join it in one local computation
        fwd = _eval_forward(self.model,
                            host_params=jax.process_count() > 1)
        feats = np.stack([np.asarray(x, np.float32)
                          .reshape(self.feature_size) for x in X])
        outs = []
        for i in range(0, len(feats), self.batch_size):
            outs.append(np.asarray(fwd(jnp.asarray(feats[i:i + self.batch_size]))))
        return np.concatenate(outs, axis=0)

    def transform(self, X) -> np.ndarray:
        return self._forward(X)

    predict = transform


class DLClassifier(DLEstimator):
    """Classification sugar: scalar 1-based labels in, argmax predictions
    out (reference ``DLClassifier``)."""

    def __init__(self, model, criterion, feature_size: Sequence[int]):
        super().__init__(model, criterion, feature_size, (1,))

    def _samples(self, X, y) -> List[Sample]:
        # scalar class-id labels: ClassNLL-style criteria take (N,) targets
        self._check_lengths(X, y)
        out = []
        for feat, lab in zip(X, y):
            f = np.asarray(feat, np.float32).reshape(self.feature_size)
            out.append(Sample(f, np.float32(np.asarray(lab).reshape(()))))
        return out

    def _wrap(self, model) -> "DLClassifierModel":
        return DLClassifierModel(model, self.feature_size).set_batch_size(
            self.batch_size)


class DLClassifierModel(DLModel):
    """Prediction column = argmax class, 1-based like the reference's
    DoubleType predictions (``batchOutputToPrediction``)."""

    def transform(self, X) -> np.ndarray:
        out = self._forward(X)
        if out.ndim != 2:
            raise ValueError(f"classifier output must be 2-D, got {out.shape}")
        return out.argmax(axis=1).astype(np.float64) + 1.0

    predict = transform
