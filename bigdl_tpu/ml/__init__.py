"""bigdl_tpu.ml — ML-pipeline estimator wrappers.

Reference equivalents: the Spark ML layer
(``org.apache.spark.ml.DLEstimator`` / ``DLClassifier``,
``spark/dl/src/main/scala/org/apache/spark/ml/DLClassifier.scala:32``) —
fit/transform wrappers that plug the trainer into a pipeline framework.
The TPU-native analog targets the de-facto Python pipeline convention
(scikit-learn's fit/predict/transform) instead of Spark ML params.
"""

from bigdl_tpu.ml.estimator import (DLEstimator, DLModel, DLClassifier,
                                    DLClassifierModel)

__all__ = ["DLEstimator", "DLModel", "DLClassifier", "DLClassifierModel"]
