"""Load a trained model from any supported format and validate it.

Reference equivalent: ``example/loadmodel/ModelValidator.scala`` — one CLI
that loads a bigdl / caffe / torch / tensorflow model and evaluates
Top1/Top5 accuracy over a labeled image folder.

Run::

    python -m bigdl_tpu.examples.model_validator \
        -t caffe --caffeDefPath deploy.prototxt --modelPath net.caffemodel \
        -f <val-image-tree> -b 32
    python -m bigdl_tpu.examples.model_validator -t bigdl \
        --modelPath model.snapshot -f <val-image-tree>
"""

import argparse

import numpy as np

import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import DataSet
from bigdl_tpu.dataset.image import (BGRImgToSample, CenterCrop,
                                     ChannelNormalize, LocalImgReader)
from bigdl_tpu.utils import file_io


def load_model(model_type: str, model_path: str, caffe_def_path=None,
               tf_inputs=None, tf_outputs=None):
    """Dispatch on model type (reference ModelValidator's match)."""
    if model_type == "bigdl":
        return file_io.load(model_path)
    if model_type == "caffe":
        from bigdl_tpu.utils.caffe.loader import load_caffe
        if not caffe_def_path:
            raise SystemExit("caffe models need --caffeDefPath")
        return load_caffe(caffe_def_path, model_path)
    if model_type == "torch":
        from bigdl_tpu.utils.torch_module import load_model as load_t7
        return load_t7(model_path)
    if model_type == "tf":
        from bigdl_tpu.utils.tf.loader import load as load_tf
        if not (tf_inputs and tf_outputs):
            raise SystemExit("tf models need --inputs and --outputs")
        return load_tf(model_path, tf_inputs, tf_outputs)
    raise SystemExit(f"unknown model type {model_type!r} "
                     "(want bigdl|caffe|torch|tf)")


def validation_samples(folder: str, crop: int = 224, scale_to: int = 256,
                       mean=(104.0, 117.0, 123.0), std=(1.0, 1.0, 1.0)):
    """Labeled image tree → centered-crop normalized samples (reference
    preprocessors in ``example/loadmodel/Preprocessor.scala``)."""
    ds = DataSet.image_folder(folder, scale_to=scale_to)
    ds = (ds.transform(LocalImgReader(scale_to))
            .transform(CenterCrop(crop, crop))
            .transform(ChannelNormalize(mean, std))
            .transform(BGRImgToSample()))
    return list(ds.data(train=False))


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Load a bigdl/caffe/torch/tf model and validate it")
    p.add_argument("-f", "--folder", required=True,
                   help="label-per-subdirectory validation image tree")
    p.add_argument("-t", "--model-type", required=True,
                   choices=["bigdl", "caffe", "torch", "tf"])
    p.add_argument("--modelPath", required=True)
    p.add_argument("--caffeDefPath")
    p.add_argument("--inputs", nargs="*", help="tf graph input node names")
    p.add_argument("--outputs", nargs="*", help="tf graph output node names")
    p.add_argument("-b", "--batch-size", type=int, default=32)
    p.add_argument("--crop", type=int, default=224)
    p.add_argument("--meanFile",
                   help=".npy channel-mean file (else caffe BGR means)")
    args = p.parse_args(argv)

    model = load_model(args.model_type, args.modelPath, args.caffeDefPath,
                       args.inputs, args.outputs)
    model.evaluate()

    mean = (tuple(np.load(args.meanFile).ravel()[:3]) if args.meanFile
            else (104.0, 117.0, 123.0))
    samples = validation_samples(args.folder, crop=args.crop, mean=mean)
    results = optim.Evaluator(model).test(
        samples, [optim.Top1Accuracy(), optim.Top5Accuracy()],
        args.batch_size)
    for method, result in results:
        print(f"{method}: {result}")
    return results


if __name__ == "__main__":
    main()
