"""TensorFlow GraphDef load/save demo.

Reference equivalent: ``example/tensorflow/Load.scala`` + ``Save.scala`` —
load a frozen GraphDef as a model and run it; export a model to a GraphDef
TensorFlow can import.

Run::

    python -m bigdl_tpu.examples.tensorflow_interop load \
        --modelPath model.pb --inputs Placeholder --outputs output
    python -m bigdl_tpu.examples.tensorflow_interop save \
        --out model.pb [--modelPath model.snapshot]
"""

import argparse

import numpy as np


def cmd_load(args):
    from bigdl_tpu.utils.tf.loader import load as load_tf
    model = load_tf(args.modelPath, args.inputs, args.outputs)
    model.evaluate()
    shape = tuple(int(s) for s in args.shape)
    x = np.random.RandomState(0).normal(size=shape).astype(np.float32)
    out = model.forward(x)
    print(f"loaded {args.modelPath}: forward({shape}) -> "
          f"{np.asarray(out).shape}")
    return model


def cmd_save(args):
    import bigdl_tpu.nn as nn
    from bigdl_tpu.utils import file_io
    from bigdl_tpu.utils.tf.saver import save as save_tf
    if args.modelPath:
        model = file_io.load(args.modelPath)
    else:  # the reference's Save.scala demo: a small LeNet-ish chain
        model = (nn.Sequential()
                 .add(nn.Linear(784, 128)).add(nn.Tanh())
                 .add(nn.Linear(128, 10)).add(nn.SoftMax()))
    shape = [None] + [int(s) for s in args.shape[1:]] \
        if args.shape else [None, 784]
    save_tf(model, shape, args.out)
    print(f"saved GraphDef to {args.out}")


def main(argv=None):
    p = argparse.ArgumentParser(description="TF GraphDef load/save demo")
    sub = p.add_subparsers(dest="cmd", required=True)
    pl = sub.add_parser("load")
    pl.add_argument("--modelPath", required=True)
    pl.add_argument("--inputs", nargs="+", required=True)
    pl.add_argument("--outputs", nargs="+", required=True)
    pl.add_argument("--shape", nargs="+", default=[1, 28, 28])
    pl.set_defaults(fn=cmd_load)
    ps = sub.add_parser("save")
    ps.add_argument("--out", required=True)
    ps.add_argument("--modelPath")
    ps.add_argument("--shape", nargs="+")
    ps.set_defaults(fn=cmd_save)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    main()
