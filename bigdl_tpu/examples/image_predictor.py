"""Classify a folder of images with a trained model.

Reference equivalent: ``example/imageclassification/ImagePredictor.scala`` —
load a model, run the visual pipeline over every image under a folder, and
print per-image predicted classes.

Run::

    python -m bigdl_tpu.examples.image_predictor \
        --modelPath model.snapshot -f <image-folder> [--topN 5]
"""

import argparse
import os

import numpy as np

from bigdl_tpu.dataset.image import (BGRImgToSample, CenterCrop,
                                     ChannelNormalize, LocalImgPath,
                                     LocalImgReader)
from bigdl_tpu.examples.model_validator import load_model
from bigdl_tpu.optim.predictor import Predictor

IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def image_samples(folder: str, crop: int = 224, scale_to: int = 256,
                  mean=(104.0, 117.0, 123.0)):
    """Flat or nested image folder → (paths, samples)."""
    paths = []
    for root, _, files in sorted(os.walk(folder)):
        for f in sorted(files):
            if f.lower().endswith(IMG_EXTS):
                paths.append(os.path.join(root, f))
    records = [LocalImgPath(p, 0.0) for p in paths]
    chain = ChannelNormalize(mean, (1.0, 1.0, 1.0))
    it = BGRImgToSample()(chain(CenterCrop(crop, crop)(
        LocalImgReader(scale_to)(iter(records)))))
    return paths, list(it)


def main(argv=None):
    p = argparse.ArgumentParser(description="Predict classes for images")
    p.add_argument("-f", "--folder", required=True)
    p.add_argument("--modelPath", required=True)
    p.add_argument("-t", "--model-type", default="bigdl",
                   choices=["bigdl", "caffe", "torch", "tf"])
    p.add_argument("--caffeDefPath")
    p.add_argument("--inputs", nargs="*", help="tf graph input node names")
    p.add_argument("--outputs", nargs="*", help="tf graph output node names")
    p.add_argument("-b", "--batch-size", type=int, default=32)
    p.add_argument("--crop", type=int, default=224)
    p.add_argument("--topN", type=int, default=1)
    args = p.parse_args(argv)

    model = load_model(args.model_type, args.modelPath, args.caffeDefPath,
                       args.inputs, args.outputs)
    model.evaluate()
    paths, samples = image_samples(args.folder, crop=args.crop)
    if not samples:
        raise SystemExit(f"no images under {args.folder}")

    out = Predictor(model).predict(samples, args.batch_size)
    out = np.asarray(out)
    for path, dist in zip(paths, out):
        top = np.argsort(dist)[::-1][:args.topN]
        classes = " ".join(f"{int(c) + 1}({dist[c]:.3f})" for c in top)
        print(f"{path}: {classes}")
    return list(zip(paths, out))


if __name__ == "__main__":
    main()
