"""Runnable examples mirroring the reference ``example/`` tree.

| Reference (``example/``)           | Here                                   |
|------------------------------------|----------------------------------------|
| ``loadmodel/ModelValidator.scala`` | ``model_validator.py`` (CLI)           |
| ``imageclassification/``           | ``image_predictor.py`` (CLI)           |
| ``udfpredictor/``                  | ``udf_predictor.py`` (callable + CLI)  |
| ``tensorflow/Load,Save.scala``     | ``tensorflow_interop.py`` (CLI)        |
| ``textclassification/``            | ``bigdl_tpu/models/textclassifier``    |
| ``treeLSTMSentiment/``             | ``bigdl_tpu/models/treelstm``          |
| ``lenetLocal/``                    | ``bigdl_tpu/models/lenet`` train/test  |
| ``MLPipeline/``                    | ``bigdl_tpu/ml`` estimators            |
"""
