"""Text-classification predictor packaged as a reusable UDF.

Reference equivalent: ``example/udfpredictor/`` — wraps a trained text
classifier as a user-defined function applied over a table of documents
(there: a Spark SQL UDF on a DataFrame; here: a plain callable usable with
any dataframe library, plus a CLI over a folder of ``.txt`` files).

Run::

    python -m bigdl_tpu.examples.udf_predictor \
        --modelPath model.snapshot --glove glove.6B.200d.txt -f <txt-folder>
"""

import argparse
import os

import numpy as np

from bigdl_tpu.dataset.datasets import load_glove
from bigdl_tpu.dataset.text import SentenceTokenizer
from bigdl_tpu.optim.predictor import Predictor
from bigdl_tpu.utils import file_io


def make_udf(model, word_vectors, seq_len: int = 1000,
             batch_size: int = 32):
    """Return ``predict(texts) -> 1-based class labels`` — the reference's
    ``udf(predict _)`` body (``udfpredictor/Utils.scala``): tokenize, embed
    with pretrained vectors, batch through the model."""
    model.evaluate()
    tok = SentenceTokenizer()
    if not word_vectors:
        raise ValueError("word_vectors is empty — wrong --dim for the "
                         "GloVe file? (lines with a different dimension "
                         "are skipped)")
    dim = len(next(iter(word_vectors.values())))
    predictor = Predictor(model)

    def embed(text: str) -> np.ndarray:
        from bigdl_tpu.dataset.sample import Sample
        words = next(tok(iter([text])), [])
        seq = np.zeros((seq_len, dim), dtype=np.float32)
        for i, w in enumerate(words[:seq_len]):
            v = word_vectors.get(w)
            if v is not None:
                seq[i] = v
        return Sample(seq, np.float32(0))

    def predict(texts):
        if isinstance(texts, str):
            texts = [texts]
        if not texts:
            return []
        samples = [embed(t) for t in texts]
        return (predictor.predict_class(samples, batch_size)
                .astype(int).tolist())

    return predict


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Apply a text-classifier UDF over documents")
    p.add_argument("-f", "--folder", required=True,
                   help="folder of .txt documents")
    p.add_argument("--modelPath", required=True)
    p.add_argument("--glove", required=True, help="GloVe .txt vectors")
    p.add_argument("--dim", type=int, default=200)
    p.add_argument("--seq-len", type=int, default=1000)
    p.add_argument("-b", "--batch-size", type=int, default=32)
    args = p.parse_args(argv)

    model = file_io.load(args.modelPath)
    vectors = load_glove(args.glove, args.dim)
    udf = make_udf(model, vectors, args.seq_len, args.batch_size)

    names, texts = [], []
    for f in sorted(os.listdir(args.folder)):
        path = os.path.join(args.folder, f)
        if os.path.isfile(path):
            names.append(f)
            with open(path, errors="ignore") as fh:
                texts.append(fh.read())
    if not texts:
        raise SystemExit(f"no documents under {args.folder}")
    for name, label in zip(names, udf(texts)):
        print(f"{name}: {label}")


if __name__ == "__main__":
    main()
