"""Resilient compilation: persistent executable cache, AOT warmup, buckets.

Compilation was the last unsupervised, unretried, uncached phase of a
training/serving run: every process start paid 15-45 s of unguarded XLA
compile, and a wedged remote compilation killed the whole run with no
diagnosis (ROADMAP item 4).  Every other expensive phase already restarts
from durable state — snapshots (PR 2), the optimizer loop (PR 6), ingest
(PR 7) — this module gives compilation the same contract, in three legs:

1. **Persistent cache** (:class:`CompileCache`).  Every fused-step
   lowering is keyed by (abstract-signature hash from the PR 4 recompile
   sentinel + the lowered StableHLO digest, topology from
   ``elastic.describe_topology``, jax/jaxlib/backend version) and the
   compiled executable is stored serialized under
   ``bigdl.compile.cacheDir`` with the PR 2 snapshot discipline: a
   per-entry JSON manifest carrying payload checksums, a ``.commit``
   marker written LAST, torn/uncommitted/corrupt/stale entries skipped
   with a structured log and a fresh compile — never a crash.  A second
   process over the same model+topology *loads* instead of compiles.
   Writers take a single-writer lock with a capped-backoff wait
   (``bigdl.compile.lockTimeoutSec``) so concurrent processes never
   corrupt each other — a process that cannot get the lock simply skips
   the write; ``bigdl.compile.keepLast`` GCs old entries commit-first.

2. **AOT warmup under a watchdog** (:class:`CachedStep`,
   :func:`compile_watchdog`).  ``tracked_jit`` wraps each fused step:
   execution always goes through an explicitly lowered-and-compiled
   executable, so the driver can warm every step up (telemetry-spanned,
   ``Compile/*`` metrics) before step 1 dispatches.  Each trace, cache
   load, and compile runs supervised by ``bigdl.compile.timeoutSec``: a
   wedged compile is aborted with a :class:`CompileTimeoutError`
   carrying the signature+topology diagnosis (cache loads additionally
   fall back to a fresh compile before failing), and the trainer's
   retry loop classifies it like divergence — restore and retry — while
   preemption still means leave.

3. **Shape bucketing** (:func:`configured_buckets`, :func:`pad_batch`).
   Variable batch inputs (validation remainder batches, ``Predictor``)
   round up to the configured ``bigdl.compile.buckets`` at the choke
   points (pad rows in, slice rows out), so post-warmup execution hits
   only pre-compiled signatures; ``CachedStep`` precompiles every
   bucket variant of a new signature family ahead of time and registers
   them with the PR 4 retrace sentinel, which in ``strict`` mode is the
   regression gate proving zero post-warmup retraces.

The abort caveat of the PR 6 watchdog applies: the injected exception
lands when the compiling thread next executes Python bytecode.  It
interrupts chaos-simulated hangs and host-side wedges; a thread parked
forever inside one native XLA call is only reachable by process-level
supervision, which the structured log and ``Compile/watchdog_fired``
counter exist to inform.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from bigdl_tpu import telemetry
from bigdl_tpu.utils import chaos as _chaos
from bigdl_tpu.visualization.crc32c import crc32c

logger = logging.getLogger("bigdl_tpu")

#: cache entry manifest schema; a manifest from a NEWER release is a
#: deliberate miss (recompile), never an unpickle crash
ENTRY_VERSION = 1

#: injectable for tests (lock backoff must not really sleep in tier-1)
_sleep = time.sleep


class CompileTimeoutError(RuntimeError):
    """A fused-step compile (or cache load) exceeded
    ``bigdl.compile.timeoutSec``.  Carries the signature+topology
    diagnosis so the log names *which* lowering wedged.  The trainer's
    retry loop treats it like divergence — restore the newest valid
    snapshot and retry — not like preemption (leave)."""

    def __init__(self, label: str = "", phase: str = "",
                 timeout: float = 0.0,
                 diagnosis: Optional[Dict[str, Any]] = None):
        # no-arg constructible: PyThreadState_SetAsyncExc instantiates
        # the bare class in the aborted thread; the catch site re-raises
        # with the full diagnosis attached
        self.label = label
        self.phase = phase
        self.timeout = timeout
        self.diagnosis = dict(diagnosis or {})
        if not label:
            super().__init__()
            return
        super().__init__(
            f"compile watchdog: {phase} of fused step {label!r} exceeded "
            f"bigdl.compile.timeoutSec={timeout:g}s — "
            f"diagnosis: "
            f"{json.dumps(self.diagnosis, sort_keys=True, default=str)}")


class _WatchState:
    __slots__ = ("fired", "detect_ms")

    def __init__(self):
        self.fired = False
        self.detect_ms = 0.0


def compile_timeout() -> float:
    from bigdl_tpu.utils import config
    return config.get_float("bigdl.compile.timeoutSec", 0.0)


class compile_watchdog:
    """Supervise one compile/load phase: if the body has not finished
    within ``timeout`` seconds, log the structured diagnosis, bump the
    ``Compile/watchdog_fired`` counter, and inject
    :class:`CompileTimeoutError` into the supervised thread (the PR 6
    ``_async_raise`` machinery).  ``timeout <= 0`` is a no-op.  The bare
    async-raised exception carries no message, so the caller re-raises a
    fully-diagnosed instance (see :meth:`CachedStep._compile_entry`)."""

    def __init__(self, label: str, phase: str,
                 timeout: Optional[float] = None,
                 diagnosis: Optional[Dict[str, Any]] = None):
        self.label = label
        self.phase = phase
        self.timeout = compile_timeout() if timeout is None else timeout
        self.diagnosis = dict(diagnosis or {})
        self.state = _WatchState()
        self._done = threading.Event()
        #: inject-vs-exit atomicity: __exit__ marks done and the monitor
        #: re-checks done IMMEDIATELY before injecting, both under this
        #: lock — a compile that completes right at the deadline (the
        #: fire diagnostics take real time) can never receive a stray
        #: async exception after leaving the supervised block, the same
        #: re-validate-under-the-lock discipline the PR 6 hung-step
        #: watchdog uses
        from bigdl_tpu import analysis
        self._lock = analysis.make_lock("compile_cache.watchdog")
        self._thread: Optional[threading.Thread] = None

    def __enter__(self) -> "_WatchState":
        if self.timeout <= 0:
            return self.state
        from bigdl_tpu.utils.elastic import _async_raise
        tid = threading.get_ident()
        t0 = time.monotonic()

        def monitor():
            if self._done.wait(self.timeout):
                return
            # past deadline: one fire per phase, then inject
            self.state.fired = True
            self.state.detect_ms = (time.monotonic() - t0 -
                                    self.timeout) * 1e3
            logger.error(
                "Compile watchdog: %s of fused step %r still running "
                "%.1fs past bigdl.compile.timeoutSec=%gs — aborting "
                "(diagnosis: %s)", self.phase, self.label,
                self.state.detect_ms / 1e3, self.timeout,
                json.dumps(self.diagnosis, sort_keys=True, default=str))
            telemetry.counter(
                "Compile/watchdog_fired",
                help="compile-watchdog aborts of wedged compiles").inc()
            telemetry.gauge("Compile/watchdog_detect_ms").set(
                self.state.detect_ms)
            telemetry.instant("compile/watchdog_fired", label=self.label,
                              phase=self.phase)
            with self._lock:
                if self._done.is_set():   # completed during diagnostics
                    return
                _async_raise(tid, CompileTimeoutError)

        self._thread = threading.Thread(target=monitor, daemon=True,
                                        name="bigdl-compile-watchdog")
        self._thread.start()
        return self.state

    def __exit__(self, *exc) -> None:
        with self._lock:
            self._done.set()
        t = self._thread
        if t is not None:
            t.join(timeout=1.0)
            self._thread = None


# ---- shape buckets --------------------------------------------------------


def configured_buckets() -> Optional[List[int]]:
    """The sorted ``bigdl.compile.buckets`` list, or None when bucketing
    is off.  Accepts a comma-separated string (``"8,16,32"``) or a
    sequence of ints."""
    from bigdl_tpu.utils import config
    v = config.get_property("bigdl.compile.buckets")
    if not v:
        return None
    if isinstance(v, (list, tuple)):
        sizes = [int(x) for x in v]
    else:
        sizes = [int(t) for t in str(v).split(",") if t.strip()]
    sizes = sorted(set(s for s in sizes if s > 0))
    return sizes or None


def bucket_size(n: int, buckets: Sequence[int]) -> int:
    """Smallest configured bucket >= ``n``; beyond the largest bucket,
    the next multiple of it (so the signature count stays bounded for
    any input size instead of growing one-per-ragged-length)."""
    for b in buckets:
        if n <= b:
            return b
    largest = buckets[-1]
    return ((n + largest - 1) // largest) * largest


def pad_batch(tree, n: int, padded_n: int):
    """Pad every leaf of a host batch from ``n`` to ``padded_n`` rows by
    repeating the last row (edge padding: always-valid values, so the
    padded rows cannot produce NaN/inf that a reduction might smear).
    Callers slice model outputs back to ``n`` rows host-side — the
    surviving rows are bit-identical to an unpadded forward for the
    batch-independent eval-mode graphs this feeds (conv/BN-eval/
    attention-per-row)."""
    import jax
    import numpy as np
    if padded_n == n:
        return tree

    def _pad(x):
        x = np.asarray(x)
        reps = np.repeat(x[-1:], padded_n - n, axis=0)
        return np.concatenate([x, reps], axis=0)

    return jax.tree_util.tree_map(_pad, tree)


def slice_rows(tree, n: int):
    """Undo :func:`pad_batch` on a pulled host output: first ``n`` rows
    of every leaf (no-op for leaves that already match)."""
    import jax
    import numpy as np

    def _cut(x):
        x = np.asarray(x)
        return x[:n] if x.ndim >= 1 and x.shape[0] > n else x

    return jax.tree_util.tree_map(_cut, tree)


# ---- persistent executable store ------------------------------------------


def backend_fingerprint() -> Dict[str, str]:
    """Versions an executable is only valid under: jax + jaxlib + the
    XLA backend platform and its version.  Any difference is a cache
    miss (recompile), never a deserialization crash."""
    import jax
    import jaxlib
    try:
        from jax.extend import backend as _xb
        b = _xb.get_backend()
        platform, pver = b.platform, str(b.platform_version)
    except Exception:  # pragma: no cover - very old jax
        platform, pver = "unknown", "unknown"
    return {"jax": jax.__version__, "jaxlib": jaxlib.__version__,
            "platform": platform, "platform_version": pver}


class CompileCache:
    """One persistent executable store directory (local filesystem).

    Layout per entry (``<key>`` is the hex digest of the full cache key):

    - ``<key>.bin``    — pickled ``(serialized_executable, in_tree,
      out_tree)`` payload,
    - ``<key>.json``   — manifest: entry version, label, payload checksum
      + byte count, abstract-signature hash, topology, backend
      fingerprint, creation time,
    - ``<key>.commit`` — marker written LAST; its content cross-checks
      the manifest (the atomic "entry is whole" bit).

    Reads verify commit↔manifest and payload checksum+size; any tear,
    truncation, bit-flip, schema skew, or version/topology mismatch is a
    MISS with a structured log.  Writes take the single-writer ``lock``
    file with a capped-backoff wait; a writer that cannot acquire it
    skips the write (the executable it just compiled still serves this
    process from memory)."""

    LOCK_NAME = "lock"

    def __init__(self, path: str, keep_last: Optional[int] = None):
        from bigdl_tpu.utils import config
        self.path = path
        self.keep_last = (keep_last if keep_last is not None else
                          config.get_int("bigdl.compile.keepLast", 0))
        self.lock_timeout = config.get_float(
            "bigdl.compile.lockTimeoutSec", 30.0)
        self.lock_stale = config.get_float(
            "bigdl.compile.lockStaleSec", 600.0)
        self.hits = 0
        self.misses = 0
        self.errors = 0
        self.writes = 0
        #: disk-full degradation latch: once storage is exhausted, every
        #: further store is skipped up front (the PR 8 lock-loser path —
        #: this process keeps serving executables from memory)
        self.degraded = False

    @classmethod
    def from_config(cls) -> Optional["CompileCache"]:
        from bigdl_tpu.utils import config
        path = config.get_property("bigdl.compile.cacheDir")
        if not path:
            return None
        return cls(str(path))

    # -- keying ----------------------------------------------------------

    @staticmethod
    def entry_key(label: str, signature_hash: str, hlo_digest: str,
                  topology: Optional[Dict[str, Any]],
                  fingerprint: Dict[str, str]) -> str:
        """Hex cache key.  The StableHLO digest makes the key exact (two
        models sharing parameter shapes cannot collide); signature hash,
        topology, and backend fingerprint ALSO enter the key so the same
        information that drives the miss diagnosis drives the lookup."""
        h = hashlib.sha256()
        for part in (label, signature_hash, hlo_digest,
                     json.dumps(topology or {}, sort_keys=True),
                     json.dumps(fingerprint, sort_keys=True)):
            h.update(part.encode("utf-8"))
            h.update(b"\0")
        return h.hexdigest()[:32]

    def _names(self, key: str) -> Tuple[str, str, str]:
        return (os.path.join(self.path, f"{key}.bin"),
                os.path.join(self.path, f"{key}.json"),
                os.path.join(self.path, f"{key}.commit"))

    # -- read ------------------------------------------------------------

    def load(self, key: str, expect_topology: Optional[Dict[str, Any]],
             fingerprint: Dict[str, str]) -> Optional[bytes]:
        """The verified payload bytes for ``key``, or None (a miss).
        Every rejection logs WHY — torn, corrupt, stale version, foreign
        topology — and returns None so the caller recompiles; reading
        never raises."""
        bin_p, man_p, com_p = self._names(key)
        try:
            if not os.path.exists(com_p):
                if os.path.exists(man_p) or os.path.exists(bin_p):
                    # a torn write IS a counted cache error: the metric
                    # is how an operator sees torn-write storms on a
                    # flaky store (a clean never-written key is not)
                    self._count_error()
                    logger.info(
                        "compile cache: entry %s is uncommitted (torn "
                        "write or in-flight writer) — recompiling", key)
                return None
            with open(man_p, "rb") as f:
                mbytes = f.read()
            with open(com_p, "rb") as f:
                commit = f.read().strip()
            if commit != f"{crc32c(mbytes):08x}".encode("ascii"):
                self._count_error()
                logger.warning(
                    "compile cache: entry %s commit marker does not "
                    "match its manifest — recompiling", key)
                return None
            manifest = json.loads(mbytes.decode("utf-8"))
            version = manifest.get("version", 0)
            if not isinstance(version, int) or version > ENTRY_VERSION:
                logger.warning(
                    "compile cache: entry %s has schema version %r newer "
                    "than this release (<= %d) — recompiling", key,
                    version, ENTRY_VERSION)
                return None
            if manifest.get("fingerprint") != fingerprint:
                logger.info(
                    "compile cache: entry %s was compiled under %s, this "
                    "process runs %s — version skew is a miss, "
                    "recompiling", key, manifest.get("fingerprint"),
                    fingerprint)
                return None
            if (expect_topology is not None and
                    manifest.get("topology") not in (None, expect_topology)):
                logger.info(
                    "compile cache: entry %s topology %s does not match "
                    "the resuming trainer %s — recompiling", key,
                    manifest.get("topology"), expect_topology)
                return None
            from bigdl_tpu.utils.checkpoint_manager import checksum_by_algo
            with open(bin_p, "rb") as f:
                data = f.read()
            algo = manifest.get("algo", "crc32c")
            if (len(data) != manifest.get("bytes") or
                    checksum_by_algo(algo, data) != manifest.get("checksum")):
                self._count_error()
                logger.warning(
                    "compile cache: entry %s payload fails its manifest "
                    "checksum (%d bytes) — corrupt entry skipped, "
                    "recompiling", key, len(data))
                return None
            return data
        except Exception as e:
            self._count_error()
            logger.warning(
                "compile cache: entry %s unreadable (%s: %s) — "
                "recompiling", key, type(e).__name__, e)
            return None

    def _count_error(self) -> None:
        self.errors += 1
        telemetry.counter(
            "Compile/cache_errors",
            help="corrupt/torn cache entries skipped").inc()

    # -- write -----------------------------------------------------------

    def store(self, key: str, payload: bytes, label: str,
              signature_hash: str, topology: Optional[Dict[str, Any]],
              fingerprint: Dict[str, str],
              audit: Optional[Dict[str, Any]] = None) -> bool:
        """Write one entry as a verified unit (payload → manifest →
        commit marker last) under the single-writer lock.  Returns False
        — with the executable still serving from memory — when the lock
        cannot be acquired within the backoff window or the write fails;
        a cache store must never fail a training run."""
        if self.degraded:
            return False    # disk already known full: memory-only mode
        try:
            os.makedirs(self.path, exist_ok=True)
            if not self._acquire_lock():
                logger.warning(
                    "compile cache: could not acquire the writer lock "
                    "within %.1fs — skipping the store of entry %s "
                    "(another process is writing; this process keeps "
                    "its in-memory executable)", self.lock_timeout, key)
                return False
            try:
                # C-speed payload checksum with the algo recorded (the
                # PR 2 helper: native crc32c or zlib.crc32 — the pure-
                # Python crc32c table walk would cost seconds per entry
                # against multi-MB serialized executables, on the warm
                # path this cache exists to make fast); the tiny
                # manifest↔commit cross-check below stays crc32c
                from bigdl_tpu.utils.checkpoint_manager import \
                    payload_checksum
                bin_p, man_p, com_p = self._names(key)
                algo, checksum = payload_checksum(payload)
                # chaos bit-flip AFTER the checksum: the manifest records
                # the clean payload, so only load-time verification can
                # catch the rot (the fault the injector exists to prove)
                payload = _chaos.on_compile_cache_write(key, bytes(payload))
                manifest = {
                    "version": ENTRY_VERSION,
                    "label": label,
                    "signature": signature_hash,
                    "topology": topology,
                    "fingerprint": fingerprint,
                    "algo": algo,
                    "checksum": checksum,
                    "bytes": len(payload),
                    "created": time.time(),
                }
                if audit is not None:
                    # the HLO auditor's census digest — what the offline
                    # auditor reads back; an additive key, so version 1
                    # readers without it stay loadable
                    manifest["audit"] = audit
                self._atomic_write(bin_p, payload)
                mbytes = json.dumps(manifest, sort_keys=True).encode("utf-8")
                self._atomic_write(man_p, mbytes)
                # the commit marker lands LAST: its presence is the
                # atomic "entry is whole" bit, its content cross-checks
                # the manifest
                self._atomic_write(
                    com_p, (f"{crc32c(mbytes):08x}\n").encode("ascii"))
                self.writes += 1
                self.gc()
                return True
            finally:
                self._release_lock()
        except Exception as e:
            from bigdl_tpu.resources.errors import is_storage_exhausted
            if is_storage_exhausted(e):
                # the disk is full, not flaky: latch memory-only mode so
                # every later signature skips the (pointless, multi-MB)
                # store attempt — one structured warning for the run
                self.degraded = True
                from bigdl_tpu.resources import storage as _rstorage
                _rstorage.note_degraded("compile_cache", e)
                return False
            logger.warning(
                "compile cache: store of entry %s failed (%s: %s) — "
                "continuing with the in-memory executable", key,
                type(e).__name__, e)
            return False

    @staticmethod
    def _atomic_write(path: str, data: bytes) -> None:
        # THE payload-write choke point (utils.file_io): atomic temp +
        # rename with the temp cleaned up on a failed write — a disk-full
        # mid-store must not strand multi-MB .tmp_bigdl debris per attempt
        from bigdl_tpu.utils import file_io
        file_io.write_bytes(path, data, overwrite=True)

    # -- single-writer lock ----------------------------------------------

    def _lock_path(self) -> str:
        return os.path.join(self.path, self.LOCK_NAME)

    def _acquire_lock(self) -> bool:
        """O_CREAT|O_EXCL lock file carrying pid+time, waited on with
        capped exponential backoff up to ``lockTimeoutSec``.  A lock
        older than ``lockStaleSec`` (a hard-killed writer) is stolen
        with a log line."""
        deadline = time.monotonic() + max(0.0, self.lock_timeout)
        delay = 0.05
        while True:
            try:
                fd = os.open(self._lock_path(),
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                with os.fdopen(fd, "w") as f:
                    f.write(f"{os.getpid()} {time.time()}\n")
                return True
            except FileExistsError:
                try:
                    age = time.time() - os.path.getmtime(self._lock_path())
                except OSError:
                    age = 0.0
                if age > self.lock_stale:
                    # steal by ATOMIC rename: of N waiters that all saw
                    # the lock go stale, exactly one wins the rename —
                    # the losers' renames fail and they go back to the
                    # fair O_CREAT|O_EXCL race, so a freshly re-created
                    # lock can never be unlinked by a second stealer
                    grave = f"{self._lock_path()}.stale.{os.getpid()}"
                    try:
                        os.rename(self._lock_path(), grave)
                    except OSError:
                        # lost the steal race — or rename persistently
                        # fails (read-only store): fall through to the
                        # deadline+backoff below, NEVER a bare continue
                        # (that would busy-spin unbounded with no
                        # watchdog covering the store path)
                        pass
                    else:
                        logger.warning(
                            "compile cache: stole a stale writer lock "
                            "(%.0fs old — a hard-killed writer left "
                            "it)", age)
                        try:
                            os.unlink(grave)
                        except OSError:  # pragma: no cover - gone
                            pass
                        continue
                if time.monotonic() >= deadline:
                    return False
                _sleep(min(delay, 1.0))
                delay *= 2

    def _release_lock(self) -> None:
        try:
            os.unlink(self._lock_path())
        except OSError:  # pragma: no cover - already gone
            pass

    # -- retention -------------------------------------------------------

    def entries(self) -> List[Tuple[float, str]]:
        """(created, key) for every COMMITTED entry, newest first (the
        manifest's recorded creation time orders retention; an entry
        whose manifest is unreadable sorts oldest — first to go)."""
        out: List[Tuple[float, str]] = []
        try:
            names = os.listdir(self.path)
        except OSError:
            return out
        for f in names:
            if not f.endswith(".commit"):
                continue
            key = f[:-len(".commit")]
            created = 0.0
            try:
                with open(os.path.join(self.path, f"{key}.json")) as mf:
                    created = float(json.load(mf).get("created", 0.0))
            except Exception:
                pass
            out.append((created, key))
        out.sort(reverse=True)
        return out

    def gc(self) -> None:
        """Keep the ``keep_last`` newest committed entries; drop the rest
        commit-marker FIRST (an interrupted GC leaves an uncommitted —
        ignored — entry, never a committed half-entry), manifest last."""
        if not self.keep_last or self.keep_last <= 0:
            return
        for _, key in self.entries()[self.keep_last:]:
            bin_p, man_p, com_p = self._names(key)
            for p in (com_p, bin_p, man_p):
                try:
                    os.unlink(p)
                except OSError:
                    pass


# ---- the tracked step wrapper ---------------------------------------------


def _signature_hash(args: Tuple) -> str:
    """Stable hex hash of the PR 4 abstract signature (pytree structure
    + per-leaf shape/dtype/weak-type) — the part of the cache key shared
    with the retrace sentinel's diagnosis."""
    from bigdl_tpu.analysis.retrace import abstract_signature
    treedef, sigs = abstract_signature(args)
    h = hashlib.sha256()
    h.update(repr(treedef).encode("utf-8"))
    h.update(repr(sigs).encode("utf-8"))
    return h.hexdigest()[:16]


def _spec_of(x):
    """ShapeDtypeStruct mirror of one argument leaf (keeps an explicit
    sharding so AOT bucket variants lower with the placement the
    concrete batches will arrive in)."""
    import jax
    sharding = getattr(x, "sharding", None)
    if sharding is not None:
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)
    return jax.ShapeDtypeStruct(x.shape, x.dtype)


class CachedStep:
    """One fused step: jitted inside this module (the ONE registered
    ``jax.jit`` wrapper the ``untracked-jit`` lint rule allows),
    executed exclusively through explicitly compiled executables.

    Per distinct abstract signature the flow is: lower (trace) → try the
    persistent cache (verified load + deserialize) → else compile — each
    phase telemetry-spanned and supervised by the compile watchdog —
    then store the serialized executable back (single-writer lock).
    After warmup every call is a dictionary lookup plus the executable
    dispatch; nothing ever re-enters jit's implicit trace-and-compile.

    ``bucket_argnums`` arms AOT bucket precompilation: on the first miss
    of a new signature family, every ``bigdl.compile.buckets`` variant
    (leaf dim 0 of the named args re-bucketed) is compiled ahead and
    registered with the attached retrace sentinel, so a bucketed
    validation/predict run hits only pre-compiled signatures.
    """

    def __init__(self, jitted, label: str,
                 topology: Optional[Dict[str, Any]] = None,
                 cache: Optional[CompileCache] = None,
                 bucket_argnums: Sequence[int] = (),
                 contract=None):
        self._jitted = jitted
        self.label = label
        self.topology = topology
        #: the StepContract the HLO auditor checks every lowered
        #: program of this step against (None = lookup by label)
        self.contract = contract
        self._cache = cache if cache is not None else CompileCache.from_config()
        self.bucket_argnums = tuple(bucket_argnums)
        self.sentinel = None          # retrace sentinel fed by precompiles
        self._mem: Dict[Any, Any] = {}   # signature key -> loaded executable
        #: signature families seen (bucket-arg batch dims erased): the
        #: in-plan test for batch sizes beyond the largest bucket, which
        #: round to multiples the precompiler cannot enumerate ahead
        self._families: set = set()
        self.compiles = 0
        self.cache_hits = 0
        self.cache_misses = 0
        #: per-signature provenance: {signature, source: compile|cache,
        #: trace_ms, compile_ms|load_ms} — the bench leg's raw material
        self.timings: List[Dict[str, Any]] = []

    # the MFU probe lowers the step for cost_analysis only — pass through
    def lower(self, *args):
        return self._jitted.lower(*args)

    def register_sentinel(self, sentinel) -> None:
        """Attach the retrace sentinel whose seen-set AOT bucket
        precompiles should pre-populate (see ``RetraceSentinel.
        register_warmup``)."""
        self.sentinel = sentinel

    # -- execution --------------------------------------------------------

    def _sig_key(self, args: Tuple):
        from bigdl_tpu.analysis.retrace import abstract_signature
        return abstract_signature(args)

    def __call__(self, *args):
        key = self._sig_key(args)
        exe = self._mem.get(key)
        if exe is None:
            exe = self._compile_entry(args, key)
        return self._dispatch(exe, args)

    def call_with_signature(self, args: Tuple, key):
        """Dispatch with a signature the caller already computed — the
        retrace sentinel observes every call with the identical
        ``abstract_signature`` walk, so its wrapper hands the key down
        instead of this step walking the argument tree a second time
        per iteration."""
        exe = self._mem.get(key)
        if exe is None:
            exe = self._compile_entry(args, key)
        return self._dispatch(exe, args)

    def _dispatch(self, exe, args: Tuple):
        """Execute through the RESOURCE_EXHAUSTED classifier: a real XLA
        allocation failure — or the ``oomStepAt`` injector's replica,
        raised BEFORE execution so device state is untouched — surfaces
        as the structured :class:`DeviceMemoryError` the driver's
        microbatch re-plan keys on."""
        try:
            _chaos.take_oom_dispatch(self.label)
            return exe(*args)
        except Exception as e:
            from bigdl_tpu.resources.device import classify_dispatch_error
            err = classify_dispatch_error(e, self.label)
            if err is not None:
                raise err from e
            raise

    def warmup(self, *args) -> None:
        """AOT: make sure the executable for this signature exists
        (compile or cache-load) WITHOUT executing it — the driver's
        explicit warmup phase before step 1."""
        key = self._sig_key(args)
        if key not in self._mem:
            self._compile_entry(args, key)

    @property
    def warm(self) -> bool:
        return bool(self._mem)

    # -- the miss path ----------------------------------------------------

    def _compile_entry(self, args: Tuple, key, precompile: bool = True):
        import jax
        from jax.experimental import serialize_executable as _se

        sig_hash = _signature_hash(args)
        diagnosis = {"label": self.label, "signature": sig_hash,
                     "topology": self.topology}
        timeout = compile_timeout()

        with telemetry.span(f"compile/{self.label}", signature=sig_hash):
            t0 = telemetry.clock_ns()
            try:
                with compile_watchdog(self.label, "trace", timeout,
                                      diagnosis):
                    lowered = self._jitted.lower(*args)
            except CompileTimeoutError as e:
                raise self._diagnosed(e, "trace", timeout, diagnosis)
            trace_ms = (telemetry.clock_ns() - t0) / 1e6
            telemetry.gauge("Compile/trace_ms").set(trace_ms)

            from bigdl_tpu.analysis import hlo_audit
            audit_armed = hlo_audit.armed()
            fingerprint = backend_fingerprint()
            exe = None
            cache_key = None
            hlo = None
            if self._cache is not None or audit_armed:
                # the StableHLO text digest keys the entry exactly (and
                # the armed auditor scans the same text); the executable
                # is only worth serializing (tens of MB for big steps)
                # when a persistent cache will actually consume it
                try:
                    with compile_watchdog(self.label, "trace", timeout,
                                          diagnosis):
                        hlo = lowered.as_text()
                except CompileTimeoutError as e:
                    raise self._diagnosed(e, "trace", timeout, diagnosis)
            if self._cache is not None:
                hlo_digest = hashlib.sha256(
                    hlo.encode("utf-8")).hexdigest()
                cache_key = CompileCache.entry_key(
                    self.label, sig_hash, hlo_digest, self.topology,
                    fingerprint)
                exe = self._try_cache_load(cache_key, fingerprint, timeout,
                                           diagnosis, _se)
            loaded = exe is not None
            if exe is None:
                if self._cache is not None:
                    self._count_miss()
                t1 = telemetry.clock_ns()
                try:
                    with compile_watchdog(self.label, "compile", timeout,
                                          diagnosis):
                        _chaos.on_compile(self.label)
                        exe = lowered.compile()
                except CompileTimeoutError as e:
                    raise self._diagnosed(e, "compile", timeout, diagnosis)
                compile_ms = (telemetry.clock_ns() - t1) / 1e6
                telemetry.gauge("Compile/compile_ms").set(compile_ms)
                self.compiles += 1
                self.timings.append({
                    "signature": sig_hash, "source": "compile",
                    "trace_ms": round(trace_ms, 3),
                    "compile_ms": round(compile_ms, 3)})
                logger.info(
                    "Compiled fused step %r (signature %s): trace "
                    "%.0f ms, compile %.0f ms%s", self.label, sig_hash,
                    trace_ms, compile_ms,
                    "" if self._cache is None else " — caching")
            audit_summary = None
            if audit_armed and hlo is not None:
                # audit BEFORE the store: a contract-violating program
                # must never enter the persistent cache, and the census
                # rides in the entry manifest for the offline auditor
                report = hlo_audit.audit_step(
                    self.label, hlo, compiled=exe, contract=self.contract,
                    topology=self.topology)
                audit_summary = report.census.summary()
                report.raise_or_warn()
            # HBM preflight BEFORE the first dispatch (and before the
            # store): with bigdl.resources.deviceMemBudgetMB set, a step
            # whose peak-buffer estimate cannot fit raises the structured
            # DeviceMemoryError while training state is still untouched —
            # the driver answers with a microbatch re-plan
            from bigdl_tpu.resources.device import preflight as _preflight
            _preflight(exe, self.label)
            if (not loaded and self._cache is not None
                    and cache_key is not None):
                self._store(cache_key, exe, sig_hash, fingerprint, _se,
                            audit=audit_summary)
        self._mem[key] = exe
        if self.bucket_argnums:
            self._families.add(self._family_key(args))
        if precompile and self.bucket_argnums:
            self._precompile_buckets(args)
        return exe

    @staticmethod
    def _diagnosed(e: CompileTimeoutError, phase: str, timeout: float,
                   diagnosis: Dict[str, Any]) -> CompileTimeoutError:
        """The async-raised instance is bare (no-arg constructed by
        PyThreadState_SetAsyncExc) — return a fully-diagnosed one to
        re-raise in its place; an already-diagnosed instance passes
        through."""
        if e.args:
            return e
        return CompileTimeoutError(diagnosis.get("label", "?"), phase,
                                   timeout, diagnosis)

    def _count_hit(self) -> None:
        self.cache_hits += 1
        if self._cache is not None:
            self._cache.hits += 1
        telemetry.counter("Compile/cache_hits",
                          help="fused-step executables loaded, not "
                               "compiled").inc()

    def _count_miss(self) -> None:
        self.cache_misses += 1
        if self._cache is not None:
            self._cache.misses += 1
        telemetry.counter("Compile/cache_misses",
                          help="fused-step signatures compiled fresh").inc()

    def _try_cache_load(self, cache_key: str, fingerprint: Dict[str, str],
                        timeout: float, diagnosis: Dict[str, Any], _se):
        """Verified load + deserialize, watchdog-supervised.  EVERY
        failure mode here — corrupt payload, unpicklable blob, a wedged
        deserialization aborted by the watchdog — degrades to a fresh
        compile; a cache can slow a start, never kill one."""
        data = self._cache.load(cache_key, self.topology, fingerprint)
        if data is None:
            return None
        t0 = telemetry.clock_ns()
        try:
            with telemetry.span(f"compile/cache_load/{self.label}"):
                with compile_watchdog(self.label, "cache_load", timeout,
                                      diagnosis):
                    payload, in_tree, out_tree = pickle.loads(data)
                    exe = _se.deserialize_and_load(payload, in_tree,
                                                   out_tree)
        except Exception as e:
            logger.warning(
                "compile cache: entry %s failed to deserialize (%s: %s) "
                "— falling back to a fresh compile", cache_key,
                type(e).__name__, e)
            telemetry.counter(
                "Compile/cache_errors",
                help="corrupt/torn cache entries skipped").inc()
            return None
        load_ms = (telemetry.clock_ns() - t0) / 1e6
        telemetry.gauge("Compile/load_ms").set(load_ms)
        self._count_hit()
        self.timings.append({"signature": cache_key, "source": "cache",
                             "load_ms": round(load_ms, 3)})
        logger.info(
            "Warm start: fused step %r loaded from the compile cache "
            "in %.0f ms (entry %s) — no XLA compile", self.label,
            load_ms, cache_key)
        return exe

    def _store(self, cache_key: str, exe, sig_hash: str,
               fingerprint: Dict[str, str], _se,
               audit: Optional[Dict[str, Any]] = None) -> None:
        try:
            payload = pickle.dumps(_se.serialize(exe),
                                   protocol=pickle.HIGHEST_PROTOCOL)
        except Exception as e:
            logger.warning(
                "compile cache: executable for %r is not serializable "
                "on this backend (%s: %s) — cache disabled for this "
                "entry", self.label, type(e).__name__, e)
            return
        self._cache.store(cache_key, payload, self.label, sig_hash,
                          self.topology, fingerprint, audit=audit)

    def _family_key(self, args: Tuple):
        """The signature with the batch dim of every bucket-arg leaf
        erased: two calls are in the same FAMILY when they differ only
        by (bucketed) batch size.  Anything else — dtype drift, a
        spatial-shape change, a different params tree — is a different
        family and stays subject to the retrace gate."""
        import jax
        from bigdl_tpu.analysis.retrace import _leaf_sig
        out = []
        for i, a in enumerate(args):
            leaves, td = jax.tree_util.tree_flatten(a)
            drop0 = i in self.bucket_argnums
            sigs = []
            for x in leaves:
                s = _leaf_sig(x)
                if drop0 and isinstance(s[0], tuple) and len(s[0]) >= 1:
                    s = (s[0][1:], s[1], s[2])
                sigs.append(s)
            out.append((repr(td), tuple(sigs)))
        return tuple(out)

    def _bucket_dim(self, args: Tuple) -> Optional[int]:
        import jax
        for arg in (args[i] for i in self.bucket_argnums
                    if i < len(args)):
            for leaf in jax.tree_util.tree_leaves(arg):
                if getattr(leaf, "ndim", 0) >= 1:
                    return int(leaf.shape[0])
        return None

    def register_if_bucketed(self, args: Tuple, key=None) -> None:
        """In-plan pre-check the sentinel wrapper runs BEFORE observing:
        a new signature whose batch dim is exactly a bucket-plan size
        (``bucket_size(n) == n`` — including the multiples of the
        largest bucket the choke points legitimately produce for
        oversize batches, which :meth:`_precompile_buckets` cannot
        enumerate ahead) AND whose family is already known is part of
        the bucket plan: it registers as a warmup compile instead of
        raising as a post-warmup retrace.  A signature differing in
        anything but the bucketed batch dim is a new family and still
        trips the gate."""
        if self.sentinel is None or not self.bucket_argnums:
            return
        buckets = configured_buckets()
        if not buckets:
            return
        if key is None:
            key = self._sig_key(args)
        if key in self._mem:
            return
        n = self._bucket_dim(args)
        if n is None or bucket_size(n, buckets) != n:
            return
        if self._family_key(args) in self._families:
            self.sentinel.register_warmup(args)

    # -- AOT bucket variants ----------------------------------------------

    def _precompile_buckets(self, args: Tuple) -> None:
        """Compile every configured bucket variant of this signature
        family ahead of time (dim 0 of the ``bucket_argnums`` args
        re-bucketed), registering each with the retrace sentinel so a
        later concrete call with that signature is a warm in-memory hit
        — never a post-warmup retrace."""
        import jax
        buckets = configured_buckets()
        if not buckets:
            return
        base = None
        for arg in (args[i] for i in self.bucket_argnums
                    if i < len(args)):
            for leaf in jax.tree_util.tree_leaves(arg):
                if getattr(leaf, "ndim", 0) >= 1:
                    base = int(leaf.shape[0])
                    break
            if base is not None:
                break
        if base is None:
            return
        for b in buckets:
            if b == base:
                continue
            spec_args = self._bucket_spec_args(args, b)
            key = self._sig_key(spec_args)
            if key in self._mem:
                continue
            try:
                self._compile_entry(spec_args, key, precompile=False)
            except CompileTimeoutError:
                raise          # a wedged precompile is still an abort
            except Exception as e:
                # a variant this step cannot lower (e.g. a bucket not
                # divisible by the eval mesh's data axis — those batches
                # run the local fallback forward anyway) is skipped, not
                # fatal; it stays OUT of the sentinel's warm set
                logger.info(
                    "compile cache: bucket-%d variant of %r not "
                    "precompilable (%s: %s) — skipped", b, self.label,
                    type(e).__name__, e)
                continue
            if self.sentinel is not None:
                self.sentinel.register_warmup(spec_args)
        # the triggering signature itself is part of the warm set
        if self.sentinel is not None:
            self.sentinel.register_warmup(args)

    def _bucket_spec_args(self, args: Tuple, bucket: int) -> Tuple:
        import jax

        def vary(x):
            if getattr(x, "ndim", 0) >= 1:
                spec = _spec_of(x)
                return jax.ShapeDtypeStruct(
                    (bucket,) + tuple(spec.shape[1:]), spec.dtype,
                    sharding=getattr(spec, "sharding", None))
            return _spec_of(x)

        def plain(x):
            # non-bucket args (params, module state) lower with their
            # sharding UNSPECIFIED: a concrete uncommitted array's
            # .sharding reads as committed-to-one-device in a spec,
            # which falsely conflicts with the mesh-sharded batch — the
            # primary concrete lowering never had that problem because
            # uncommitted arrays are free to move
            if hasattr(x, "shape") and hasattr(x, "dtype"):
                return jax.ShapeDtypeStruct(x.shape, x.dtype)
            return x

        out = []
        for i, a in enumerate(args):
            if i in self.bucket_argnums:
                out.append(jax.tree_util.tree_map(vary, a))
            else:
                out.append(jax.tree_util.tree_map(plain, a))
        return tuple(out)


def tracked_jit(fn, label: str, topology: Optional[Dict[str, Any]] = None,
                cache: Optional[CompileCache] = None,
                bucket_argnums: Sequence[int] = (),
                contract=None, **jit_kwargs) -> CachedStep:
    """``jax.jit`` + :class:`CachedStep` in one call — THE registered
    entry point for fused-step compilation (the ``untracked-jit`` lint
    rule flags any ``jax.jit``/``.lower()``/``.compile()`` outside this
    module).  ``contract`` is the step's program contract
    (:class:`~bigdl_tpu.analysis.program_contracts.StepContract`) —
    declared in the live registry and checked by the HLO auditor on
    every compile/cache-load.  ``jit_kwargs`` pass through to
    ``jax.jit`` (``donate_argnums``, ``out_shardings``, ...)."""
    import jax
    if contract is not None:
        from bigdl_tpu.analysis import program_contracts
        program_contracts.declare(contract)
    return CachedStep(jax.jit(fn, **jit_kwargs), label=label,
                      topology=topology, cache=cache,
                      bucket_argnums=bucket_argnums, contract=contract)
