"""Persistence: save/load of modules, optim methods, and arbitrary objects.

Reference equivalent: ``utils/File.scala:25`` — java-serialization to
local/HDFS/S3 paths (``save:67``, ``saveToHdfs:106``, ``load:162``).

Local paths pickle directly (atomic temp-file + rename).  Remote schemes
(``hdfs://``, ``s3://``, ``gs://``, …) dispatch through fsspec, which maps
each scheme to its filesystem client (pyarrow-HDFS, s3fs, …) and raises a
clear error naming the missing client when one is not installed.

Every payload write funnels through :func:`write_bytes` — the single choke
point where (a) atomic temp-file + rename semantics live, (b) the chaos
harness (``utils.chaos``) may inject torn/truncated/transient write faults,
and (c) the transient-error retry wraps remote operations: a network blip
on ``hdfs://``/``s3://`` is retried with bounded exponential backoff
(``bigdl.io.retryTimes`` / ``bigdl.io.retryInterval``) instead of aborting
a checkpoint.  Non-transient failures (missing files, permission errors,
exists-with-overwrite-False) are never retried.
"""

from __future__ import annotations

import logging
import os
import pickle
import tempfile
import time
import uuid
from typing import Any

from bigdl_tpu.utils import chaos

logger = logging.getLogger("bigdl_tpu")

_REMOTE_SCHEMES = ("hdfs://", "s3://", "s3a://", "s3n://", "gs://",
                   "abfs://", "http://", "https://", "memory://")

#: injectable for tests (no real sleeping in tier-1)
_sleep = time.sleep

#: OSError subclasses that indicate a *state* problem, not an
#: infrastructure blip — retrying cannot help and may mask bugs.
_NON_TRANSIENT = (FileNotFoundError, FileExistsError, IsADirectoryError,
                  NotADirectoryError, PermissionError)


def _is_transient(e: BaseException) -> bool:
    if getattr(e, "fatal", False):   # chaos "writer died" simulation,
        return False                 # storage exhaustion
    return (isinstance(e, (OSError, TimeoutError)) and
            not isinstance(e, _NON_TRANSIENT))


def _reraise_classified(e: BaseException, path: str):
    """Re-raise a write failure, folding raw ENOSPC/EDQUOT (real or
    chaos-injected) into the structured ``StorageExhaustedError`` the
    degradation paths key on.  A full disk is not a blip: the classified
    error is ``fatal`` so the transient retry never absorbs it."""
    from bigdl_tpu.resources.errors import (StorageExhaustedError,
                                            is_storage_exhausted)
    if not isinstance(e, StorageExhaustedError) and is_storage_exhausted(e):
        raise StorageExhaustedError(path, e) from e
    raise e


def retrying(fn, *args, op: str = ""):
    """Run ``fn(*args)`` with the bounded capped-backoff transient-error
    retry (``bigdl.io.retryTimes`` / ``bigdl.io.retryInterval``).  The
    shared transient-IO policy: every remote operation in this module
    funnels through it, and the streaming-ingest reader stage wraps its
    record fetches in it so a storage blip mid-epoch costs a delay, not
    a training run.  Non-transient failures (missing files, permission
    errors, anything marked ``fatal`` — chaos data faults) are never
    retried."""
    from bigdl_tpu.utils import config
    attempts = max(1, config.get_int("bigdl.io.retryTimes", 3))
    base = config.get_float("bigdl.io.retryInterval", 0.1)
    for attempt in range(1, attempts + 1):
        try:
            return fn(*args)
        except Exception as e:
            if attempt >= attempts or not _is_transient(e):
                raise
            delay = base * (2.0 ** (attempt - 1))
            logger.warning(
                "transient %s failure (attempt %d/%d, retrying in %.2fs): "
                "%r", op or getattr(fn, "__name__", "io"), attempt,
                attempts, delay, e)
            _sleep(delay)


#: internal alias kept for the module's own call sites
_retrying = retrying


def _is_remote(path: str) -> bool:
    return path.startswith(_REMOTE_SCHEMES)


def _dealias(path: str) -> str:
    """s3a/s3n are hadoop aliases for s3."""
    for alias in ("s3a://", "s3n://"):
        if path.startswith(alias):
            return "s3://" + path[len(alias):]
    return path


def _fs(path: str):
    """(filesystem, in-fs path) for a remote scheme via fsspec."""
    try:
        import fsspec
    except ImportError as e:  # pragma: no cover - fsspec is in the image
        raise NotImplementedError(
            f"remote filesystem scheme in {path!r} needs fsspec "
            "(reference: utils/File.scala:106)") from e
    fs, fpath = fsspec.core.url_to_fs(_dealias(path))
    return fs, fpath


def makedirs(path: str) -> None:
    """Directory creation for local or remote checkpoint roots
    (reference checkpoints live under an HDFS dir, ``File.scala:106``)."""
    if _is_remote(path):
        fs, p = _fs(path)
        _retrying(lambda: fs.makedirs(p, exist_ok=True), op="makedirs")
        return
    if path.startswith("file://"):
        path = path[len("file://"):]
    os.makedirs(path, exist_ok=True)


def listdir(path: str):
    """Base names under a local or remote directory; [] when absent."""
    if _is_remote(path):
        fs, p = _fs(path)

        def _ls():
            if not fs.exists(p):
                return []
            return [e.rstrip("/").rsplit("/", 1)[-1]
                    for e in fs.ls(p, detail=False)]

        return _retrying(_ls, op="listdir")
    if path.startswith("file://"):
        path = path[len("file://"):]
    if not os.path.isdir(path):
        return []
    return os.listdir(path)


def join(base: str, *parts: str) -> str:
    """Path join that keeps remote scheme separators."""
    if _is_remote(base) or base.startswith("file://"):
        return "/".join([base.rstrip("/")] + [p.strip("/") for p in parts])
    return os.path.join(base, *parts)


def size(path: str):
    """Byte size of a local or remote object, or ``None`` when the store
    cannot report one.  Lets callers verify a payload against its
    manifest-recorded length with one stat instead of a full read —
    truncation (the realistic torn-write mode: the rename still commits
    a short object) is caught without transferring multi-GB snapshots."""
    try:
        if _is_remote(path):
            fs, p = _fs(path)
            return int(_retrying(lambda: fs.size(p), op="size"))
        if path.startswith("file://"):
            path = path[len("file://"):]
        return os.path.getsize(path)
    except Exception:
        return None


def _write_bytes_remote(path: str, data: bytes, overwrite: bool) -> None:
    fs, p = _fs(path)
    if not overwrite and fs.exists(p):
        raise FileExistsError(f"{path} already exists and overwrite is "
                              "False (reference File.scala overWrite)")
    # write-then-rename, mirroring the local atomic path: a crash
    # mid-write must not leave a truncated snapshot that restore would
    # pick as the newest and retry-load forever.  The temp name is
    # unique per process: on a shared store two writers racing on the
    # same destination must never mv each other's half-written temp
    tmp = f"{p}.tmp_bigdl.{os.getpid()}.{uuid.uuid4().hex[:8]}"
    try:
        payload = chaos.on_write(path, data)
    except BaseException as e:
        partial = getattr(e, "partial", None)
        if partial is not None:
            # a "writer died mid-write": the torn temp stays behind,
            # exactly like a hard-killed process would leave it
            with fs.open(tmp, "wb") as f:
                f.write(partial)
        raise
    try:
        chaos.take_disk_full(path)
        with fs.open(tmp, "wb") as f:
            f.write(payload)
        fs.mv(tmp, p)
    except BaseException as e:
        try:
            if fs.exists(tmp):
                fs.rm(tmp)
        except Exception:
            pass
        _reraise_classified(e, path)


def write_bytes(path: str, data: bytes, overwrite: bool = True) -> None:
    """Atomically write ``data`` to a local or remote path (temp file +
    rename).  The single payload-write choke point: chaos injection and
    the remote transient retry both live here."""
    data = bytes(data)
    if _is_remote(path):
        _retrying(_write_bytes_remote, path, data, overwrite,
                  op="write")
        return
    if path.startswith("file://"):
        path = path[len("file://"):]
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(
            f"{path} already exists and overwrite is False "
            "(reference File.scala overWrite check)")
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp_bigdl_")
    try:
        payload = chaos.on_write(path, data)
    except BaseException as e:
        partial = getattr(e, "partial", None)
        if partial is not None:
            with os.fdopen(fd, "wb") as f:
                f.write(partial)
        else:
            os.close(fd)
            os.unlink(tmp)
        raise
    try:
        chaos.take_disk_full(path)
    except BaseException as e:
        os.close(fd)            # fdopen below never adopted it
        os.unlink(tmp)
        _reraise_classified(e, path)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)
    except BaseException as e:
        if os.path.exists(tmp):
            os.unlink(tmp)
        _reraise_classified(e, path)


def read_bytes(path: str) -> bytes:
    """Read a local or remote object fully into memory."""
    if _is_remote(path):
        fs, p = _fs(path)

        def _read():
            with fs.open(p, "rb") as f:
                return f.read()

        return _retrying(_read, op="read")
    if path.startswith("file://"):
        path = path[len("file://"):]
    with open(path, "rb") as f:
        return f.read()


def save(obj: Any, path: str, overwrite: bool = True) -> None:
    """Serialize ``obj`` to ``path`` (reference ``File.save:67`` /
    ``saveToHdfs:106``).  Atomic on local and remote paths alike."""
    write_bytes(path, pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL),
                overwrite)


def modified_time(path: str):
    """Last-modified POSIX timestamp of a local or remote object, or
    ``None`` when the backing filesystem cannot report one.  Used to
    age-gate sweeps of orphaned atomic-write temps: a temp younger than
    the gate may belong to a live writer elsewhere."""
    try:
        if _is_remote(path):
            fs, p = _fs(path)
            mt = fs.modified(p)
            return mt.timestamp()
        if path.startswith("file://"):
            path = path[len("file://"):]
        return os.path.getmtime(path)
    except Exception:
        return None


def remove(path: str) -> None:
    """Delete a local or remote object; silently absent-tolerant (used to
    sweep orphaned atomic-write temps left by hard-killed writers)."""
    if _is_remote(path):
        fs, p = _fs(path)

        def _rm():
            if fs.exists(p):
                fs.rm(p)

        _retrying(_rm, op="remove")
        return
    if path.startswith("file://"):
        path = path[len("file://"):]
    if os.path.exists(path):
        os.unlink(path)


def load(path: str) -> Any:
    """Deserialize from ``path`` (reference ``File.load:162``)."""
    return pickle.loads(read_bytes(path))
