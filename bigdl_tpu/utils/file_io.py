"""Persistence: save/load of modules, optim methods, and arbitrary objects.

Reference equivalent: ``utils/File.scala:25`` — java-serialization to
local/HDFS/S3 paths (``save:67``, ``saveToHdfs:106``, ``load:162``).

Local paths pickle directly (atomic temp-file + rename).  Remote schemes
(``hdfs://``, ``s3://``, ``gs://``, …) dispatch through fsspec, which maps
each scheme to its filesystem client (pyarrow-HDFS, s3fs, …) and raises a
clear error naming the missing client when one is not installed.
"""

from __future__ import annotations

import os
import pickle
import tempfile
import uuid
from typing import Any

_REMOTE_SCHEMES = ("hdfs://", "s3://", "s3a://", "s3n://", "gs://",
                   "abfs://", "http://", "https://", "memory://")


def _is_remote(path: str) -> bool:
    return path.startswith(_REMOTE_SCHEMES)


def _dealias(path: str) -> str:
    """s3a/s3n are hadoop aliases for s3."""
    for alias in ("s3a://", "s3n://"):
        if path.startswith(alias):
            return "s3://" + path[len(alias):]
    return path


def _fs(path: str):
    """(filesystem, in-fs path) for a remote scheme via fsspec."""
    import fsspec
    fs, fpath = fsspec.core.url_to_fs(_dealias(path))
    return fs, fpath


def makedirs(path: str) -> None:
    """Directory creation for local or remote checkpoint roots
    (reference checkpoints live under an HDFS dir, ``File.scala:106``)."""
    if _is_remote(path):
        fs, p = _fs(path)
        fs.makedirs(p, exist_ok=True)
        return
    if path.startswith("file://"):
        path = path[len("file://"):]
    os.makedirs(path, exist_ok=True)


def listdir(path: str):
    """Base names under a local or remote directory; [] when absent."""
    if _is_remote(path):
        fs, p = _fs(path)
        if not fs.exists(p):
            return []
        return [e.rstrip("/").rsplit("/", 1)[-1]
                for e in fs.ls(p, detail=False)]
    if path.startswith("file://"):
        path = path[len("file://"):]
    if not os.path.isdir(path):
        return []
    return os.listdir(path)


def join(base: str, *parts: str) -> str:
    """Path join that keeps remote scheme separators."""
    if _is_remote(base) or base.startswith("file://"):
        return "/".join([base.rstrip("/")] + [p.strip("/") for p in parts])
    return os.path.join(base, *parts)


def _fsspec_open(path: str, mode: str):
    try:
        import fsspec
    except ImportError as e:  # pragma: no cover - fsspec is in the image
        raise NotImplementedError(
            f"remote filesystem scheme in {path!r} needs fsspec "
            "(reference: utils/File.scala:106)") from e
    return fsspec.open(_dealias(path), mode)


def save(obj: Any, path: str, overwrite: bool = True) -> None:
    """Serialize ``obj`` to ``path`` (reference ``File.save:67`` /
    ``saveToHdfs:106``).  Local writes are atomic (temp file + rename)."""
    if _is_remote(path):
        fs, p = _fs(path)
        if not overwrite and fs.exists(p):
            raise FileExistsError(f"{path} already exists and overwrite is "
                                  "False (reference File.scala overWrite)")
        # write-then-rename, mirroring the local atomic path: a crash
        # mid-write must not leave a truncated snapshot that
        # Checkpoint.latest() would pick as the newest and retry-load
        # forever.  The temp name is unique per process: on a shared
        # store two writers racing on the same destination must never
        # mv each other's half-written temp
        tmp = f"{p}.tmp_bigdl.{os.getpid()}.{uuid.uuid4().hex[:8]}"
        try:
            with fs.open(tmp, "wb") as f:
                pickle.dump(obj, f, protocol=pickle.HIGHEST_PROTOCOL)
            fs.mv(tmp, p)
        except BaseException:
            try:
                if fs.exists(tmp):
                    fs.rm(tmp)
            except Exception:
                pass
            raise
        return
    if path.startswith("file://"):
        path = path[len("file://"):]
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(
            f"{path} already exists and overwrite is False "
            "(reference File.scala overWrite check)")
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp_bigdl_")
    try:
        with os.fdopen(fd, "wb") as f:
            pickle.dump(obj, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def modified_time(path: str):
    """Last-modified POSIX timestamp of a local or remote object, or
    ``None`` when the backing filesystem cannot report one.  Used to
    age-gate sweeps of orphaned atomic-write temps: a temp younger than
    the gate may belong to a live writer elsewhere."""
    try:
        if _is_remote(path):
            fs, p = _fs(path)
            mt = fs.modified(p)
            return mt.timestamp()
        if path.startswith("file://"):
            path = path[len("file://"):]
        return os.path.getmtime(path)
    except Exception:
        return None


def remove(path: str) -> None:
    """Delete a local or remote object; silently absent-tolerant (used to
    sweep orphaned atomic-write temps left by hard-killed writers)."""
    if _is_remote(path):
        fs, p = _fs(path)
        if fs.exists(p):
            fs.rm(p)
        return
    if path.startswith("file://"):
        path = path[len("file://"):]
    if os.path.exists(path):
        os.unlink(path)


def load(path: str) -> Any:
    """Deserialize from ``path`` (reference ``File.load:162``)."""
    if _is_remote(path):
        with _fsspec_open(path, "rb") as f:
            return pickle.load(f)
    if path.startswith("file://"):
        path = path[len("file://"):]
    with open(path, "rb") as f:
        return pickle.load(f)
