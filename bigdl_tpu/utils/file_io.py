"""Persistence: save/load of modules, optim methods, and arbitrary objects.

Reference equivalent: ``utils/File.scala:25`` — java-serialization to
local/HDFS/S3 paths.  Here: pickle to local paths (HDFS/S3 support is gated on
optional deps; fsspec-style schemes raise a clear error when unavailable —
this image is egress-free, so remote filesystems cannot be exercised anyway).

Checkpoint layout matches the reference protocol
(``optim/DistriOptimizer.scala:394-416``): ``model.<neval>`` /
``optimMethod.<neval>`` files in a checkpoint directory.
"""

from __future__ import annotations

import os
import pickle
import tempfile
from typing import Any


def _check_scheme(path: str) -> str:
    if path.startswith(("hdfs://", "s3://", "s3a://", "s3n://")):
        raise NotImplementedError(
            f"remote filesystem scheme in {path!r}: HDFS/S3 persistence "
            "requires the corresponding filesystem client which is not "
            "available in this environment (reference: utils/File.scala:106)")
    if path.startswith("file://"):
        path = path[len("file://"):]
    return path


def save(obj: Any, path: str, overwrite: bool = True) -> None:
    """Serialize ``obj`` to ``path`` (reference ``File.save:67``).

    Writes atomically: temp file in the same directory, then rename.
    """
    path = _check_scheme(path)
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(
            f"{path} already exists and overwrite is False "
            "(reference File.scala overWrite check)")
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp_bigdl_")
    try:
        with os.fdopen(fd, "wb") as f:
            pickle.dump(obj, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load(path: str) -> Any:
    """Deserialize from ``path`` (reference ``File.load:162``)."""
    path = _check_scheme(path)
    with open(path, "rb") as f:
        return pickle.load(f)
