"""LoggerFilter: console-noise redirection to a log file.

Reference equivalent: ``utils/LoggerFilter.scala:34`` — log4j configuration
that keeps the console at ERROR for chatty frameworks while appending
everything to ``bigdl.log``; invoked at the top of every Train main.

Properties (reference ``bigdl.utils.LoggerFilter.*``):
- ``bigdl.utils.LoggerFilter.disable``    — leave logging untouched
- ``bigdl.utils.LoggerFilter.logFile``    — path (default ./bigdl.log)
- ``bigdl.utils.LoggerFilter.enableSparkLog`` — here: whether chatty
  third-party loggers (jax/tensorflow) also go to the file
- ``bigdl.utils.LoggerFilter.maxBytes``   — rotate the log file once it
  reaches this size (default 10 MiB; 0 disables rotation)
- ``bigdl.utils.LoggerFilter.backupCount`` — rotated generations kept
  (default 2: ``bigdl.log.1``, ``bigdl.log.2``)
"""

from __future__ import annotations

import logging
import logging.handlers
import os
from typing import Optional, Sequence

# the chatty frameworks whose INFO spam is kept off the console
# (the reference lists org.apache.spark.*; here it is the XLA stack)
_CHATTY = ("jax", "jax._src", "tensorflow", "absl")


def redirect_spark_info_logs(log_file: Optional[str] = None,
                             chatty: Sequence[str] = _CHATTY) -> str:
    """Keep the console readable: chatty loggers print only >= ERROR, while
    EVERYTHING (bigdl_tpu + chatty, >= INFO) is appended to the log file.
    Returns the log file path.  Name kept from the reference
    (``LoggerFilter.redirectSparkInfoLogs``)."""
    from bigdl_tpu.utils import config

    if config.get_bool("bigdl.utils.LoggerFilter.disable", False):
        return ""
    path = (log_file or
            config.get_property("bigdl.utils.LoggerFilter.logFile") or
            os.path.join(os.getcwd(), "bigdl.log"))

    fmt = logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s - %(message)s")
    # size-capped rotation: a long-lived serving process must not grow
    # an unbounded bigdl.log (maxBytes=0 restores the unbounded append)
    max_bytes = config.get_int("bigdl.utils.LoggerFilter.maxBytes",
                               10 * 1024 * 1024)
    backups = config.get_int("bigdl.utils.LoggerFilter.backupCount", 2)
    if max_bytes > 0:
        file_handler: logging.Handler = \
            logging.handlers.RotatingFileHandler(
                path, maxBytes=max_bytes, backupCount=max(0, backups))
    else:
        file_handler = logging.FileHandler(path)
    file_handler.setLevel(logging.INFO)
    file_handler.setFormatter(fmt)

    console = logging.StreamHandler()
    console.setLevel(logging.INFO)
    console.setFormatter(fmt)

    bigdl = logging.getLogger("bigdl_tpu")
    bigdl.setLevel(logging.INFO)
    bigdl.handlers = [file_handler, console]
    bigdl.propagate = False

    include_chatty = config.get_bool(
        "bigdl.utils.LoggerFilter.enableSparkLog", True)
    err_console = logging.StreamHandler()
    err_console.setLevel(logging.ERROR)
    err_console.setFormatter(fmt)
    for name in chatty:
        lg = logging.getLogger(name)
        # detach from the root handler chain so INFO spam cannot reach the
        # console; errors still print, INFO goes to the file
        lg.propagate = False
        lg.handlers = ([file_handler] if include_chatty else []) + [err_console]
        lg.setLevel(logging.INFO)
    return path
