"""Torch7 ``.t7`` module tree ↔ Module conversion.

Reference equivalent: the module half of ``utils/TorchFile.scala`` —
``readModule`` dispatch (``TorchFile.scala:142-187``) and the
``write<Layer>`` family (``:640-`` writers with ``writeGeneralParameters``):
load a torch7-serialized nn.* tree as a trained model, and save a model so
stock torch7 (or the reference) can read it.

Weight layout bridges (same conventions as the caffe/TF importers):
torch Linear stores (out, in) — native is (in, out); torch SpatialConvolution
stores OIHW (the reference writer views it 2-D as (nOut, nIn*kH*kW),
``TorchFile.scala:482``) — native is HWIO.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.utils import torch_file
from bigdl_tpu.utils.torch_file import LongStorage, TorchObject

# torch classes with no constructor arguments worth preserving
_PARAM_FREE = {
    "nn.Tanh": nn.Tanh, "nn.Sigmoid": nn.Sigmoid,
    "nn.LogSoftMax": nn.LogSoftMax, "nn.SoftMax": nn.SoftMax,
    "nn.SoftPlus": nn.SoftPlus, "nn.SoftSign": nn.SoftSign,
    "nn.Identity": nn.Identity, "nn.Abs": nn.Abs, "nn.Exp": nn.Exp,
    "nn.Square": nn.Square, "nn.Sqrt": nn.Sqrt,
    "nn.CAddTable": nn.CAddTable, "nn.FlattenTable": nn.FlattenTable,
    "nn.LogSigmoid": nn.LogSigmoid, "nn.TanhShrink": nn.TanhShrink,
}


def _f32(v) -> np.ndarray:
    return np.asarray(v, dtype=np.float32)


def _children(payload: Dict) -> List[Any]:
    mods = payload.get("modules") or {}
    if isinstance(mods, dict):
        return [mods[k] for k in sorted(mods)]
    return list(mods)


def to_module(obj: TorchObject) -> nn.Module:
    """Convert a torch7 nn.* object tree into a Module
    (reference ``TorchFile.readModule``, ``TorchFile.scala:142``)."""
    cls, p = obj.torch_class, obj.payload
    if cls in _PARAM_FREE:
        return _PARAM_FREE[cls]()
    if cls == "nn.Sequential":
        seq = nn.Sequential()
        for c in _children(p):
            seq.add(to_module(c))
        return seq
    if cls == "nn.Concat":
        cat = nn.Concat(int(p["dimension"]))
        for c in _children(p):
            cat.add(to_module(c))
        return cat
    if cls == "nn.ConcatTable":
        ct = nn.ConcatTable()
        for c in _children(p):
            ct.add(to_module(c))
        return ct
    if cls == "nn.Linear":
        w = _f32(p["weight"])                       # (out, in)
        b = p.get("bias")
        return nn.Linear(w.shape[1], w.shape[0], with_bias=b is not None,
                         init_weight=np.ascontiguousarray(w.T),
                         init_bias=None if b is None else _f32(b).ravel())
    if cls in ("nn.SpatialConvolution", "nn.SpatialConvolutionMM"):
        n_in = int(p["nInputPlane"])
        n_out = int(p["nOutputPlane"])
        kw, kh = int(p["kW"]), int(p["kH"])
        w = _f32(p["weight"]).reshape(n_out, n_in, kh, kw)  # OIHW (2-D view ok)
        b = p.get("bias")
        return nn.SpatialConvolution(
            n_in, n_out, kw, kh, int(p["dW"]), int(p["dH"]),
            int(p.get("padW", 0)), int(p.get("padH", 0)),
            with_bias=b is not None,
            init_weight=np.transpose(w, (2, 3, 1, 0)),      # -> HWIO
            init_bias=None if b is None else _f32(b).ravel())
    if cls == "nn.SpatialMaxPooling":
        m = nn.SpatialMaxPooling(int(p["kW"]), int(p["kH"]),
                                 int(p["dW"]), int(p["dH"]),
                                 int(p.get("padW", 0)), int(p.get("padH", 0)))
        return m.ceil() if p.get("ceil_mode") else m
    if cls == "nn.SpatialAveragePooling":
        m = nn.SpatialAveragePooling(
            int(p["kW"]), int(p["kH"]), int(p["dW"]), int(p["dH"]),
            int(p.get("padW", 0)), int(p.get("padH", 0)),
            ceil_mode=bool(p.get("ceil_mode")),
            count_include_pad=bool(p.get("count_include_pad", True)))
        return m
    if cls in ("nn.BatchNormalization", "nn.SpatialBatchNormalization"):
        mean = _f32(p["running_mean"]).ravel()
        var = _f32(p["running_var"]).ravel()
        affine = bool(p.get("affine", p.get("weight") is not None))
        klass = (nn.SpatialBatchNormalization
                 if cls == "nn.SpatialBatchNormalization"
                 else nn.BatchNormalization)
        bn = klass(mean.shape[0], eps=float(p.get("eps", 1e-5)),
                   momentum=float(p.get("momentum", 0.1)), affine=affine,
                   init_weight=None if not affine else _f32(p["weight"]).ravel(),
                   init_bias=None if not affine else _f32(p["bias"]).ravel())
        bn._ensure_init()
        bn.state = {"running_mean": mean, "running_var": var}
        return bn
    if cls == "nn.ReLU":
        return nn.ReLU()
    if cls == "nn.ReLU6":
        return nn.ReLU6()        # torch implements it as HardTanh(0, 6)
    if cls == "nn.HardTanh":
        return nn.HardTanh(float(p.get("min_val", -1.0)),
                           float(p.get("max_val", 1.0)))
    if cls == "nn.Threshold":
        return nn.Threshold(float(p.get("threshold", 1e-6)),
                            float(p.get("val", 0.0)))
    if cls == "nn.Dropout":
        return nn.Dropout(float(p.get("p", 0.5)))
    if cls == "nn.View":
        v = nn.View(*(int(s) for s in np.asarray(p["size"]).ravel()))
        if p.get("numInputDims"):
            v.set_num_input_dims(int(p["numInputDims"]))
        return v
    if cls == "nn.Reshape":
        return nn.Reshape([int(s) for s in np.asarray(p["size"]).ravel()],
                          batch_mode=p.get("batchMode"))
    if cls == "nn.SpatialZeroPadding":
        return nn.SpatialZeroPadding(int(p["pad_l"]), int(p["pad_r"]),
                                     int(p["pad_t"]), int(p["pad_b"]))
    raise ValueError(f"unsupported torch module class {cls}")


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------

_EMPTY = np.zeros((0,), dtype=np.float32)


def _general(table: Dict, dtype: str = "torch.FloatTensor") -> Dict:
    """(reference ``writeGeneralParameters``, ``TorchFile.scala:450``)."""
    table["gradInput"] = _EMPTY
    table["output"] = _EMPTY
    table["_type"] = dtype
    return table


def from_module(m: nn.Module) -> TorchObject:
    """Convert a Module tree to torch7 nn.* objects
    (reference ``TorchFile.writeObject`` module cases)."""
    for cls, klass in _PARAM_FREE.items():
        if type(m) is klass:
            return TorchObject(cls, _general({}))
    if type(m) is nn.ReLU:
        return TorchObject("nn.ReLU", _general({"inplace": False}))
    if type(m) is nn.ReLU6:
        # torch7 ReLU6 extends HardTanh(0, 6): fields must be present or
        # stock torch errors on nil min_val at run time
        return TorchObject("nn.ReLU6", _general(
            {"min_val": 0.0, "max_val": 6.0, "inplace": False}))
    if type(m) in (nn.HardTanh, nn.Clamp):
        return TorchObject("nn.HardTanh", _general(
            {"min_val": float(m.min_value), "max_val": float(m.max_value),
             "inplace": False}))
    if isinstance(m, nn.Sequential):
        mods = {i + 1: from_module(c) for i, c in enumerate(m.children)}
        return TorchObject("nn.Sequential", _general({"modules": mods}))
    if isinstance(m, nn.ConcatTable):
        mods = {i + 1: from_module(c) for i, c in enumerate(m.children)}
        return TorchObject("nn.ConcatTable", _general({"modules": mods}))
    if isinstance(m, nn.Concat):
        mods = {i + 1: from_module(c) for i, c in enumerate(m.children)}
        return TorchObject("nn.Concat", _general(
            {"modules": mods, "dimension": float(m.dimension)}))
    m._ensure_init()
    p = m.params if m._params is not None else {}
    if getattr(m, "format", "NCHW") != "NCHW" or \
            getattr(m, "channel_axis", 1) not in (1,):
        # TF-imported NHWC convs/BNs/poolings have no torch representation
        raise ValueError(f"cannot export NHWC-format "
                         f"{type(m).__name__} to torch (NCHW only)")
    if isinstance(m, nn.SpatialConvolution):
        if m.n_group != 1:
            raise ValueError("nGroup is not supported in torch")
        w = np.transpose(_f32(p["weight"]), (3, 2, 0, 1))   # HWIO -> OIHW
        t = _general({
            "nInputPlane": float(m.n_input_plane),
            "nOutputPlane": float(m.n_output_plane),
            "kW": float(m.kernel_w), "kH": float(m.kernel_h),
            "dW": float(m.stride_w), "dH": float(m.stride_h),
            "padW": float(m.pad_w), "padH": float(m.pad_h),
            # the reference writer views weight 2-D (TorchFile.scala:482)
            "weight": w.reshape(m.n_output_plane, -1),
            "gradWeight": np.zeros_like(w).reshape(m.n_output_plane, -1),
            "fInput": _EMPTY, "fGradInput": _EMPTY,
        })
        if m.with_bias:
            t["bias"] = _f32(p["bias"])
            t["gradBias"] = np.zeros_like(t["bias"])
        return TorchObject("nn.SpatialConvolution", t)
    if isinstance(m, nn.Linear):
        t = _general({"weight": _f32(p["weight"]).T,        # -> (out, in)
                      "gradWeight": np.zeros(
                          (m.output_size, m.input_size), np.float32)})
        if m.with_bias:
            t["bias"] = _f32(p["bias"])
            t["gradBias"] = np.zeros_like(t["bias"])
        return TorchObject("nn.Linear", t)
    if isinstance(m, nn.SpatialMaxPooling):
        return TorchObject("nn.SpatialMaxPooling", _general({
            "kW": float(m.kw), "kH": float(m.kh),
            "dW": float(m.dw), "dH": float(m.dh),
            "padW": float(m.pad_w), "padH": float(m.pad_h),
            "ceil_mode": bool(m.ceil_mode), "indices": _EMPTY}))
    if isinstance(m, nn.SpatialAveragePooling):
        return TorchObject("nn.SpatialAveragePooling", _general({
            "kW": float(m.kw), "kH": float(m.kh),
            "dW": float(m.dw), "dH": float(m.dh),
            "padW": float(m.pad_w), "padH": float(m.pad_h),
            "ceil_mode": bool(m.ceil_mode),
            "count_include_pad": bool(m.count_include_pad),
            "divide": True}))
    if isinstance(m, nn.BatchNormalization):   # covers Spatial subclass
        s = m.state
        t = _general({"running_mean": _f32(s["running_mean"]),
                      "running_var": _f32(s["running_var"]),
                      "eps": float(m.eps), "momentum": float(m.momentum),
                      "affine": bool(m.affine)})
        if m.affine:
            t["weight"] = _f32(p["weight"])
            t["bias"] = _f32(p["bias"])
            t["gradWeight"] = np.zeros_like(t["weight"])
            t["gradBias"] = np.zeros_like(t["bias"])
        cls = ("nn.SpatialBatchNormalization"
               if isinstance(m, nn.SpatialBatchNormalization)
               else "nn.BatchNormalization")
        return TorchObject(cls, t)
    if isinstance(m, nn.Threshold):
        return TorchObject("nn.Threshold", _general(
            {"threshold": float(m.th), "val": float(m.v), "inplace": False}))
    if isinstance(m, nn.Dropout):
        return TorchObject("nn.Dropout", _general(
            {"p": float(m.p), "noise": _EMPTY, "v2": True}))
    if isinstance(m, nn.View):
        # torch7 View:__init__ excludes inferred (-1) dims from numElements
        n_elem = float(np.prod([s for s in m.sizes if s >= 0]))
        t = _general({"size": LongStorage(m.sizes), "numElements": n_elem})
        if m.num_input_dims:
            t["numInputDims"] = float(m.num_input_dims)
        return TorchObject("nn.View", t)
    if isinstance(m, nn.Reshape):
        if any(s < 0 for s in m.size):
            # torch7 Reshape has no inferred-dim support; its nelement
            # check would silently mis-branch on a negative product
            raise ValueError("cannot export Reshape with an inferred (-1) "
                             "dim to torch (use View instead)")
        return TorchObject("nn.Reshape", _general(
            {"size": LongStorage(m.size),
             "nelement": float(np.prod(m.size)),
             "batchMode": m.batch_mode}))
    if isinstance(m, nn.SpatialZeroPadding):
        return TorchObject("nn.SpatialZeroPadding", _general(
            {"pad_l": float(m.pl), "pad_r": float(m.pr),
             "pad_t": float(m.pt), "pad_b": float(m.pb)}))
    raise ValueError(f"cannot export {type(m).__name__} to torch")


def load_model(path: str) -> nn.Module:
    """Load a ``.t7`` file containing a torch7 nn module tree
    (reference ``Module.loadTorch`` → ``TorchFile.loadModule``)."""
    obj = torch_file.load(path)
    if not isinstance(obj, TorchObject):
        raise ValueError(f"{path} does not contain a torch module "
                         f"(got {type(obj).__name__})")
    return to_module(obj)


def save_model(path: str, model: nn.Module) -> None:
    """Save a Module tree as a torch7-readable ``.t7``
    (reference ``AbstractModule.saveTorch`` → ``TorchFile.saveModule``)."""
    torch_file.save(path, from_module(model))
