"""Elastic training: topology-elastic restore, preemption, hung-step watchdog.

The PR 2 fault-tolerance loop assumed the world it restored into was the
world it snapshotted from: same device count, same mesh, and a failure
mode that announces itself by raising.  Production TPU fleets violate all
three — preemptible slices come and go (the snapshot taken on N devices
must resume on M), the scheduler delivers SIGTERM with a grace window
instead of an exception, and the nastiest failure is the step that never
*finishes* (a wedged collective, a deadlocked host thread) and therefore
never raises anything.  This module is the three missing legs:

1. **Topology-elastic restore.**  Snapshots record the saving topology
   (:func:`describe_topology` — device count, mesh axis names/sizes, the
   ZeRO-1 slot partition axis, which fused step wrote them) in the
   checkpoint manifest.  At restore, :func:`check_restore_topology`
   compares it against the resuming trainer's topology: same topology
   restores as before; a different one either enters the reshard path
   (``bigdl.elastic.reshardOnRestore``, default on — snapshots publish
   CANONICAL per-parameter host trees, so resharding = re-partitioning
   those trees for the new mesh and re-placing them with the new
   ``NamedSharding``, timed by :func:`timed` into the metrics registry)
   or is rejected with a :class:`TopologyMismatchError` that names every
   mismatching axis instead of failing deep inside a shape check.

2. **Preemption handling.**  :class:`PreemptionHandler` installs
   SIGTERM/SIGINT handlers (``bigdl.elastic.handleSignals``) that only
   set a flag — signal-safe by construction; the driver loop polls
   :func:`preemption_requested` once per iteration and unwinds through
   a *graceful drain*: flush the dispatch pipeline, publish the carries,
   raise :class:`Preempted`.  The retry loop recognizes the class —
   preemption commits a final verified snapshot plus a resumable marker
   within ``bigdl.elastic.gracePeriod`` and exits, where divergence
   restores-and-retries.

3. **Hung-step watchdog.**  :class:`HungStepWatchdog` is a monitor
   thread fed one :meth:`~HungStepWatchdog.heartbeat` per driver
   iteration.  Completed intervals feed the PR 5 step-time EMA
   (:class:`~bigdl_tpu.telemetry.step_stats.SlowStepDetector`, whose
   warmup-minimum seeding keeps compile steps out of the baseline — the
   compile-warmup exemption); when the *open* interval exceeds
   ``bigdl.watchdog.stallFactor`` x EMA the watchdog fires ONCE for that
   stall (re-arming only after a heartbeat lands plus a cooldown): dumps
   the telemetry timeline, bumps the registry counters, and aborts the
   driver thread with an injected :class:`HungStepError` so the retry
   loop restores the newest valid snapshot instead of hanging the job
   forever.  (The async-exception abort lands when the wedged thread
   re-enters Python bytecode — it interrupts chaos-simulated stalls and
   host-side wedges; a thread parked forever inside a C extension call
   is only reachable by process-level supervision, which the log line
   and counters are there to inform.)

Everything is provable on CPU: ``utils/chaos.py`` injects preemption
signals (``bigdl.chaos.preemptAt``), stalled steps
(``bigdl.chaos.stallStepAt``) and mid-run topology changes
(``bigdl.chaos.topologyChangeAt``), and ``tests/test_elastic.py`` holds
the parity proofs.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

logger = logging.getLogger("bigdl_tpu")

#: schema key the checkpoint manifest stores the topology under
TOPOLOGY_KEY = "topology"


class TopologyMismatchError(RuntimeError):
    """A snapshot's saved topology is incompatible with the resuming
    trainer and resharding was disabled — the structured alternative to
    an unpickle/shape crash.  ``mismatches`` names every differing
    field."""

    def __init__(self, saved: Dict[str, Any], current: Dict[str, Any],
                 mismatches: List[str]):
        self.saved = saved
        self.current = current
        self.mismatches = list(mismatches)
        super().__init__(
            "snapshot topology does not match the resuming trainer "
            f"({'; '.join(mismatches)}) and bigdl.elastic.reshardOnRestore "
            "is disabled — enable it to reshard the ZeRO-1 slots onto the "
            "new mesh, or resume on the saving topology "
            f"(saved={saved}, current={current})")


class Preempted(RuntimeError):
    """The run was asked to stop (SIGTERM/SIGINT or an injected
    preemption): the driver drained gracefully and — when a checkpoint
    is configured — a final verified snapshot plus a resumable marker
    were committed.  Deliberately NOT retried by the failure loop:
    preemption means *leave*, divergence means *rewind*."""


class HungStepError(RuntimeError):
    """Injected into the driver thread by the hung-step watchdog: a step
    exceeded ``bigdl.watchdog.stallFactor`` x the step-time EMA.  The
    retry loop treats it like any crash — restore newest valid snapshot
    and resume."""


# ---- topology ------------------------------------------------------------


def describe_topology(mesh=None, step: str = "local",
                      slot_axis: Optional[str] = None) -> Dict[str, Any]:
    """The manifest-serializable description of the topology a snapshot
    is being written from: plain ints/strings only (it travels through
    the JSON manifest).  ``step`` names the fused step that owns the
    layout (``local`` / ``shard_map`` / ``gspmd`` / ``pipeline``);
    ``slot_axis`` is the mesh axis the ZeRO-1 optimizer slots shard
    over (None: slots are unsharded)."""
    if mesh is None:
        return {"device_count": 1, "axes": {}, "step": step,
                "slot_axis": slot_axis}
    return {
        "device_count": int(mesh.size),
        "axes": {str(a): int(s) for a, s in mesh.shape.items()},
        "step": str(step),
        "slot_axis": slot_axis,
    }


def compare_topology(saved: Optional[Dict[str, Any]],
                     current: Optional[Dict[str, Any]]) -> List[str]:
    """Human-readable mismatch list between a snapshot's saved topology
    and the resuming trainer's; empty means compatible as-is.  A snapshot
    with no topology record (pre-schema-2) compares equal to anything —
    those snapshots restore same-topology by assumption, exactly as they
    did before the schema carried topology at all."""
    if not saved or not current:
        return []
    out: List[str] = []
    if saved.get("device_count") != current.get("device_count"):
        out.append(f"device_count {saved.get('device_count')} -> "
                   f"{current.get('device_count')}")
    s_axes = saved.get("axes") or {}
    c_axes = current.get("axes") or {}
    for name in sorted(set(s_axes) | set(c_axes)):
        if s_axes.get(name) != c_axes.get(name):
            out.append(f"axis '{name}' {s_axes.get(name)} -> "
                       f"{c_axes.get(name)}")
    if saved.get("step") != current.get("step"):
        out.append(f"step {saved.get('step')!r} -> {current.get('step')!r}")
    return out


def check_restore_topology(saved: Optional[Dict[str, Any]],
                           current: Optional[Dict[str, Any]]) -> str:
    """``"same"`` when the snapshot restores without resharding,
    ``"reshard"`` when the topology changed and
    ``bigdl.elastic.reshardOnRestore`` allows re-partitioning; raises
    :class:`TopologyMismatchError` otherwise."""
    mismatches = compare_topology(saved, current)
    if not mismatches:
        return "same"
    from bigdl_tpu.utils import config
    if config.get_bool("bigdl.elastic.reshardOnRestore", True):
        logger.info(
            "elastic restore: snapshot topology differs from the resuming "
            "trainer (%s) — resharding ZeRO-1 slots onto the new mesh",
            "; ".join(mismatches))
        return "reshard"
    raise TopologyMismatchError(saved or {}, current or {}, mismatches)


def place_slots(place_fn, resharding: bool):
    """Shared protocol of the three trainer slot-placement legs
    (shard_map dp, GSPMD dp x tp, pipeline): run ``place_fn`` — the
    device_put of optimizer slots onto the current mesh — under the
    ``Elastic/reshard_ms`` timer when ``resharding`` (the slots were
    just restored from a checkpoint, see
    ``Optimizer._consume_elastic_resumed``), blocking for completion so
    the gauge measures the transfer rather than the dispatch.  Fresh
    zeros and in-process re-placements take the identical path untimed
    and unblocked."""
    with timed("reshard", enabled=resharding):
        out = place_fn()
        if resharding:
            import jax
            jax.block_until_ready(out)
        return out


def count_reshard() -> None:
    """Bump ``Elastic/reshards`` — called by the restore path for the
    snapshot ACTUALLY loaded, not per candidate examined: a fallback walk
    past a corrupt newest snapshot is one restore, not several."""
    from bigdl_tpu import telemetry
    telemetry.counter(
        "Elastic/reshards",
        help="topology-elastic restores that re-partitioned").inc()


class _TimedHandle:
    __slots__ = ("record",)

    def __init__(self, record: bool):
        self.record = record

    def cancel(self) -> None:
        self.record = False


@contextmanager
def timed(metric: str, enabled: bool = True):
    """Time a restore/reshard phase into the metrics registry
    (``Elastic/<metric>_ms`` gauge; last value wins — these are per-event
    durations the bench leg and the end-of-run snapshot read).
    ``enabled=False`` is a no-op, so call sites shared between fresh and
    resumed runs stay single-path.  Yields a handle whose ``cancel()``
    suppresses the recording — for bodies that discover mid-flight the
    event did not happen (a restore scan that found nothing)."""
    handle = _TimedHandle(enabled)
    if not enabled:
        yield handle
        return
    from bigdl_tpu import telemetry
    t0 = time.perf_counter()
    try:
        yield handle
    finally:
        if handle.record:
            telemetry.gauge(f"Elastic/{metric}_ms").set(
                (time.perf_counter() - t0) * 1e3)


# ---- preemption ----------------------------------------------------------

_PREEMPT = {"requested": False, "reason": None, "at": None}


def request_preemption(reason: str = "signal") -> None:
    """Flag the run for graceful shutdown (signal handlers and the chaos
    injector call this; anything here must stay async-signal-safe — set
    state, no locks beyond the GIL, no IO.  In particular NO metric
    registry touches: a handler interrupting the main thread inside a
    registry/metric lock would deadlock on re-acquiring it — the
    ``Elastic/preemptions`` counter is bumped by the driver when it
    observes the flag)."""
    _PREEMPT["requested"] = True
    _PREEMPT["reason"] = reason
    # the grace clock starts HERE: the drain the driver runs before the
    # final snapshot (pipeline flush + publish) spends the same window
    _PREEMPT["at"] = time.monotonic()
    # flight-recorder note: one GIL-atomic deque append, no locks/IO —
    # still signal-safe.  The bundle itself is written later by whoever
    # observes the flag (fleet supervisor tick / optimizer Preempted
    # branch), never from here.
    mod = sys.modules.get("bigdl_tpu.telemetry.incident")
    if mod is not None:
        mod.record("preemption/requested", reason=reason)


def preemption_requested() -> bool:
    return _PREEMPT["requested"]


def preemption_reason() -> Optional[str]:
    return _PREEMPT["reason"]


def preemption_requested_at() -> Optional[float]:
    """``time.monotonic()`` of the preemption request, or None."""
    return _PREEMPT["at"]


def clear_preemption() -> None:
    """Reset the flag (a resumed run in the same process starts clean)."""
    _PREEMPT["requested"] = False
    _PREEMPT["reason"] = None
    _PREEMPT["at"] = None


def grace_period() -> float:
    from bigdl_tpu.utils import config
    return config.get_float("bigdl.elastic.gracePeriod", 30.0)


class PreemptionHandler:
    """Context manager that routes SIGTERM/SIGINT into
    :func:`request_preemption` for the duration of a training run.

    Installed only when ``bigdl.elastic.handleSignals`` is on AND the
    caller runs on the main thread (CPython restricts ``signal.signal``
    to it); previous handlers are restored on exit, so a library user's
    own signal strategy survives the run.  The handler body is flag-only
    — every consequence (pipeline flush, publish, the final snapshot)
    happens on the driver thread at the next iteration boundary, inside
    the grace period the scheduler granted."""

    SIGNALS = ("SIGTERM", "SIGINT")

    def __init__(self, enabled: Optional[bool] = None):
        from bigdl_tpu.utils import config
        if enabled is None:
            enabled = config.get_bool("bigdl.elastic.handleSignals", False)
        self.enabled = bool(enabled)
        self._previous: Dict[int, Any] = {}

    def __enter__(self) -> "PreemptionHandler":
        if not self.enabled:
            return self
        import signal
        if threading.current_thread() is not threading.main_thread():
            logger.warning(
                "bigdl.elastic.handleSignals is on but optimize() runs "
                "off the main thread — signal handlers not installed")
            self.enabled = False
            return self

        def handler(signum, frame):   # noqa: ARG001 — signal signature
            request_preemption(reason=f"signal {signum}")

        for name in self.SIGNALS:
            signum = getattr(signal, name)
            self._previous[signum] = signal.signal(signum, handler)
        return self

    def __exit__(self, *exc) -> None:
        if not self._previous:
            return
        import signal
        for signum, prev in self._previous.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, TypeError):  # pragma: no cover - teardown
                pass
        self._previous.clear()


#: resumable-marker filename committed next to the grace-period snapshot
PREEMPT_MARKER = "preempted"


def write_preemption_marker(ckpt_path: str, neval: int) -> None:
    """Drop the resumable marker into the checkpoint directory: a tiny
    JSON naming the snapshot the grace-period drain committed, so an
    external supervisor (or the next attempt) can tell an orderly
    preemption from a crash without scanning manifests."""
    import json
    from bigdl_tpu.utils import file_io
    payload = json.dumps({
        "neval": int(neval),
        "reason": preemption_reason() or "preempted",
        "unix_time": time.time(),
    }, sort_keys=True).encode("utf-8")
    try:
        file_io.write_bytes(file_io.join(ckpt_path, PREEMPT_MARKER),
                            payload, overwrite=True)
    except Exception as e:  # the marker is advisory, the snapshot is not
        logger.warning("could not write preemption marker: %r", e)


def read_preemption_marker(ckpt_path: str) -> Optional[Dict[str, Any]]:
    import json
    from bigdl_tpu.utils import file_io
    try:
        data = file_io.read_bytes(file_io.join(ckpt_path, PREEMPT_MARKER))
    except Exception:
        return None
    return json.loads(data.decode("utf-8"))


def clear_preemption_marker(ckpt_path: str) -> None:
    from bigdl_tpu.utils import file_io
    try:
        file_io.remove(file_io.join(ckpt_path, PREEMPT_MARKER))
    except Exception:
        pass


# ---- hung-step watchdog --------------------------------------------------

#: name -> () -> dict: subsystem snapshots the watchdog logs when it
#: fires, so a stall is DIAGNOSED (which ingest stage wedged, how stale
#: each ring is) rather than just detected.  Providers must be cheap,
#: lock-light, and never touch device values — they run on the monitor
#: thread while the driver is presumed hung.
_STALL_DIAGNOSTICS: Dict[str, Any] = {}


def register_stall_diagnostic(name: str, provider) -> None:
    """Register ``provider() -> dict`` to be reported on every watchdog
    fire (idempotent by name — re-registering replaces)."""
    _STALL_DIAGNOSTICS[name] = provider


def stall_diagnostics() -> Dict[str, Any]:
    """Snapshot every registered provider (a failing provider reports
    its error instead of masking the fire)."""
    out: Dict[str, Any] = {}
    for name, provider in list(_STALL_DIAGNOSTICS.items()):
        try:
            out[name] = provider()
        except Exception as e:  # diagnostics must not mask the abort
            out[name] = {"error": repr(e)}
    return out


def _async_raise(thread_id: int, exc_type) -> bool:
    """Inject ``exc_type`` into the thread with ``thread_id`` (CPython's
    PyThreadState_SetAsyncExc).  The exception surfaces when that thread
    next executes bytecode."""
    import ctypes
    set_exc = ctypes.pythonapi.PyThreadState_SetAsyncExc
    set_exc.argtypes = (ctypes.c_ulong, ctypes.py_object)
    set_exc.restype = ctypes.c_int
    res = set_exc(ctypes.c_ulong(thread_id), ctypes.py_object(exc_type))
    if res > 1:  # pragma: no cover - interpreter-level inconsistency
        set_exc(ctypes.c_ulong(thread_id), None)
        return False
    return res == 1


class HungStepWatchdog:
    """Monitor thread detecting a driver iteration that never finishes.

    The driver calls :meth:`heartbeat` once per loop iteration; the
    interval between consecutive heartbeats is a completed step and
    feeds the EMA (a :class:`SlowStepDetector` seeded from the warmup
    MINIMUM, so compile steps cannot poison the baseline — detection is
    disarmed until the warmup completes).  The monitor wakes every
    ``poll_interval`` seconds and compares the OPEN interval — time
    since the last heartbeat — against ``factor`` x EMA.  One stall
    fires exactly once, however long it lasts; after the stalled step
    finally completes (or the driver is reborn by the retry loop), a
    ``cooldown`` of completed heartbeats must pass before the next fire.

    Firing dumps the telemetry timeline (``bigdl.watchdog.timelineDir``,
    when tracing is armed), records ``Elastic/watchdog_fired`` /
    ``Elastic/watchdog_detect_ms`` in the metrics registry, invokes
    ``on_fire`` (tests/bench probes), and — when ``abort`` is on —
    injects :class:`HungStepError` into the driver thread so the retry
    loop can restore the newest valid snapshot.

    Subclasses supervising a different loop override :attr:`EXC` /
    :attr:`METRIC_PREFIX` / :attr:`INSTANT_NAME` (the serving engine's
    hung-dispatch watchdog injects its own error class and counts under
    ``Serving/*``); the monitor/suppression machinery is shared.
    """

    #: exception class injected into the supervised thread on a fire
    EXC = HungStepError
    #: registry namespace for the fired/detect_ms metrics
    METRIC_PREFIX = "Elastic"
    #: tracer instant-event name emitted on a fire
    INSTANT_NAME = "watchdog/hung_step"

    def __init__(self, factor: float, warmup: int = 5, cooldown: int = 50,
                 poll_interval: float = 0.25, abort: bool = True,
                 timeline_dir: Optional[str] = None, on_fire=None):
        from bigdl_tpu.telemetry import SlowStepDetector
        self.factor = float(factor)
        self.detector = SlowStepDetector(self.factor, warmup=warmup,
                                         cooldown=0)
        self.cooldown = max(0, int(cooldown))
        self.poll_interval = max(0.01, float(poll_interval))
        self.abort = abort
        self.timeline_dir = timeline_dir
        self.on_fire = on_fire
        self.fired = 0
        from bigdl_tpu import analysis
        self._lock = analysis.make_lock("elastic.watchdog")
        self._last_beat_ns: Optional[int] = None
        self._beats = 0
        self._fired_this_stall = False       # guarded-by: _lock
        self._cool_left = 0
        self._paused = 0
        #: the start()->first-beat interval covers setup, not a step, and
        #: must not feed the EMA — a near-zero observation would deflate
        #: the stall threshold and fire on healthy steps
        self._skip_next_observe = True
        #: step time accrued BEFORE a pause interrupted the interval —
        #: added back at the next heartbeat so the observation is the
        #: true step work minus the paused span.  (Discarding post-pause
        #: intervals instead would starve the EMA whenever every
        #: iteration checkpoints/validates, silently disarming the
        #: watchdog.)
        self._carry_ns = 0
        self._driver_tid: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def from_config(cls, timeline_dir: Optional[str] = None,
                    on_fire=None) -> Optional["HungStepWatchdog"]:
        """A watchdog per the ``bigdl.watchdog.*`` keys, or None when
        ``stallFactor`` is unset (the default: no monitor thread at
        all — zero overhead for runs that did not opt in)."""
        from bigdl_tpu.utils import config
        factor = config.get_float("bigdl.watchdog.stallFactor", 0.0)
        if factor <= 0:
            return None
        return cls(
            factor,
            warmup=config.get_int("bigdl.watchdog.warmupSteps", 5),
            cooldown=config.get_int("bigdl.watchdog.cooldownSteps", 50),
            poll_interval=config.get_float("bigdl.watchdog.pollInterval",
                                           0.25),
            timeline_dir=(timeline_dir if timeline_dir is not None else
                          config.get_property("bigdl.watchdog.timelineDir")),
            on_fire=on_fire)

    @property
    def enabled(self) -> bool:
        return self.factor > 0

    # -- driver side ------------------------------------------------------

    def start(self) -> "HungStepWatchdog":
        """Begin monitoring; call from the DRIVER thread (its identity is
        what the abort targets)."""
        from bigdl_tpu import telemetry
        self._driver_tid = threading.get_ident()
        self._last_beat_ns = telemetry.clock_ns()
        self._stop.clear()
        self._thread = threading.Thread(target=self._monitor, daemon=True,
                                        name="bigdl-watchdog")
        self._thread.start()
        return self

    def heartbeat(self) -> None:
        """One driver iteration completed.  Cheap: a clock read and a
        few float ops under a lock the monitor holds for microseconds."""
        from bigdl_tpu import telemetry
        now = telemetry.clock_ns()
        with self._lock:
            last = self._last_beat_ns
            self._last_beat_ns = now
            self._beats += 1
            skip = self._skip_next_observe
            self._skip_next_observe = False
            carry, self._carry_ns = self._carry_ns, 0
            if self._fired_this_stall:
                self._fired_this_stall = False
                self._cool_left = self.cooldown
            elif self._cool_left > 0:
                self._cool_left -= 1
        if last is not None and not skip:
            # completed STEP intervals feed the EMA outside the lock —
            # the detector is only ever touched from the driver thread
            self.detector.observe(float(carry + now - last))

    def reset_interval(self) -> None:
        """Restart the open interval WITHOUT feeding the EMA — for loop
        rounds that did bookkeeping but no supervised step (a serving
        dequeue round that shed everything and assembled an empty
        batch): their duration is neither a completed step nor a hang,
        and letting it accumulate across rounds would eventually fire
        the watchdog on a healthy thread."""
        from bigdl_tpu import telemetry
        with self._lock:
            self._last_beat_ns = telemetry.clock_ns()
            self._carry_ns = 0

    @contextmanager
    def paused(self):
        """Suspend stall detection over a legitimately-long driver phase
        (validation, checkpoint, publish): those are not hung steps, and
        their duration must count neither against the open interval nor
        into the EMA.  The step time already spent is banked into a
        carry and the clock restarts on resume, so the next completed
        heartbeat observes the step's true work with the paused span
        excised — NOT a near-zero tail (which would deflate the EMA) and
        NOT nothing at all (skipping would starve the EMA and silently
        disarm the watchdog when every iteration checkpoints)."""
        from bigdl_tpu import telemetry
        with self._lock:
            if self._paused == 0 and self._last_beat_ns is not None:
                # bank the step time already spent this interval; the
                # next heartbeat observes carry + post-pause time = the
                # step's true work, the paused span excluded exactly
                self._carry_ns += telemetry.clock_ns() - self._last_beat_ns
            self._paused += 1
        try:
            yield
        finally:
            with self._lock:
                self._paused -= 1
                if self._paused == 0:
                    self._last_beat_ns = telemetry.clock_ns()

    def stop(self) -> None:
        # set under the lock: _fire re-checks _stop under the same lock
        # immediately before injecting, so a monitor that raced the end
        # of the run cannot abort a driver that already completed (an
        # async exception cannot be un-injected — the target thread sees
        # it at its very next bytecode, before any clear could run)
        with self._lock:
            self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    # -- monitor side -----------------------------------------------------

    def threshold_ns(self) -> float:
        """Current stall threshold; inf while the EMA is still in its
        compile-warmup window (detection disarmed)."""
        return self.detector.threshold()

    def _monitor(self) -> None:
        from bigdl_tpu import telemetry
        while not self._stop.wait(self.poll_interval):
            threshold = self.threshold_ns()
            if threshold == float("inf"):
                continue
            with self._lock:
                last = self._last_beat_ns
                carry = self._carry_ns
                blocked = (not self._paused and
                           not self._fired_this_stall and
                           self._cool_left == 0)
            if last is None or not blocked:
                continue
            # carry counts: step work banked before a mid-step pause is
            # part of how long THIS step has really been running
            open_ns = carry + telemetry.clock_ns() - last
            if open_ns <= threshold:
                continue
            with self._lock:
                # re-check against a beat/pause that landed between the
                # first snapshot and here: firing on a stale interval
                # would abort a HEALTHY driver that already moved on
                # (e.g. into a paused checkpoint write)
                if (self._fired_this_stall or self._paused or
                        self._last_beat_ns != last):
                    continue
                self._fired_this_stall = True
            self.fired += 1
            self._fire(open_ns, threshold, last)

    def _fire(self, open_ns: float, threshold_ns: float,
              beat_snapshot) -> None:
        from bigdl_tpu import telemetry
        detect_ms = (open_ns - threshold_ns) / 1e6
        logger.error(
            "Hung step detected: current step open for %.1f ms "
            "(> %.1f ms = %.1f x EMA); aborting with %s "
            "(watchdog fire %d this run)",
            open_ns / 1e6, threshold_ns / 1e6, self.factor,
            self.EXC.__name__, self.fired)
        telemetry.counter(f"{self.METRIC_PREFIX}/watchdog_fired",
                          help="hung-step watchdog aborts").inc()
        telemetry.gauge(f"{self.METRIC_PREFIX}/watchdog_detect_ms").set(
            detect_ms)
        telemetry.instant(self.INSTANT_NAME,
                          open_ms=round(open_ns / 1e6, 3),
                          threshold_ms=round(threshold_ns / 1e6, 3))
        diagnostics = stall_diagnostics()
        if diagnostics:
            # name the wedged subsystem while the evidence is fresh: the
            # ingest engine registers its per-stage stats + ring ages
            # here, so "the step hung" comes with "the decode ring has
            # not progressed in 40 s"
            logger.error("hung-step diagnostics: %s", diagnostics)
        if self.timeline_dir and telemetry.tracing_enabled():
            try:
                # bounded (bigdl.telemetry.maxTimelineDumps, oldest-first
                # eviction) and disk-full-guarded: a watchdog firing in a
                # loop must not fill the disk with dump files
                from bigdl_tpu.resources import storage as _rstorage
                _rstorage.bounded_timeline_export(os.path.join(
                    str(self.timeline_dir),
                    f"watchdog_{self.fired}_timeline.json"))
            except Exception as e:  # diagnostics must not mask the abort
                logger.warning("watchdog timeline dump failed: %r", e)
        if self.on_fire is not None:
            try:
                self.on_fire(open_ns, threshold_ns)
            except Exception as e:  # pragma: no cover - probe bug
                logger.warning("watchdog on_fire callback failed: %r", e)
        if self.abort and self._driver_tid is not None:
            with self._lock:
                if self._stop.is_set():
                    # the run completed while this fire was in flight —
                    # aborting a finished driver would turn a clean end
                    # into a restore-and-retrain
                    logger.info("hung-step abort suppressed: the run "
                                "already completed")
                    return
                if self._paused or self._last_beat_ns != beat_snapshot:
                    # the diagnostics above (timeline dump, file I/O)
                    # take real time: a step that finished marginally
                    # past threshold may have heartbeat (or entered a
                    # paused phase) meanwhile — the driver is healthy
                    # again, injecting now would abort the NEXT step
                    logger.info("hung-step abort suppressed: the step "
                                "completed during fire diagnostics")
                    return
                injected = _async_raise(self._driver_tid, self.EXC)
            if not injected:
                logger.error(
                    "watchdog could not inject %s into the "
                    "driver thread (already exited?)", self.EXC.__name__)
