"""The ``bigdl.*`` configuration-property tier.

Reference equivalent: JVM system properties documented in
``docs/docs/UserGuide/configuration.md:28-41`` and read ad hoc across the
tree (``utils/Engine.scala:113-137``, ``parameters/AllReduceParameter.scala:34``,
``optim/DistriOptimizer.scala:751-752``).

TPU-native form: environment variables ``BIGDL_<DOTTED_NAME>`` (dots →
underscores, upper-cased) with programmatic overrides via :func:`set_property`.
The property names keep the reference's dotted vocabulary so its docs map 1:1.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

# name -> default; the reference's table (configuration.md:28-41) minus the
# JVM/Spark-only knobs that have no TPU analog (thread-pool sizes, nio).
_DEFAULTS: Dict[str, Any] = {
    "bigdl.engineType": "tpu",
    "bigdl.localMode": False,
    "bigdl.coreNumber": None,              # discovered from jax
    "bigdl.failure.retryTimes": 5,
    "bigdl.failure.retryTimeInterval": 120,  # base backoff seconds
    "bigdl.failure.maxRetryInterval": 900,   # backoff cap (exponential+jitter)
    "bigdl.io.retryTimes": 3,                # remote-fs transient retry budget
    "bigdl.io.retryInterval": 0.1,           # remote-fs retry base seconds
    "bigdl.checkpoint.keepLast": 0,          # snapshot retention; 0 = keep all
    "bigdl.checkpoint.asyncWrite": False,    # background checkpoint writer
    "bigdl.divergence.guard": True,          # skip non-finite updates in-step
    "bigdl.divergence.maxBadSteps": 5,       # consecutive bad steps → restore
    # chaos-injection harness (utils/chaos.py); 0/None = disarmed
    "bigdl.chaos.failWriteAt": 0,
    "bigdl.chaos.truncateWriteAt": 0,
    "bigdl.chaos.transientWrites": 0,
    "bigdl.chaos.failStepAt": 0,
    "bigdl.chaos.nanLossAt": None,
    "bigdl.chaos.preemptAt": 0,        # iteration k: simulated SIGTERM
    "bigdl.chaos.stallStepAt": None,   # "k:seconds": iteration k hangs
    "bigdl.chaos.topologyChangeAt": 0,  # iteration k: mesh goes away
    # ingest-stage fault injection (dataset/ingest.py stage threads)
    "bigdl.chaos.corruptRecordAt": None,  # "k" / "k:m": records read as corrupt
    "bigdl.chaos.corruptRecordEvery": 0,  # every Nth record reads corrupt
    "bigdl.chaos.failDecodeAt": None,     # "k" / "k:m": records fail decode
    "bigdl.chaos.transientReads": 0,      # first n record reads blip + recover
    "bigdl.chaos.killStageThread": None,  # "stage" / "stage:k": silent death
    # compile-subsystem fault injection (utils/compile_cache.py)
    "bigdl.chaos.corruptCompileCacheAt": 0,  # k: bit-flip the k-th cache entry
    "bigdl.chaos.hangCompileAt": None,    # "k" / "k:seconds": wedge k-th compile
    # serving-path fault injection (bigdl_tpu/serving)
    "bigdl.chaos.slowRequestAt": None,    # "k" / "k:seconds": k-th request handled slow
    "bigdl.chaos.poisonRequestAt": None,  # "k" / "k:m": admission positions k..m poison
    "bigdl.chaos.hangDispatchAt": None,   # "k" / "k:seconds": k-th batch dispatch wedges
    "bigdl.chaos.burstArrivals": None,    # "k" / "k:n": n extra arrivals at position k
    # LM-serving fault injection (bigdl_tpu/serving/lm.py)
    "bigdl.chaos.poisonPromptAt": None,   # "k" / "k:m": admission positions k..m poison prompts
    "bigdl.chaos.hangDecodeAt": None,     # "k" / "k:seconds": k-th decode iteration wedges
    "bigdl.chaos.evictBlockAt": 0,        # k: a KV block "evicts" at decode iteration k
    # fleet-control-plane faults (bigdl_tpu/fleet)
    "bigdl.chaos.killReplicaAt": None,    # "k" / "k:replica": async-kill a replica's
    # batcher thread at the fleet's k-th submitted request
    "bigdl.chaos.corruptCandidateAt": 0,  # k: corrupt the k-th rollout candidate's
    # weights after its fingerprint is taken (pre-cutover verify must catch it)
    "bigdl.chaos.sigtermFleetAt": 0,      # k: fleet-wide preemption (SIGTERM) at
    # the fleet's k-th submitted request
    # elastic training (utils/elastic.py): topology-elastic restore +
    # graceful preemption
    "bigdl.elastic.gracePeriod": 30.0,  # seconds for the final drain+snapshot
    "bigdl.elastic.reshardOnRestore": True,  # N->M slot reshard vs reject
    "bigdl.elastic.handleSignals": False,    # SIGTERM/SIGINT -> graceful drain
    "bigdl.elastic.globalShuffle": True,  # one global epoch permutation
    # (partition-count-invariant batch stream) vs partition-local blocks
    # (pre-elastic per-host memory footprint, same-topology replay only)
    # hung-step watchdog (utils/elastic.py): step open > k x EMA -> abort
    "bigdl.watchdog.stallFactor": 0,   # 0 disables the monitor thread
    "bigdl.watchdog.pollInterval": 0.25,  # monitor wake period, seconds
    "bigdl.watchdog.warmupSteps": 5,   # EMA warmup (compile exemption)
    "bigdl.watchdog.cooldownSteps": 50,  # heartbeats between fires
    "bigdl.watchdog.timelineDir": None,  # dump telemetry timeline here on fire
    "bigdl.check.singleton": False,
    "bigdl.summary.flushSecs": 2.0,
    # SUPERSEDED and unread: kept only so existing setters don't error —
    # the executable cache below (bigdl.compile.cacheDir) is the one that
    # works; jax's own compile cache is armed via jax.config
    # jax_compilation_cache_dir, not through this table
    "bigdl.compilation.cacheDir": None,
    # resilient compilation (utils/compile_cache.py): persistent fused-step
    # executable cache + AOT warmup watchdog + shape buckets.  NOT the
    # near-namesake bigdl.compilation.cacheDir above.
    "bigdl.compile.cacheDir": None,        # executable cache dir; None = off
    "bigdl.compile.timeoutSec": 0,         # compile watchdog abort; 0 = off
    "bigdl.compile.keepLast": 0,           # cache entries retained; 0 = all
    "bigdl.compile.buckets": None,         # "8,16,32": ragged eval/predict batches pad up
    "bigdl.compile.lockTimeoutSec": 30.0,  # single-writer lock wait cap
    "bigdl.compile.lockStaleSec": 600.0,   # steal writer locks older than this
    "bigdl.pipeline.depth": 8,             # driver-loop dispatch pipeline
    # overload-tolerant serving (bigdl_tpu/serving): admission-controlled
    # request path — bounded queue, per-request deadlines, shedding,
    # poison quarantine, hung-dispatch watchdog, graceful drain
    "bigdl.serving.maxBatch": 16,          # batcher coalesce ceiling
    "bigdl.serving.maxQueueDepth": 128,    # admission queue bound (reject past it)
    "bigdl.serving.deadlineMs": 1000.0,    # default per-request deadline
    "bigdl.serving.admissionDeadlineFactor": 1.0,  # reject when projected wait > f x deadline
    "bigdl.serving.lingerMs": 0.0,         # batcher waits this long to fill a batch
    "bigdl.serving.pollInterval": 0.05,    # batcher idle/monitor wake period, seconds
    "bigdl.serving.stallFactor": 0,        # hung-dispatch watchdog: abort > k x EMA; 0 off
    "bigdl.serving.warmupBatches": 3,      # dispatch-EMA warmup (compile exemption)
    "bigdl.serving.cooldownSteps": 8,      # batches after a watchdog fire before re-admission
    "bigdl.serving.gracePeriod": 5.0,      # drain window for SIGTERM / stop, seconds
    # LM token serving (bigdl_tpu/serving/lm.py): continuous batching over
    # a paged KV cache — ONE fixed (maxBatch, 1) decode shape, bucketed
    # prefill plan, streaming per-request token output
    "bigdl.lm.maxBatch": 8,                # concurrent decode slots (the fixed decode batch)
    "bigdl.lm.maxContext": 256,            # prompt + generated tokens ceiling per sequence
    "bigdl.lm.blockSize": 16,              # KV-cache tokens per block
    "bigdl.lm.cacheBlocks": 0,             # KV pool blocks incl. dump block; 0 = derive
    # maxBatch x blocks_per_seq(maxContext) + 1
    "bigdl.lm.prefillBuckets": None,       # "16,32,64": prompt pad-up plan; None = pow2
    # ladder from blockSize to maxContext
    "bigdl.lm.maxNewTokens": 64,           # default generation cap per request
    "bigdl.lm.deadlineMs": 5000.0,         # default end-to-end per-request deadline
    "bigdl.lm.maxQueueDepth": 128,         # admission queue bound (reject past it)
    "bigdl.lm.admissionDeadlineFactor": 0,  # reject when projected wait > f x deadline; 0 off
    "bigdl.lm.stallFactor": 0,             # hung-decode watchdog: abort > k x EMA; 0 off
    "bigdl.lm.warmupSteps": 3,             # decode-EMA warmup (compile exemption)
    "bigdl.lm.cooldownSteps": 8,           # decode iterations after a watchdog fire
    # before re-admission
    "bigdl.lm.gracePeriod": 5.0,           # drain window for SIGTERM / stop, seconds
    "bigdl.lm.pollInterval": 0.01,         # scheduler idle wake period, seconds
    "bigdl.lm.quantize": "off",            # "int8": decode matmuls on int8 weights,
    # gated by the auditor precision pass + an allclose logits check
    "bigdl.lm.quantizeRtol": 0.05,         # int8-gate allclose rtol vs full precision
    "bigdl.lm.quantizeAtol": 0.05,         # int8-gate allclose atol vs full precision
    # fleet control plane (bigdl_tpu/fleet): N models x N replicas under one
    # supervisor — zero-downtime hot swap, blue/green rollout gated on the
    # semantic checkpoint fingerprint + shadow-traffic parity, crash restarts,
    # replica autoscaling, checkpoint-to-serving promotion
    "bigdl.fleet.replicas": 1,             # replicas per service at add_model
    "bigdl.fleet.minReplicas": 1,          # autoscale floor
    "bigdl.fleet.maxReplicas": 4,          # autoscale ceiling
    "bigdl.fleet.pollInterval": 0.05,      # supervisor tick period, seconds
    "bigdl.fleet.maxReplicaRestarts": 2,   # crash restarts per replica slot
    "bigdl.fleet.gracePeriod": 5.0,        # retired-replica drain window, seconds
    "bigdl.fleet.shadowSample": 8,         # live requests mirrored per rollout
    "bigdl.fleet.parityMode": "bitwise",   # bitwise | allclose | off
    "bigdl.fleet.parityRtol": 1e-5,        # allclose rtol for shadow parity
    "bigdl.fleet.parityAtol": 1e-6,        # allclose atol for shadow parity
    "bigdl.fleet.promotionPollSec": 0.2,   # checkpoint watch_latest cadence
    "bigdl.fleet.autoscale.enabled": False,   # scale replica count per service
    "bigdl.fleet.autoscale.intervalSec": 0.25,  # decision cadence
    "bigdl.fleet.autoscale.upQueueFrac": 0.5,   # mean queue fill frac -> +1
    "bigdl.fleet.autoscale.downQueueFrac": 0.05,  # below this -> -1 toward floor
    "bigdl.fleet.autoscale.p99Factor": 0.8,  # +1 when p99 > factor x deadline
    "bigdl.fleet.autoscale.patience": 2,   # consecutive signals before acting
    "bigdl.fleet.autoscale.cooldown": 3,   # hold intervals after an action
    # streaming ingest engine (dataset/ingest.py): stage-pipelined
    # real-data path — sharded seqfile readers -> record ring -> decode
    # pool -> decoded window -> native assembler -> batch ring -> device
    # transfer-ahead (engine.BatchPrefetcher)
    "bigdl.ingest.shards": 2,              # parallel seqfile reader threads
    "bigdl.ingest.decodeWorkers": None,    # decode pool size; None = host cores
    "bigdl.ingest.recordRingDepth": 256,   # reader -> decode record ring
    "bigdl.ingest.decodedRingDepth": None, # in-flight decode window; None = 2x batch
    "bigdl.ingest.batchRingDepth": 2,      # assembled batches buffered ahead
    "bigdl.ingest.batchesInFlight": 2,     # device uploads in flight (transfer-ahead)
    "bigdl.ingest.deviceAugment": False,   # pack FULL uint8 frames + ride-along
    # crop offsets/flips; crop/flip/transpose runs on device (nn.DeviceAugment)
    "bigdl.ingest.zeroCopyUpload": True,   # dlpack handoff of assembler buffers
    # at the host->device crossing (engine.to_device); falls back per-array
    # stage autoscaling (dataset/ingest.py _Autoscaler): the supervisor
    # adds/retires decode workers (and native assemble threads) from the
    # per-stage starve/backpressure signals, governor as upper bound
    "bigdl.ingest.autoscale.enabled": True,   # scale decode/assemble workers
    "bigdl.ingest.autoscale.min": 1,          # decode-worker floor
    "bigdl.ingest.autoscale.max": 0,          # worker ceiling; 0 = host cores
    "bigdl.ingest.autoscale.intervalSec": 0.25,  # decision cadence
    "bigdl.ingest.autoscale.upStarveFrac": 0.2,  # assemble starve frac -> +1
    "bigdl.ingest.autoscale.downStarveFrac": 0.02,  # below this (or
    # backpressure-bound) -> -1 toward the floor
    "bigdl.ingest.autoscale.patience": 2,     # consecutive signals before acting
    "bigdl.ingest.autoscale.cooldown": 3,     # hold intervals after an action
    # decoded-epoch cache (dataset/epoch_cache.py): repeated-epoch training
    # pays JPEG decode once; RAM segments, optional checksummed disk spill
    "bigdl.ingest.epochCache": False,      # cache decoded frames across epochs
    "bigdl.ingest.epochCacheDir": None,    # disk-spill dir; None = RAM only
    "bigdl.ingest.epochCacheBudgetMB": 0,  # cache byte cap; 0 = governor only
    "bigdl.ingest.epochCacheSegmentRecords": 256,  # records per segment
    # self-healing ingest (error taxonomy + quarantine + supervision)
    "bigdl.ingest.maxBadRecords": 0,       # data-error quarantine budget; 0 = fail fast
    "bigdl.ingest.maxStageRestarts": 2,    # dead-stage restarts before escalation
    "bigdl.ingest.fallbackOnFailure": False,  # dead engine -> sync MT path mid-epoch
    "bigdl.ingest.stallTimeoutSec": 0,     # wedged-ring detection; 0 = disabled
    # static-analysis / sanitizer passes (bigdl_tpu/analysis): each pass is
    # "strict" (raise), "warn" (log + count), or "off"
    "bigdl.analysis.retrace": "warn",      # recompile sentinel on fused steps
    "bigdl.analysis.retraceWarmupSteps": 2,  # calls treated as warmup compiles
    "bigdl.analysis.retraceBudget": 2,     # distinct signatures allowed in warmup
    "bigdl.analysis.hostSync": "warn",     # implicit device→host pulls in hot loop
    "bigdl.analysis.hotLoopScope": "iteration",  # sanitize fetch+step, or "step"
    "bigdl.analysis.contracts": "warn",    # module contract checker strictness
    "bigdl.analysis.lockWitness": "off",   # runtime lock-order witness
    # (analysis/lockwitness): strict raises LockOrderViolation on any
    # acquisition-order cycle, warn logs once per edge pair; armed
    # strict for every tier-1 test by the conftest fixture
    "bigdl.chaos.lockDelayAt": None,   # "<lockname>:k[:seconds]": the k-th
    # acquisition of the named witness lock stalls (default 0.05 s),
    # deterministically widening a racy window; once per position
    # HLO program auditor (bigdl_tpu/analysis/hlo_audit): static passes
    # over every fused step's lowered StableHLO, same strict/warn/off
    # vocabulary as bigdl.analysis.*
    "bigdl.audit.collectives": "warn",  # collective contract checker
    "bigdl.audit.precision": "warn",    # f64 / f32-in-bf16 drift pass
    "bigdl.audit.memory": "warn",       # peak-buffer + transpose budget pass
    # training-state integrity (bigdl_tpu/integrity): on-device
    # fingerprints + cross-replica agreement + weight-health gates.
    # everyN is the DRIVER pull/verify cadence — when > 0 the fused
    # steps compute fingerprints every iteration and the driver
    # classifies them every N iterations; 0 disables the whole path
    "bigdl.integrity.everyN": 0,
    "bigdl.integrity.seed": 0x51D0,        # projection-sign seed
    "bigdl.integrity.healthFactor": 0,     # weight-health gate: > k x EMA; 0 off
    "bigdl.integrity.healthWarmup": 5,     # EMA warmup observations
    "bigdl.integrity.healthCooldown": 50,  # observations between fires
    # integrity fault injection (silent-data-corruption simulators)
    "bigdl.chaos.bitflipParamAt": None,    # "k" / "k:leaf": flip one param bit
    "bigdl.chaos.desyncReplicaAt": None,   # "k" / "k:replica": one dp replica drifts
    "bigdl.chaos.corruptStateBeforeSaveAt": 0,  # k: k-th snapshot capture corrupted
    # audit fault injection: provoke the violations the auditor exists
    # to catch (step-BUILD time, unlike the runtime chaos hooks above)
    "bigdl.chaos.extraAllGather": False,  # redundant all-gather in shard_map
    "bigdl.chaos.f32Upcast": False,       # f32 matmul inside a bf16 program
    "bigdl.chaos.dropBucketCollective": None,  # k: bucket k's reduce-scatter
    # silently replaced by a local slice — MISSING-collective auditor prey
    # latency-hiding collective overlap (parallel/distri_optimizer.py):
    # the ZeRO-1 exchange runs as N independent per-bucket reduce-scatter
    # -> update -> all-gather chains so XLA's latency-hiding scheduler can
    # overlap ICI with compute; same wire bytes, same numerics
    "bigdl.parallel.overlap": True,        # False = monolithic baseline step
    "bigdl.parallel.overlapBuckets": 4,    # contiguous param buckets per step
    # MoE execution path (nn/moe.py): "einsum" = capacity-slot dispatch/
    # combine einsums (GShard reference form), "grouped" = expert-sorted
    # scatter + grouped batched matmul + gather-combine (same capacity-drop
    # and aux-loss semantics, O(t*k*d) instead of O(t*E*C*d) data movement)
    "bigdl.moe.impl": "einsum",
    # default activation-checkpoint policy for transformer_lm blocks when
    # the builder's remat arg is unset: "nothing" / "dots" / "save_attn"
    # (nn.Remat's preset vocabulary); None = no remat
    "bigdl.remat.policy": None,
    # runtime telemetry (bigdl_tpu/telemetry): span tracer + step-time
    # decomposition + metrics registry
    "bigdl.telemetry.trace": False,        # arm the span tracer
    "bigdl.telemetry.ringSize": 65536,     # per-thread span ring capacity
    "bigdl.telemetry.tracePath": None,     # export Chrome trace JSON here at run end
    "bigdl.telemetry.snapshotPath": None,  # write telemetry.json registry snapshot here
    "bigdl.telemetry.logEveryN": 1,        # throughput log line every N iterations
    "bigdl.telemetry.percentileWindow": 512,  # rolling step-latency window
    "bigdl.telemetry.slowStepFactor": 0,   # slow step = > k x EMA; 0 disables
    "bigdl.telemetry.slowStepWarmup": 5,   # EMA warmup steps before detection
    "bigdl.telemetry.slowStepCooldown": 50,  # min steps between anomaly windows
    "bigdl.telemetry.profileOnSlowStep": None,  # dir: capture jax.profiler + timeline
    "bigdl.telemetry.mfu": False,          # estimate fused-step FLOPs -> MFU logging
    "bigdl.telemetry.peakTflops": None,    # chip peak for MFU% (None: log TFLOP/s)
    "bigdl.telemetry.maxTimelineDumps": 8,  # timeline dump files per run
    # (slow-step detector + watchdog), oldest-first eviction; 0 disables
    # resource-exhaustion resilience (bigdl_tpu/resources): HBM preflight
    # + microbatch backoff, host-memory governor, disk-full degradation
    "bigdl.resources.deviceMemBudgetMB": 0,  # HBM budget per fused step;
    # preflight + dispatch-OOM -> microbatch re-plan; 0 = preflight off
    "bigdl.resources.hostMemBudgetMB": 0,  # soft host budget over all
    # accounted rings/queues; breach shrinks depths; 0 = accounting only
    # resource-exhaustion fault injection (utils/chaos.py)
    "bigdl.chaos.oomStepAt": 0,        # k: k-th step dispatch raises a
    # realistic RESOURCE_EXHAUSTED (once per plan)
    "bigdl.chaos.diskFullAt": None,    # "k"/"k:substr" (comma-separable):
    # the k-th write_bytes [matching substr] raises ENOSPC, once each
    "bigdl.chaos.hostMemPressureAt": 0,  # k: governor poll k reports
    # zero free bytes (once per plan) — shrinker/backpressure prey
    "bigdl.chaos.starveStageAt": None,   # "stage:k" / "stage:k:seconds":
    # the named ingest stage throttles from its k-th item for the window —
    # downstream stages starve; autoscaler acceptance prey
    # per-request distributed tracing (telemetry/request_trace.py)
    "bigdl.trace.requests": False,       # mint a trace id per serving/LM/fleet
    # submission; span chain + terminal verdict per request
    "bigdl.trace.maxTraces": 2048,       # retained traces (oldest evicted first)
    "bigdl.trace.maxSpansPerTrace": 512,  # per-trace span bound (then truncated flag)
    # incident flight recorder (telemetry/incident.py)
    "bigdl.incident.ringSize": 512,      # bounded structured-event ring capacity
    "bigdl.incident.maxDumps": 8,        # bundle files per run, oldest-first
    # eviction; 0 disables bundle writes entirely
    "bigdl.incident.dir": None,          # bundle directory; None = CWD
    "bigdl.incident.autoDump": True,     # write one bundle per terminal fault slug
    # driver log file (utils/logger_filter.py): size-capped rotation so a
    # long run cannot grow bigdl.log without bound
    "bigdl.utils.LoggerFilter.disable": False,      # leave logging untouched
    "bigdl.utils.LoggerFilter.enableSparkLog": True,  # redirect chatty infra logs
    "bigdl.utils.LoggerFilter.logFile": None,       # None = <CWD>/bigdl.log
    "bigdl.utils.LoggerFilter.maxBytes": 10485760,  # rotate past 10 MiB
    "bigdl.utils.LoggerFilter.backupCount": 2,      # rotated files retained
}

_OVERRIDES: Dict[str, Any] = {}


def _env_key(name: str) -> str:
    return name.replace(".", "_").upper()


def get_property(name: str, default: Optional[Any] = None) -> Any:
    """Resolution order: set_property override > env var > table default."""
    if name in _OVERRIDES:
        return _OVERRIDES[name]
    env = os.environ.get(_env_key(name))
    if env is not None:
        return env
    if name in _DEFAULTS and _DEFAULTS[name] is not None:
        return _DEFAULTS[name]
    return default


def get_int(name: str, default: int = 0) -> int:
    v = get_property(name, default)
    return int(v)


def get_float(name: str, default: float = 0.0) -> float:
    v = get_property(name, default)
    return float(v)


def get_bool(name: str, default: bool = False) -> bool:
    v = get_property(name, default)
    if isinstance(v, bool):
        return v
    return str(v).lower() in ("1", "true", "yes", "on")


def set_property(name: str, value: Any) -> None:
    _OVERRIDES[name] = value


def clear_property(name: str) -> None:
    _OVERRIDES.pop(name, None)


def known_properties() -> Dict[str, Any]:
    """The full table with current values (for diagnostics)."""
    return {k: get_property(k) for k in _DEFAULTS}


def non_default_properties() -> Dict[str, Any]:
    """Every property whose effective value differs from the table
    default — programmatic overrides, ``BIGDL_*`` environment settings,
    and override keys outside the table.  The incident bundle embeds
    exactly this (the *effective* configuration an operator must know
    to explain a run, without the 200-line full table)."""
    out: Dict[str, Any] = {}
    for name, default in _DEFAULTS.items():
        value = get_property(name)
        if value != default and not (value is None and default is None):
            out[name] = value
    for name, value in _OVERRIDES.items():
        if name not in _DEFAULTS:
            out[name] = value
    return out
