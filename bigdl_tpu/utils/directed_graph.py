"""DirectedGraph / Node: generic DAG with traversals and the ``->`` edge DSL.

Reference equivalent: ``utils/DirectedGraph.scala:34,135`` — used by the Graph
container and the TF-import pattern matcher.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Iterator, List, Optional


class Edge:
    def __init__(self, from_index: Optional[int] = None):
        self.from_index = from_index


class Node:
    """Graph node holding an element (reference ``Node``, ``:135``)."""

    def __init__(self, element: Any):
        self.element = element
        self.nexts: List[tuple] = []   # (Node, Edge)
        self.prevs: List[tuple] = []   # (Node, Edge)

    def add(self, node: "Node", edge: Optional[Edge] = None) -> "Node":
        """``self -> node`` (reference ``Node.->:155``).  Returns ``node``."""
        e = edge or Edge()
        self.nexts.append((node, e))
        node.prevs.append((self, e))
        return node

    def __rshift__(self, node: "Node") -> "Node":
        return self.add(node)

    def delete(self, node: "Node", edge: Optional[Edge] = None) -> "Node":
        if edge is not None:
            self.nexts = [(n, e) for n, e in self.nexts
                          if not (n is node and e is edge)]
            node.prevs = [(n, e) for n, e in node.prevs
                          if not (n is self and e is edge)]
        else:
            self.nexts = [(n, e) for n, e in self.nexts if n is not node]
            node.prevs = [(n, e) for n, e in node.prevs if n is not self]
        return self

    def remove_prev_edges(self) -> "Node":
        for p, e in list(self.prevs):
            p.nexts = [(n, ee) for n, ee in p.nexts if ee is not e]
        self.prevs = []
        return self

    def remove_next_edges(self) -> "Node":
        for n, e in list(self.nexts):
            n.prevs = [(p, ee) for p, ee in n.prevs if ee is not e]
        self.nexts = []
        return self

    def graph(self, reverse: bool = False) -> "DirectedGraph":
        return DirectedGraph(self, reverse)

    def __repr__(self):
        return f"Node({self.element!r})"


class DirectedGraph:
    """DAG rooted at ``source`` (reference ``DirectedGraph.scala:34``).

    ``reverse=True`` walks ``prevs`` instead of ``nexts`` — used for backward
    passes from the output node.
    """

    def __init__(self, source: Node, reverse: bool = False):
        self.source = source
        self.reverse = reverse

    def _next(self, node: Node) -> List[Node]:
        edges = node.prevs if self.reverse else node.nexts
        return [n for n, _ in edges]

    def size(self) -> int:
        return sum(1 for _ in self.bfs())

    def edges(self) -> int:
        return sum(len(self._next(n)) for n in self.bfs())

    def bfs(self) -> Iterator[Node]:
        """Breadth-first from source (reference ``BFS:108``)."""
        seen = {id(self.source)}
        queue = deque([self.source])
        while queue:
            node = queue.popleft()
            yield node
            for n in self._next(node):
                if id(n) not in seen:
                    seen.add(id(n))
                    queue.append(n)

    def dfs(self) -> Iterator[Node]:
        """Depth-first from source (reference ``DFS:85``)."""
        seen = set()
        stack = [self.source]
        while stack:
            node = stack.pop()
            if id(node) in seen:
                continue
            seen.add(id(node))
            yield node
            for n in self._next(node):
                if id(n) not in seen:
                    stack.append(n)

    def topology_sort(self) -> List[Node]:
        """Kahn's algorithm; raises on cycles (reference ``topologySort:52``)."""
        nodes = list(self.bfs())
        in_graph = {id(n) for n in nodes}
        indeg = {}
        for n in nodes:
            back = n.nexts if self.reverse else n.prevs
            indeg[id(n)] = sum(1 for p, _ in back if id(p) in in_graph)
        queue = deque(n for n in nodes if indeg[id(n)] == 0)
        out: List[Node] = []
        while queue:
            node = queue.popleft()
            out.append(node)
            for n in self._next(node):
                if id(n) in in_graph:
                    indeg[id(n)] -= 1
                    if indeg[id(n)] == 0:
                        queue.append(n)
        if len(out) != len(nodes):
            raise ValueError("graph contains a cycle, cannot topology-sort")
        return out

    def clone_graph(self) -> "DirectedGraph":
        mapping = {}
        for n in self.bfs():
            mapping[id(n)] = Node(n.element)
        for n in self.bfs():
            for nxt, e in n.nexts:
                if id(nxt) in mapping:
                    mapping[id(n)].add(mapping[id(nxt)], Edge(e.from_index))
        return DirectedGraph(mapping[id(self.source)], self.reverse)
