"""Table: the Lua-style heterogeneous container.

Reference equivalent: ``utils/Table.scala:34`` — an int/any-keyed map used both
as an Activity (multi-input/output of layers) and as the optimizer state dict,
with a ``T(...)`` varargs constructor that auto-assigns 1-based integer keys.

In the TPU rebuild, layer activities are plain nested lists/tuples/dicts of jax
arrays (pytrees — XLA-friendly and jit-transparent), so Table is NOT on the hot
path.  It survives as the user-facing optimizer-state / multi-value container
for API parity: 1-based integer keys, ``insert``/``remove`` with Lua shifting
semantics, and pytree registration so a Table can still cross a jit boundary.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional

import jax


class Table:
    """Int/any-keyed map with Lua array semantics (reference ``utils/Table``)."""

    def __init__(self, state: Optional[Dict[Any, Any]] = None):
        self._state: Dict[Any, Any] = dict(state) if state else {}

    # -- map interface ----------------------------------------------------

    def __getitem__(self, key):
        return self._state[key]

    def get(self, key, default=None):
        return self._state.get(key, default)

    def __setitem__(self, key, value):
        self._state[key] = value

    def __delitem__(self, key):
        del self._state[key]

    def __contains__(self, key) -> bool:
        return key in self._state

    def contains(self, key) -> bool:
        return key in self._state

    def get_or_update(self, key, factory):
        if key not in self._state:
            self._state[key] = factory()
        return self._state[key]

    def update(self, other) -> "Table":
        src = other._state if isinstance(other, Table) else other
        self._state.update(src)
        return self

    def keys(self):
        return self._state.keys()

    def values(self):
        return self._state.values()

    def items(self):
        return self._state.items()

    def __iter__(self) -> Iterator:
        return iter(self._state)

    def __len__(self) -> int:
        return len(self._state)

    # -- Lua array semantics ----------------------------------------------

    def length(self) -> int:
        """Count of the contiguous 1..n integer-key prefix (Lua ``#t``)."""
        n = 0
        while (n + 1) in self._state:
            n += 1
        return n

    def insert(self, *args) -> "Table":
        """``insert(value)`` appends; ``insert(index, value)`` shifts right
        (reference ``Table.insert``)."""
        if len(args) == 1:
            self._state[self.length() + 1] = args[0]
        else:
            index, value = args
            n = self.length()
            for i in range(n, index - 1, -1):
                self._state[i + 1] = self._state[i]
            self._state[index] = value
        return self

    def remove(self, index: Optional[int] = None):
        """Remove at index (default: last), shifting left."""
        n = self.length()
        if index is None:
            index = n
        if index == 0 or index > n and index not in self._state:
            return None
        value = self._state.pop(index, None)
        for i in range(index + 1, n + 1):
            self._state[i - 1] = self._state.pop(i)
        return value

    # -- misc -------------------------------------------------------------

    def clone(self) -> "Table":
        return Table(dict(self._state))

    def to_dict(self) -> Dict[Any, Any]:
        return dict(self._state)

    def to_seq(self):
        """The 1..n prefix as a list (reference ``toSeq``)."""
        return [self._state[i] for i in range(1, self.length() + 1)]

    def __eq__(self, other) -> bool:
        if isinstance(other, Table):
            return self._state == other._state
        return NotImplemented

    def __hash__(self):
        return id(self)

    def __repr__(self) -> str:
        inner = ", ".join(f"{k}: {v!r}" for k, v in sorted(
            self._state.items(), key=lambda kv: str(kv[0])))
        return f"T{{{inner}}}"


def T(*args, **kwargs) -> Table:
    """Varargs constructor: ``T(a, b)`` → {1: a, 2: b}; kwargs become string
    keys (reference ``object T``, ``utils/Table.scala:269``)."""
    t = Table()
    for i, v in enumerate(args, start=1):
        t[i] = v
    for k, v in kwargs.items():
        t[k] = v
    return t


def _table_flatten(t: Table):
    keys = sorted(t._state.keys(), key=lambda k: (str(type(k)), str(k)))
    return [t._state[k] for k in keys], tuple(keys)


def _table_unflatten(keys, values) -> Table:
    return Table(dict(zip(keys, values)))


jax.tree_util.register_pytree_node(Table, _table_flatten, _table_unflatten)
