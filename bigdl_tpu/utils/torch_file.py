"""Torch7 ``.t7`` binary serialization: reader + writer.

Reference equivalent: ``utils/TorchFile.scala:67`` (1,056 LoC) — the full
Torch7 object format used for Torch interop and the reference's TH-parity
test harness.

Format (little-endian): each value is a type tag (int32) followed by the
payload.  Tags: NIL=0, NUMBER=1 (double), STRING=2 (len+bytes), TABLE=3,
TORCH=4 (object: index, version string ``V <n>``, class name, class payload),
BOOLEAN=5.  Objects are memoised by index so aliased tensors/tables
round-trip as aliases.  Tensors serialize as (ndim, sizes, strides,
storage-offset(1-based), Storage object); storages as (size, raw data).

Scope: numbers, booleans, strings, tables (dict/list), Float/Double/Long/
Int/Byte tensors and storages — the subset the reference's model/tensor
files actually contain.  Unknown torch classes raise with the class name.
"""

from __future__ import annotations

import struct
from typing import Any, BinaryIO, Dict, Optional

import numpy as np

TYPE_NIL = 0
TYPE_NUMBER = 1
TYPE_STRING = 2
TYPE_TABLE = 3
TYPE_TORCH = 4
TYPE_BOOLEAN = 5

_TENSOR_CLASSES = {
    "torch.DoubleTensor": np.float64,
    "torch.FloatTensor": np.float32,
    "torch.LongTensor": np.int64,
    "torch.IntTensor": np.int32,
    "torch.ByteTensor": np.uint8,
    "torch.CharTensor": np.int8,
    "torch.ShortTensor": np.int16,
}
_STORAGE_CLASSES = {
    "torch.DoubleStorage": np.float64,
    "torch.FloatStorage": np.float32,
    "torch.LongStorage": np.int64,
    "torch.IntStorage": np.int32,
    "torch.ByteStorage": np.uint8,
    "torch.CharStorage": np.int8,
    "torch.ShortStorage": np.int16,
}
_DTYPE_TO_TENSOR = {np.dtype(v): k for k, v in _TENSOR_CLASSES.items()}
_DTYPE_TO_STORAGE = {np.dtype(v): k for k, v in _STORAGE_CLASSES.items()}


class TorchObject:
    """A torch class instance, kept as (class_name, payload table).

    The reader produces these for any non-tensor torch class (nn modules,
    optim states, …); the writer serializes them back, so module trees
    round-trip.  ``utils/torch_module.py`` converts nn.* trees to Modules."""

    def __init__(self, torch_class: str, payload: Any):
        self.torch_class = torch_class
        self.payload = payload

    def __repr__(self):
        return f"TorchObject({self.torch_class})"


class LongStorage:
    """Marks an int sequence to serialize as ``torch.LongStorage`` (torch
    stores View/Reshape sizes as storages, not tensors)."""

    def __init__(self, values):
        self.values = np.asarray(values, dtype=np.int64).ravel()

    def __repr__(self):
        return f"LongStorage({self.values.tolist()})"


# ---------------------------------------------------------------------------
# reading
# ---------------------------------------------------------------------------

class _Reader:
    def __init__(self, f: BinaryIO):
        self.f = f
        self.memo: Dict[int, Any] = {}

    def _i32(self) -> int:
        return struct.unpack("<i", self.f.read(4))[0]

    def _i64(self) -> int:
        return struct.unpack("<q", self.f.read(8))[0]

    def _f64(self) -> float:
        return struct.unpack("<d", self.f.read(8))[0]

    def read(self) -> Any:
        tag = self._i32()
        if tag == TYPE_NIL:
            return None
        if tag == TYPE_NUMBER:
            v = self._f64()
            import math
            return int(v) if math.isfinite(v) and v == int(v) else v
        if tag == TYPE_STRING:
            n = self._i32()
            return self.f.read(n).decode("utf-8", errors="replace")
        if tag == TYPE_BOOLEAN:
            return self._i32() == 1
        if tag == TYPE_TABLE:
            return self._read_table()
        if tag == TYPE_TORCH:
            return self._read_torch()
        raise ValueError(f"unknown t7 type tag {tag}")

    def _read_table(self) -> Any:
        idx = self._i32()
        if idx in self.memo:
            return self.memo[idx]
        out: Dict[Any, Any] = {}
        self.memo[idx] = out
        n = self._i32()
        for _ in range(n):
            k = self.read()
            out[k] = self.read()
        # 1..n integer keys -> python list (lua array-style table)
        if out and all(isinstance(k, int) for k in out) and \
                sorted(out) == list(range(1, len(out) + 1)):
            lst = [out[i] for i in range(1, len(out) + 1)]
            self.memo[idx] = lst
            return lst
        return out

    def _raw_string(self) -> str:
        """Class/version strings inside a TORCH record carry no type tag."""
        n = self._i32()
        return self.f.read(n).decode("utf-8", errors="replace")

    def _read_torch(self) -> Any:
        idx = self._i32()
        if idx in self.memo:
            return self.memo[idx]
        version = self._raw_string()  # "V 1"-style version marker
        if version.startswith("V "):
            cls = self._raw_string()
        else:  # legacy files: no version record, that WAS the class name
            cls = version
        if cls in _TENSOR_CLASSES:
            t = self._read_tensor(np.dtype(_TENSOR_CLASSES[cls]))
            self.memo[idx] = t
            return t
        if cls in _STORAGE_CLASSES:
            s = self._read_storage(np.dtype(_STORAGE_CLASSES[cls]))
            self.memo[idx] = s
            return s
        obj = TorchObject(cls, self.read())
        self.memo[idx] = obj
        return obj

    def _read_tensor(self, dtype) -> np.ndarray:
        ndim = self._i32()
        sizes = [self._i64() for _ in range(ndim)]
        strides = [self._i64() for _ in range(ndim)]
        offset = self._i64() - 1  # 1-based
        storage = self.read()
        if ndim == 0 or storage is None:
            return np.zeros(sizes, dtype=dtype)
        flat = np.asarray(storage, dtype=dtype)
        itemsize = flat.itemsize
        return np.lib.stride_tricks.as_strided(
            flat[offset:], shape=sizes,
            strides=[s * itemsize for s in strides]).copy()

    def _read_storage(self, dtype) -> np.ndarray:
        n = self._i64()
        return np.frombuffer(self.f.read(n * dtype.itemsize),
                             dtype=dtype).copy()


# ---------------------------------------------------------------------------
# writing
# ---------------------------------------------------------------------------

class _Writer:
    def __init__(self, f: BinaryIO):
        self.f = f
        self.memo: Dict[int, int] = {}   # id(obj) -> index
        # memoised objects are kept alive so CPython cannot reuse their id
        # for a later, distinct object (which would serialize as an alias)
        self._keep: list = []
        self.next_index = 1

    def _i32(self, v: int) -> None:
        self.f.write(struct.pack("<i", v))

    def _i64(self, v: int) -> None:
        self.f.write(struct.pack("<q", v))

    def _raw_string(self, s: str) -> None:
        data = s.encode("utf-8")
        self._i32(len(data))
        self.f.write(data)

    def write(self, obj: Any) -> None:
        if obj is None:
            self._i32(TYPE_NIL)
        elif isinstance(obj, bool):
            self._i32(TYPE_BOOLEAN)
            self._i32(1 if obj else 0)
        elif isinstance(obj, (int, float)):
            self._i32(TYPE_NUMBER)
            self.f.write(struct.pack("<d", float(obj)))
        elif isinstance(obj, str):
            data = obj.encode("utf-8")
            self._i32(TYPE_STRING)
            self._i32(len(data))
            self.f.write(data)
        elif isinstance(obj, np.ndarray):
            self._write_tensor(obj)
        elif isinstance(obj, TorchObject):
            self._i32(TYPE_TORCH)
            if self._memoise(obj) is not None:
                return
            self._raw_string("V 1")
            self._raw_string(obj.torch_class)
            self.write(obj.payload)
        elif isinstance(obj, LongStorage):
            self._i32(TYPE_TORCH)
            if self._memoise(obj) is not None:
                return
            self._raw_string("V 1")
            self._raw_string("torch.LongStorage")
            self._i64(obj.values.size)
            self.f.write(obj.values.tobytes())
        elif isinstance(obj, dict):
            self._write_table(obj, obj.items())
        elif isinstance(obj, (list, tuple)):
            self._write_table(obj, ((i + 1, v) for i, v in enumerate(obj)),
                              n=len(obj))
        else:
            raise TypeError(f"cannot serialize {type(obj).__name__} to .t7")

    def _memoise(self, obj) -> Optional[int]:
        """Returns the existing index (after writing it) or None if new."""
        if id(obj) in self.memo:
            self._i32(self.memo[id(obj)])
            return self.memo[id(obj)]
        self.memo[id(obj)] = self.next_index
        self._keep.append(obj)
        self._i32(self.next_index)
        self.next_index += 1
        return None

    def _write_table(self, obj, items, n: Optional[int] = None) -> None:
        self._i32(TYPE_TABLE)
        if self._memoise(obj) is not None:
            return
        self._i32(len(obj) if n is None else n)
        for k, v in items:
            self.write(k)
            self.write(v)

    def _write_tensor(self, arr: np.ndarray) -> None:
        cls = _DTYPE_TO_TENSOR.get(arr.dtype)
        if cls is None:
            arr = arr.astype(np.float32)
            cls = "torch.FloatTensor"
        self._i32(TYPE_TORCH)
        if self._memoise(arr) is not None:
            return
        self._raw_string("V 1")
        self._raw_string(cls)
        arr = np.ascontiguousarray(arr)
        self._i32(arr.ndim)
        for s in arr.shape:
            self._i64(s)
        stride = 1
        strides = []
        for s in reversed(arr.shape):
            strides.append(stride)
            stride *= s
        for s in reversed(strides):
            self._i64(s)
        self._i64(1)  # storage offset, 1-based
        # storage object
        self._i32(TYPE_TORCH)
        self._i32(self.next_index)
        self.next_index += 1
        self._raw_string("V 1")
        self._raw_string(_DTYPE_TO_STORAGE[arr.dtype])
        self._i64(arr.size)
        self.f.write(arr.tobytes())


def load(path: str) -> Any:
    """Read one value from a ``.t7`` file (reference ``TorchFile.load``)."""
    with open(path, "rb") as f:
        return _Reader(f).read()


def save(path: str, obj: Any) -> None:
    """Write one value to a ``.t7`` file (reference ``TorchFile.save``)."""
    with open(path, "wb") as f:
        _Writer(f).write(obj)
