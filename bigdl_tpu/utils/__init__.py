"""bigdl_tpu.utils — Table, persistence, RNG, DAG, misc helpers.

Mirrors the reference's ``com.intel.analytics.bigdl.utils`` (SURVEY §2.2),
minus the thread-pool machinery (XLA owns intra-op parallelism on TPU).
"""

from bigdl_tpu.utils.table import Table, T
from bigdl_tpu.utils import file_io
from bigdl_tpu.utils.file_io import save, load
from bigdl_tpu.utils.random_generator import RandomGenerator
from bigdl_tpu.utils.directed_graph import DirectedGraph, Node, Edge


def kth_largest(arr, k: int):
    """k-th largest element (1-based k) — straggler threshold helper
    (reference ``utils/Util.scala:20`` quickselect)."""
    import numpy as np
    a = np.asarray(arr)
    if not (1 <= k <= a.size):
        raise ValueError(f"k={k} out of range for size {a.size}")
    return np.partition(a, a.size - k)[a.size - k]


__all__ = ["Table", "T", "file_io", "save", "load", "RandomGenerator",
           "DirectedGraph", "Node", "Edge", "kth_largest"]
