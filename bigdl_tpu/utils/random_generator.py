"""RandomGenerator: seedable host-side RNG for data pipelines and init.

Reference equivalent: ``utils/RandomGenerator.scala:23`` — a hand-written,
thread-local Mersenne Twister used for init and data augmentation.

TPU-native split: *device-side* randomness (Dropout masks, RReLU slopes) uses
jax's counter-based PRNG keys threaded through ``Module.apply`` — reproducible
under jit and across shardings, which a stateful MT could never be.
*Host-side* randomness (shuffles, crops, jitter in the numpy data pipeline)
uses this class: numpy's MT19937, same algorithm family as the reference, one
instance per thread.
"""

from __future__ import annotations

import threading

import numpy as np


class RandomGenerator:
    """Thread-local seedable generator (mirrors reference RNG surface)."""

    _tls = threading.local()

    def __init__(self, seed: int = 5489):  # 5489 = MT19937 default, as in Torch
        self._seed = seed
        self._rng = np.random.RandomState(seed)

    @classmethod
    def RNG(cls) -> "RandomGenerator":
        """The thread-local instance (reference ``RandomGenerator.RNG``)."""
        inst = getattr(cls._tls, "inst", None)
        if inst is None:
            inst = cls()
            cls._tls.inst = inst
        return inst

    @classmethod
    def adopt(cls, inst: "RandomGenerator") -> None:
        """Install ``inst`` as THIS thread's generator.

        Used by single-producer worker threads (``Engine.BatchPrefetcher``)
        that take over a stream the constructing thread started: epoch
        reshuffles must continue the SAME RandomState the user seeded via
        ``set_seed`` on the main thread, not a fresh default-seeded
        thread-local — otherwise reproducibility silently depends on which
        thread performs the rollover (prefetch depth 0 vs >0).

        Adoption is a HANDOFF, not a share: after it, the worker thread is
        the stream's single drawer for the prefetcher's lifetime.  The
        underlying numpy RandomState is not thread-safe, so the handing-off
        thread must not keep drawing from the same instance concurrently —
        use a separate seeded ``RandomGenerator`` (or another thread, whose
        thread-local is distinct) for any concurrent host randomness."""
        cls._tls.inst = inst

    def set_seed(self, seed: int) -> "RandomGenerator":
        self._seed = seed
        self._rng = np.random.RandomState(seed)
        return self

    def get_seed(self) -> int:
        return self._seed

    @property
    def np(self) -> np.random.RandomState:
        return self._rng

    def uniform(self, a: float = 0.0, b: float = 1.0) -> float:
        return float(self._rng.uniform(a, b))

    def normal(self, mean: float = 0.0, stdv: float = 1.0) -> float:
        return float(self._rng.normal(mean, stdv))

    def bernoulli(self, p: float = 0.5) -> bool:
        return bool(self._rng.uniform() <= p)

    def random_int(self, low: int, high: int) -> int:
        """Inclusive-exclusive [low, high)."""
        return int(self._rng.randint(low, high))

    def permutation(self, n: int) -> np.ndarray:
        return self._rng.permutation(n)

    def shuffle(self, arr) -> None:
        self._rng.shuffle(arr)
