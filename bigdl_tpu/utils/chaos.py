"""Chaos harness: config-driven fault injection for the storage layer and
the driver loop.

Production training stacks prove their recovery paths by injecting the
failures they claim to survive (the reference proves its retry loop with a
model that throws on schedule, ``optim/DistriOptimizerSpec.scala:89-99``).
This module is the TPU-native fault injector: a thin choke point that
``utils.file_io`` consults on every payload write and the shared ``_drive``
loop consults on every iteration.  All behaviour is driven by
``bigdl.chaos.*`` configuration keys so the same injection plan runs
identically under pytest, a soak script, or a real cluster rehearsal:

==============================  =============================================
``bigdl.chaos.failWriteAt``     k: the k-th payload write raises
                                :class:`ChaosError` after writing a partial
                                prefix (a torn write + crash — the atomic
                                temp never reaches its final name).
``bigdl.chaos.truncateWriteAt`` k: the k-th payload write silently drops the
                                second half of its bytes and "succeeds" —
                                the worst case: the rename commits a
                                corrupt object only a checksum can catch.
``bigdl.chaos.transientWrites`` n: the first n payload writes raise a
                                transient :class:`ChaosError` and then
                                recover — exercises the bounded retry in
                                ``file_io`` (a blip on ``hdfs://``/``s3://``
                                must not abort a checkpoint).
``bigdl.chaos.failStepAt``      k: the driver loop raises at iteration k
                                (simulated preemption mid-training; the
                                retry-from-snapshot loop must absorb it).
``bigdl.chaos.nanLossAt``       "k" or "k:m": the driver reports a
                                non-finite loss for the k-th..m-th driver
                                iterations OBSERVED by the harness (counted
                                across retries, so a restore-and-replay
                                runs past the span and recovers) —
                                exercises the divergence guard's host-side
                                counting without poisoning device state.
``bigdl.chaos.preemptAt``       k: at iteration k the harness calls
                                ``elastic.request_preemption`` ONCE — the
                                same flag a real SIGTERM handler sets, so
                                the driver's graceful drain (publish +
                                final verified snapshot + resumable
                                marker) runs exactly as under a scheduler
                                preemption.
``bigdl.chaos.stallStepAt``     "k" or "k:seconds": iteration k blocks the
                                driver thread for ``seconds`` (default
                                5.0) — a simulated wedged step the
                                hung-step watchdog must detect and abort.
``bigdl.chaos.topologyChangeAt``  k: the driver raises ONCE at iteration k
                                (like ``failStepAt`` but named for the
                                scenario) — the test/rehearsal then
                                resumes the snapshot on a DIFFERENT
                                device count, proving the topology-
                                elastic restore path end to end.
``bigdl.chaos.corruptRecordAt`` "k" or "k:m": ingest records k..m (0-based
                                stream position) read as corrupt — the
                                quarantine must skip them, the sync path
                                must die on them.
``bigdl.chaos.corruptRecordEvery`` n: every n-th ingest record reads corrupt
                                (rate-based sibling of ``corruptRecordAt``
                                for throughput-under-dirt benchmarks).
``bigdl.chaos.failDecodeAt``    "k" or "k:m": records k..m decode to an
                                undecodable-image error (a data fault the
                                decode stage must quarantine, not an IO
                                blip).
``bigdl.chaos.transientReads``  n: the first n ingest record reads raise a
                                transient :class:`ChaosError` and then
                                recover — exercises the reader stage's
                                capped-backoff retry (a remote-read blip
                                must not quarantine anything or abort).
``bigdl.chaos.killStageThread`` "stage" or "stage:k" (stage in reader /
                                assembler / decode): the named ingest
                                stage thread dies SILENTLY after its k-th
                                item (default 1) — no error surfaced, no
                                done flag: exactly the failure the stage
                                supervisor must detect and restart.
``bigdl.chaos.starveStageAt``   "stage:k" or "stage:k:seconds" (stage in
                                read / decode / assemble): from its k-th
                                item the named ingest stage THROTTLES
                                (each item pauses ~50 ms) for ``seconds``
                                (default 1.0) — the stage stays alive but
                                its downstream starves, exactly the
                                signal the stage autoscaler must answer
                                with added workers (once per plan).
``bigdl.chaos.corruptCompileCacheAt`` k: the k-th compile-cache entry
                                written gets one bit flipped AFTER its
                                manifest checksum was computed — a
                                committed-but-rotten entry the warm-start
                                verification must catch and degrade to a
                                recompile.
``bigdl.chaos.hangCompileAt``   "k" or "k:seconds": the k-th XLA compile
                                wedges for ``seconds`` (default 5.0) —
                                the compile watchdog must detect it
                                within ``bigdl.compile.timeoutSec`` and
                                abort with a diagnosed
                                ``CompileTimeoutError``.
``bigdl.chaos.slowRequestAt``   "k" or "k:seconds": the k-th serving request
                                PROCESSED by the batcher stalls its handling
                                for ``seconds`` (default 5.0) — requests
                                queued behind it blow their deadlines, which
                                the dequeue-time shed must absorb (once per
                                plan).
``bigdl.chaos.poisonRequestAt`` "k" or "k:m": serving requests with admission
                                position k..m (0-based) read as poison — the
                                per-request quarantine must fail exactly
                                those with ``ServingDataError`` and keep
                                their batches alive (once per position).
``bigdl.chaos.hangDispatchAt``  "k" or "k:seconds": the k-th serving batch
                                dispatch wedges for ``seconds`` (default
                                5.0) — the hung-dispatch watchdog must
                                abort it, fail the in-flight requests with
                                diagnosis, and cool the engine down (once
                                per plan).
``bigdl.chaos.burstArrivals``   "k" or "k:n": the open-loop load generator
                                fires n extra back-to-back arrivals (default
                                8) at arrival position k (0-based) — a
                                thundering herd the admission control must
                                reject fast instead of collapsing tail
                                latency (once per position).
``bigdl.chaos.poisonPromptAt``  "k" or "k:m": LM serving prompts with
                                admission position k..m (0-based) read as
                                poison — the LM engine's per-request
                                quarantine must fail exactly those with
                                ``ServingDataError`` while the decode
                                batch keeps streaming (once per
                                position).
``bigdl.chaos.hangDecodeAt``    "k" or "k:seconds": the k-th LM decode
                                iteration wedges for ``seconds`` (default
                                5.0), sleeping in short slices — the
                                hung-decode watchdog must abort it, shed
                                the in-flight streams with diagnosis, and
                                cool the engine down (once per plan).
``bigdl.chaos.evictBlockAt``    k: at LM decode iteration k one active
                                sequence's KV blocks "evict" — the engine
                                must shed exactly that stream with a
                                retriable infra error, free its blocks,
                                and keep every other stream intact (once
                                per plan).
``bigdl.chaos.bitflipParamAt``  "k" or "k:leaf": at iteration k ONE
                                mid-mantissa bit of the first element of
                                float parameter leaf ``leaf`` (default 0)
                                flips —
                                finite-preserving silent data corruption
                                that ``all_finite`` cannot see; only the
                                integrity fingerprints (continuity or
                                cross-replica agreement) catch it.  Once
                                per plan, so the healed replay runs clean.
``bigdl.chaos.desyncReplicaAt`` "k" or "k:replica": inside the fused step
                                at iteration k, data-parallel replica
                                ``replica`` (default 1) perturbs its own
                                copy of the updated parameters — the
                                replica stays SELF-consistent (its own
                                continuity fingerprints match), so only
                                cross-replica agreement detects the drift.
                                Traced into the step, gated on the
                                iteration tick: fires exactly once since a
                                healed run resumes past iteration k.
``bigdl.chaos.corruptStateBeforeSaveAt``  k: the k-th checkpoint capture is
                                corrupted in host RAM AFTER the semantic
                                fingerprint was computed but BEFORE
                                serialization — the payload checksums are
                                taken over the already-corrupt bytes and
                                verify clean; only the recomputed
                                fingerprint at restore can refuse it.
``bigdl.chaos.oomStepAt``       k: the k-th tracked-step dispatch raises a
                                realistic RESOURCE_EXHAUSTED allocation
                                failure BEFORE executing (device state
                                untouched, exactly like a real XLA OOM
                                surfaced at dispatch) — the driver must
                                classify it as a RESOURCE fault and
                                answer with a microbatch re-plan, never
                                a same-plan retry.  Once per plan.
``bigdl.chaos.diskFullAt``      "k" or "k:substr", comma-separable
                                ("2:checkpoints,1:compile_cache"): the
                                k-th ``file_io.write_bytes`` whose path
                                contains ``substr`` (every write when
                                omitted) raises ENOSPC — disk-full
                                degradation prey for the checkpoint
                                manager, compile cache, and telemetry
                                exporters.  Once per entry per plan.
``bigdl.chaos.hostMemPressureAt``  k: the host-memory governor's k-th
                                poll reports zero free bytes regardless
                                of the accounted total — the registered
                                shrinkers (ring depth halving, paused
                                read-ahead) must fire and the batch
                                stream must stay bit-identical.  Once
                                per plan.
``bigdl.chaos.killReplicaAt``   "k" or "k:replica": at the fleet's k-th
                                SUBMITTED request, serving replica
                                ``replica`` (default 0) of the submitting
                                service has its batcher thread killed with
                                an async-raised ``BaseException`` — a hard
                                crash the engine's internal handler cannot
                                absorb.  The fleet supervisor must detect
                                the dead replica, sweep its stranded
                                in-flight requests into ``shed``, and
                                restart the slot.  Once per plan.
``bigdl.chaos.corruptCandidateAt``  k: the k-th rollout candidate PREPARED
                                gets one float of its weights nudged IN
                                PLACE after the rollout captured the
                                expected semantic fingerprint — the
                                pre-cutover fingerprint re-verification
                                must refuse promotion and roll back while
                                the incumbent keeps serving.  Once per
                                plan.
``bigdl.chaos.sigtermFleetAt``  k: at the fleet's k-th submitted request
                                the harness calls
                                ``elastic.request_preemption`` ONCE — a
                                fleet-wide SIGTERM.  Every replica
                                self-drains, in-flight rollouts abort with
                                rollback, and the fleet's accounting
                                identity must still balance exactly.
``bigdl.chaos.lockDelayAt``     "<lockname>:k[:seconds]": the k-th
                                acquisition of the named lock-witness
                                lock (``analysis.make_lock`` names)
                                stalls for ``seconds`` (default 0.05)
                                just after the acquisition-order check —
                                deterministically widening a racy window
                                so an ordering race that needs a lost
                                quantum can be reproduced on demand.
                                Once per position per plan.
==============================  =============================================

Counters are process-local and monotonically increasing from
:func:`install`.  ``install()``/``uninstall()`` arm and disarm the
harness; when disarmed (the default) every hook is a no-op behind a single
attribute check, so production paths pay nothing.
"""

from __future__ import annotations

import sys as _sys
import threading
from typing import Optional, Tuple


class ChaosError(IOError):
    """An injected storage/step fault.  Subclasses ``IOError`` so the
    production code paths cannot tell it from a real infrastructure
    failure — that is the point."""


def _incident_note(kind: str, **fields) -> None:
    """Flight-recorder note at each injection: the incident bundle's
    event ring must NAME the fault a run was subjected to, or the
    forensics read as a spontaneous failure.  Lazy lookup (never an
    import) — chaos sits below telemetry in the import DAG, and a
    disabled/absent recorder must cost nothing here."""
    mod = _sys.modules.get("bigdl_tpu.telemetry.incident")
    if mod is not None:
        mod.record(f"chaos/{kind}", **fields)


class _ChaosState:
    """One armed injection plan (counters + parsed config)."""

    def __init__(self):
        from bigdl_tpu.utils import config
        self.fail_write_at = config.get_int("bigdl.chaos.failWriteAt", 0)
        self.truncate_write_at = config.get_int(
            "bigdl.chaos.truncateWriteAt", 0)
        self.transient_writes = config.get_int(
            "bigdl.chaos.transientWrites", 0)
        self.fail_step_at = config.get_int("bigdl.chaos.failStepAt", 0)
        self.nan_loss_at = _parse_span(
            config.get_property("bigdl.chaos.nanLossAt"))
        self.preempt_at = config.get_int("bigdl.chaos.preemptAt", 0)
        self.stall_step_at, self.stall_seconds = _parse_stall(
            config.get_property("bigdl.chaos.stallStepAt"))
        self.topology_change_at = config.get_int(
            "bigdl.chaos.topologyChangeAt", 0)
        self.corrupt_record_at = _parse_span(
            config.get_property("bigdl.chaos.corruptRecordAt"))
        self.corrupt_record_every = config.get_int(
            "bigdl.chaos.corruptRecordEvery", 0)
        self.fail_decode_at = _parse_span(
            config.get_property("bigdl.chaos.failDecodeAt"))
        self.transient_reads = config.get_int(
            "bigdl.chaos.transientReads", 0)
        self.kill_stage, self.kill_stage_after = _parse_kill(
            config.get_property("bigdl.chaos.killStageThread"))
        (self.starve_stage_name, self.starve_stage_after,
         self.starve_stage_seconds) = _parse_starve(
            config.get_property("bigdl.chaos.starveStageAt"))
        self.corrupt_cache_at = config.get_int(
            "bigdl.chaos.corruptCompileCacheAt", 0)
        self.hang_compile_at, self.hang_compile_seconds = _parse_stall(
            config.get_property("bigdl.chaos.hangCompileAt"))
        self.slow_request_at, self.slow_request_seconds = _parse_stall(
            config.get_property("bigdl.chaos.slowRequestAt"))
        self.poison_request_at = _parse_span(
            config.get_property("bigdl.chaos.poisonRequestAt"))
        self.hang_dispatch_at, self.hang_dispatch_seconds = _parse_stall(
            config.get_property("bigdl.chaos.hangDispatchAt"))
        self.burst_arrivals_at, self.burst_arrivals_n = _parse_burst(
            config.get_property("bigdl.chaos.burstArrivals"))
        self.poison_prompt_at = _parse_span(
            config.get_property("bigdl.chaos.poisonPromptAt"))
        self.hang_decode_at, self.hang_decode_seconds = _parse_stall(
            config.get_property("bigdl.chaos.hangDecodeAt"))
        self.evict_block_at = config.get_int("bigdl.chaos.evictBlockAt", 0)
        self.bitflip_at, self.bitflip_leaf = _parse_indexed(
            config.get_property("bigdl.chaos.bitflipParamAt"), 0)
        self.desync_at, self.desync_replica = _parse_indexed(
            config.get_property("bigdl.chaos.desyncReplicaAt"), 1)
        self.corrupt_save_at = config.get_int(
            "bigdl.chaos.corruptStateBeforeSaveAt", 0)
        self.oom_step_at = config.get_int("bigdl.chaos.oomStepAt", 0)
        self.disk_full_plan = _parse_disk_full(
            config.get_property("bigdl.chaos.diskFullAt"))
        self.host_pressure_at = config.get_int(
            "bigdl.chaos.hostMemPressureAt", 0)
        self.kill_replica_at, self.kill_replica_index = _parse_indexed(
            config.get_property("bigdl.chaos.killReplicaAt"), 0)
        self.corrupt_candidate_at = config.get_int(
            "bigdl.chaos.corruptCandidateAt", 0)
        self.sigterm_fleet_at = config.get_int(
            "bigdl.chaos.sigtermFleetAt", 0)
        (self.lock_delay_name, self.lock_delay_at,
         self.lock_delay_seconds) = _parse_lock_delay(
            config.get_property("bigdl.chaos.lockDelayAt"))
        self.writes = 0
        self.steps_failed = 0
        self.steps_seen = 0
        self.transient_raised = 0
        self.transient_reads_raised = 0
        self.record_faults_fired: set = set()   # positions fired once
        self.decode_faults_fired: set = set()
        self.stage_kills = 0
        self.stage_starve_started: Optional[float] = None
        self.stage_starve_done = False
        self.stage_starve_throttles = 0
        self.preempts = 0
        self.stalls = 0
        self.topology_changes = 0
        self.cache_writes = 0
        self.compiles = 0
        self.compile_hangs = 0
        self.serving_requests = 0
        self.request_stalls = 0
        self.poison_fired: set = set()
        self.dispatches = 0
        self.dispatch_hangs = 0
        self.bursts_fired: set = set()
        self.prompt_poison_fired: set = set()
        self.decode_hangs = 0
        self.block_evictions = 0
        self.bitflip_due: Optional[int] = None  # leaf index, consume-once
        self.bitflips = 0
        self.state_corruptions = 0
        self.captures = 0
        self.step_dispatches = 0
        self.oom_fired = 0
        self.disk_full_fired = 0
        self.pressure_fired = 0
        self.replica_kills = 0
        self.candidates_prepared = 0
        self.candidate_corruptions = 0
        self.fleet_sigterms = 0
        self.lock_delays_fired: set = set()
        self.lock_delays = 0
        # raw by design: the injection-plan bookkeeping lock must not
        # feed the witness it injects into
        self._lock = threading.Lock()  # lint: allow(raw-lock-in-threaded-module)

    # ---- storage-layer hooks -------------------------------------------

    def on_write(self, path: str, data: bytes) -> bytes:
        """Called by ``file_io`` with every payload about to be written.
        Returns the (possibly corrupted) bytes to write, or raises."""
        with self._lock:
            # transient faults count ATTEMPTS, not completed writes: the
            # retrying caller sees n failures then a clean success
            if self.transient_raised < self.transient_writes:
                self.transient_raised += 1
                raise ChaosError(
                    f"chaos: transient write failure "
                    f"{self.transient_raised}/{self.transient_writes} "
                    f"on {path}")
            self.writes += 1
            k = self.writes
        if k == self.truncate_write_at:
            # silent torn write: rename will still commit it
            return data[:max(1, len(data) // 2)]
        if k == self.fail_write_at:
            raise _TornWrite(path, data[:max(1, len(data) // 2)])
        return data

    # ---- lock-witness hooks --------------------------------------------

    def lock_delay(self, name: str, n: int) -> float:
        """Seconds the ``n``-th acquisition of witness lock ``name``
        should stall (0.0 almost always).  Once per position per plan."""
        if not self.lock_delay_name or name != self.lock_delay_name:
            return 0.0
        if n != self.lock_delay_at:
            return 0.0
        with self._lock:
            if n in self.lock_delays_fired:
                return 0.0
            self.lock_delays_fired.add(n)
            self.lock_delays += 1
        return self.lock_delay_seconds

    # ---- driver-loop hooks ---------------------------------------------

    def on_step(self, neval: int) -> bool:
        """Called by the driver loop at the top of iteration ``neval``.
        Raises for a simulated preemption; returns True when the loss of
        this iteration should be reported non-finite."""
        with self._lock:
            self.steps_seen += 1
            seen = self.steps_seen
        if self.fail_step_at and neval == self.fail_step_at:
            with self._lock:
                if self.steps_failed == 0:   # fail once, not every retry
                    self.steps_failed += 1
                    raise ChaosError(
                        f"chaos: simulated step failure at iteration "
                        f"{neval}")
        if self.topology_change_at and neval == self.topology_change_at:
            with self._lock:
                if self.topology_changes == 0:   # once, not every retry
                    self.topology_changes += 1
                    raise ChaosError(
                        f"chaos: mesh lost at iteration {neval} — resume "
                        "on a different topology")
        if self.preempt_at and neval == self.preempt_at:
            with self._lock:
                fire = self.preempts == 0        # one SIGTERM, not a storm
                self.preempts += 1 if fire else 0
            if fire:
                from bigdl_tpu.utils import elastic
                elastic.request_preemption(
                    reason=f"chaos preemption at iteration {neval}")
        if self.stall_step_at and neval == self.stall_step_at:
            with self._lock:
                fire = self.stalls == 0          # one wedge per plan
                self.stalls += 1 if fire else 0
            if fire:
                # block the driver in Python-land: the watchdog's injected
                # HungStepError lands the moment this sleep returns —
                # exactly how a recovered-but-overdue step should die
                import time
                time.sleep(self.stall_seconds)
        if self.bitflip_at and neval == self.bitflip_at:
            with self._lock:
                if self.bitflips == 0:       # one flip per plan — a healed
                    self.bitflips = 1        # replay must run clean
                    self.bitflip_due = self.bitflip_leaf
        lo, hi = self.nan_loss_at
        return bool(lo) and lo <= seen <= hi

    # ---- integrity hooks -----------------------------------------------

    def take_bitflip(self) -> Optional[int]:
        """Consume the pending bit-flip marked by :meth:`on_step`:
        returns the float-leaf index to corrupt, or None.  The trainer's
        run_step applies the flip to live device state through the
        ``host_pull`` choke point — simulated in-memory SDC."""
        with self._lock:
            due, self.bitflip_due = self.bitflip_due, None
        return due

    def corrupt_state_before_save(self, obj):
        """Called by the checkpoint manager with each captured state
        AFTER its semantic fingerprint was computed; the
        ``corruptStateBeforeSaveAt``-th capture gets one float nudged in
        a deep copy (the original live state stays clean) — so every
        payload checksum is taken over already-corrupt bytes and
        verifies, and only the fingerprint recomputation at restore can
        refuse the snapshot.  Once per plan."""
        if not self.corrupt_save_at:
            return obj
        with self._lock:
            self.captures += 1
            fire = (self.captures == self.corrupt_save_at and
                    self.state_corruptions == 0)
            if fire:
                self.state_corruptions = 1
        if not fire:
            return obj
        # the copy is a pickle round trip, not a deepcopy: the live
        # graph's leaves may be immutable device arrays, while the
        # serialized form holds host numpy buffers — the same form the
        # snapshot stores and the restore-time fingerprint walks
        import pickle
        corrupt = pickle.loads(pickle.dumps(obj))
        flipped = _corrupt_first_float(corrupt)
        if not flipped:   # nothing float-like found: leave pristine
            return obj
        return corrupt

    # ---- compile-subsystem hooks ---------------------------------------

    def on_compile_cache_write(self, key: str, payload: bytes) -> bytes:
        """Called by the compile cache with every entry payload about to
        be stored; the ``corruptCompileCacheAt``-th entry gets ONE bit
        flipped AFTER its manifest checksum was computed — the worst
        case: a committed entry whose payload silently rotted, which
        only the checksum verification at load time can catch."""
        with self._lock:
            self.cache_writes += 1
            k = self.cache_writes
        if k == self.corrupt_cache_at and payload:
            flipped = bytearray(payload)
            flipped[len(flipped) // 2] ^= 0x40
            return bytes(flipped)
        return payload

    def on_compile(self, label: str) -> None:
        """Called immediately before the ``hangCompileAt``-th XLA
        compile: wedge the compiling thread for ``seconds`` (default
        5.0), sleeping in short slices so the compile watchdog's
        injected :class:`CompileTimeoutError` lands within one slice of
        the abort — the interruptible stand-in for a hung remote
        compilation.  One wedge per plan."""
        if not self.hang_compile_at:
            return
        with self._lock:
            self.compiles += 1
            fire = (self.compiles == self.hang_compile_at and
                    self.compile_hangs == 0)
            if fire:
                self.compile_hangs = 1
        if fire:
            import time
            end = time.monotonic() + self.hang_compile_seconds
            while time.monotonic() < end:
                time.sleep(0.02)

    # ---- serving-path hooks --------------------------------------------

    def on_serving_request(self, index: int) -> None:
        """Called by the serving batcher as it begins handling each
        dequeued request (``index`` is its admission position, for
        logs).  The ``slowRequestAt``-th request HANDLED stalls the
        batcher for ``seconds`` (default 5.0) — everything queued behind
        it ages toward its deadline, exercising the dequeue-time shed.
        One stall per plan."""
        if not self.slow_request_at:
            return
        with self._lock:
            self.serving_requests += 1
            fire = (self.serving_requests == self.slow_request_at and
                    self.request_stalls == 0)
            if fire:
                self.request_stalls = 1
        if fire:
            import time
            time.sleep(self.slow_request_seconds)

    def poison_request(self, index: int) -> bool:
        """True when the request at admission position ``index``
        (0-based) should read as poison — the serving quarantine must
        fail exactly that request with ``ServingDataError`` and keep the
        batch alive.  Once per position per plan (a client retrying a
        rejected request is not re-poisoned)."""
        lo, hi = self.poison_request_at
        if bool(hi >= 0) and lo <= index <= hi:
            with self._lock:
                fire = index not in self.poison_fired
                self.poison_fired.add(index)
            if fire:
                _incident_note("poison_request", index=index)
            return fire
        return False

    def on_dispatch(self, label: str = "") -> None:
        """Called immediately before each serving batch dispatch: the
        ``hangDispatchAt``-th dispatch wedges for ``seconds`` (default
        5.0), sleeping in short slices so the hung-dispatch watchdog's
        injected ``HungDispatchError`` lands within one slice — the
        interruptible stand-in for a wedged device step.  One wedge per
        plan."""
        if not self.hang_dispatch_at:
            return
        with self._lock:
            self.dispatches += 1
            fire = (self.dispatches == self.hang_dispatch_at and
                    self.dispatch_hangs == 0)
            if fire:
                self.dispatch_hangs = 1
        if fire:
            import time
            _incident_note("hang_dispatch", dispatch=self.dispatches,
                           seconds=self.hang_dispatch_seconds)
            end = time.monotonic() + self.hang_dispatch_seconds
            while time.monotonic() < end:
                time.sleep(0.02)

    def poison_prompt(self, index: int) -> bool:
        """True when the LM prompt at admission position ``index``
        (0-based) should read as poison — the LM engine must quarantine
        exactly that stream with ``ServingDataError`` while the decode
        batch keeps streaming.  Once per position per plan."""
        lo, hi = self.poison_prompt_at
        if bool(hi >= 0) and lo <= index <= hi:
            with self._lock:
                fire = index not in self.prompt_poison_fired
                self.prompt_poison_fired.add(index)
            if fire:
                _incident_note("poison_prompt", index=index)
            return fire
        return False

    def on_decode_step(self, step: int) -> None:
        """Called by the LM scheduler before each decode iteration
        (``step`` is 1-based): the ``hangDecodeAt``-th iteration wedges
        for ``seconds`` (default 5.0), sleeping in short slices so the
        hung-decode watchdog's injected ``HungDispatchError`` lands
        within one slice — the interruptible stand-in for a wedged
        decode dispatch.  One wedge per plan."""
        if not self.hang_decode_at:
            return
        with self._lock:
            fire = (step >= self.hang_decode_at and
                    self.decode_hangs == 0)
            if fire:
                self.decode_hangs = 1
        if fire:
            import time
            _incident_note("hang_decode", step=step,
                           seconds=self.hang_decode_seconds)
            end = time.monotonic() + self.hang_decode_seconds
            while time.monotonic() < end:
                time.sleep(0.02)

    def evict_block(self, step: int) -> bool:
        """True when one active sequence's KV blocks should "evict" at
        LM decode iteration ``step`` (1-based) — the engine sheds that
        stream retriably, frees the blocks, and keeps every other stream
        intact.  Once per plan."""
        if not self.evict_block_at:
            return False
        with self._lock:
            fire = (step >= self.evict_block_at and
                    self.block_evictions == 0)
            if fire:
                self.block_evictions = 1
        if fire:
            _incident_note("evict_block", step=step)
        return fire

    def burst_arrivals(self, index: int) -> int:
        """Extra back-to-back arrivals the open-loop load generator
        should fire at arrival position ``index`` (0-based): ``n`` at
        the configured position (default 8), else 0.  Once per position
        per plan."""
        at, n = self.burst_arrivals_at, self.burst_arrivals_n
        if at < 0 or index != at:
            return 0
        with self._lock:
            fire = index not in self.bursts_fired
            self.bursts_fired.add(index)
        return n if fire else 0

    # ---- ingest-stage hooks --------------------------------------------

    def on_record_read(self, index: int) -> None:
        """Called by the ingest reader stage with each record's 0-based
        stream position BEFORE handing it downstream.  Raises a transient
        :class:`ChaosError` for the first ``transientReads`` reads (the
        retrying reader sees n blips then success) or a
        :class:`CorruptRecord` for records in the ``corruptRecordAt``
        span / on the ``corruptRecordEvery`` grid."""
        with self._lock:
            if self.transient_reads_raised < self.transient_reads:
                self.transient_reads_raised += 1
                raise ChaosError(
                    f"chaos: transient read failure "
                    f"{self.transient_reads_raised}/{self.transient_reads} "
                    f"on record {index}")
        lo, hi = self.corrupt_record_at
        if bool(hi >= 0) and lo <= index <= hi:
            with self._lock:
                fire = index not in self.record_faults_fired
                self.record_faults_fired.add(index)
            if fire:     # each position dirties ONCE per plan — a fresh
                raise CorruptRecord(index)   # epoch pass is not re-dirtied
        if (self.corrupt_record_every and
                index and index % self.corrupt_record_every == 0):
            raise CorruptRecord(index)

    def on_decode(self, index: int) -> None:
        """Called with a record's stream position before decode; raises
        an undecodable-image error inside the ``failDecodeAt`` span
        (once per position, like ``corruptRecordAt``)."""
        lo, hi = self.fail_decode_at
        if bool(hi >= 0) and lo <= index <= hi:
            with self._lock:
                fire = index not in self.decode_faults_fired
                self.decode_faults_fired.add(index)
            if fire:
                raise UndecodableImage(index)

    def kill_stage_thread(self, stage: str, items: int) -> bool:
        """True exactly once, when the named ingest stage has processed
        its ``killStageThread`` item count — the stage then returns
        silently (no error, no done flag), simulating a crashed thread
        the supervisor must notice."""
        if self.kill_stage != stage or items < self.kill_stage_after:
            return False
        with self._lock:
            if self.stage_kills:
                return False        # one death per plan, not per restart
            self.stage_kills = 1
        return True

    def starve_stage(self, stage: str, items: int) -> None:
        """Called by each ingest stage with its running item count: once
        the named stage reaches its ``starveStageAt`` item, every call
        inside the window pauses ~50 ms — the stage stays alive but its
        output rate collapses, so the DOWNSTREAM stage starves (the
        autoscaler's scale-up signal, forced on demand).  The window
        closes ``seconds`` after the first throttled item; once per
        plan."""
        import time as _time
        if (self.starve_stage_name != stage or self.stage_starve_done or
                items < self.starve_stage_after):
            return
        with self._lock:
            if self.stage_starve_started is None:
                self.stage_starve_started = _time.monotonic()
            remaining = (self.stage_starve_started +
                         self.starve_stage_seconds - _time.monotonic())
            if remaining <= 0:
                self.stage_starve_done = True
                return
            self.stage_starve_throttles += 1
        _time.sleep(min(0.05, remaining))

    # ---- resource-exhaustion hooks -------------------------------------

    def take_oom_dispatch(self, label: str) -> None:
        """Called by ``CachedStep`` immediately before each executable
        dispatch: the ``oomStepAt``-th dispatch raises a realistic
        RESOURCE_EXHAUSTED allocation failure — the message replicates
        what jaxlib's XlaRuntimeError carries, so the production
        classifier cannot tell it from a real HBM OOM.  Raised BEFORE
        execution: device state is untouched, exactly the real failure
        mode.  Once per plan (the re-planned step runs clean)."""
        if not self.oom_step_at:
            return
        with self._lock:
            self.step_dispatches += 1
            fire = (self.step_dispatches == self.oom_step_at and
                    self.oom_fired == 0)
            if fire:
                self.oom_fired = 1
        if fire:
            _incident_note("oom_step", label=label,
                           dispatch=self.step_dispatches)
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: Out of memory while trying to "
                f"allocate 17179869184 bytes (chaos: injected device "
                f"OOM on step {label!r} dispatch "
                f"{self.step_dispatches})")

    def take_disk_full(self, path: str) -> None:
        """Called by ``file_io.write_bytes`` with each destination path
        about to be written: each armed ``diskFullAt`` entry counts the
        writes whose path contains its substring and raises a plain
        ``OSError(ENOSPC)`` at its k-th match — the SAME raw error a
        full disk produces, so the classification into
        ``StorageExhaustedError`` is exercised, not bypassed.  Once per
        entry per plan."""
        if not self.disk_full_plan:
            return
        import errno
        fire = False
        with self._lock:
            for entry in self.disk_full_plan:
                if entry["fired"] or (entry["substr"] and
                                      entry["substr"] not in path):
                    continue
                entry["count"] += 1
                if entry["count"] >= entry["k"]:
                    entry["fired"] = True
                    self.disk_full_fired += 1
                    fire = True
                    break
        if fire:
            _incident_note("disk_full", path=path)
            raise OSError(errno.ENOSPC,
                          f"No space left on device (chaos: injected "
                          f"disk-full writing {path})")

    def host_mem_pressure(self, poll_index: int) -> bool:
        """True when the governor's ``poll_index``-th poll should report
        zero free bytes (injected host-memory pressure).  Once per
        plan."""
        if not self.host_pressure_at:
            return False
        with self._lock:
            fire = (poll_index >= self.host_pressure_at and
                    self.pressure_fired == 0)
            if fire:
                self.pressure_fired = 1
        if fire:
            _incident_note("host_mem_pressure", poll=poll_index)
        return fire

    # ---- fleet-control-plane hooks -------------------------------------

    def kill_replica(self, submits: int) -> Optional[int]:
        """Replica index to hard-kill NOW, or None: fires once when the
        fleet's submitted-request count reaches ``killReplicaAt``.  The
        fleet async-raises a ``BaseException`` into the victim batcher
        thread — the crash its supervisor must detect and restart."""
        if not self.kill_replica_at:
            return None
        with self._lock:
            fire = (submits >= self.kill_replica_at and
                    self.replica_kills == 0)
            if fire:
                self.replica_kills = 1
        if fire:
            _incident_note("kill_replica", submits=submits,
                           replica=self.kill_replica_index)
        return self.kill_replica_index if fire else None

    def corrupt_candidate(self, model) -> bool:
        """Called by the rollout path with each candidate model AFTER
        its expected semantic fingerprint was captured: the
        ``corruptCandidateAt``-th candidate prepared gets one float
        nudged IN PLACE (the candidate is what would serve, so the
        corruption must be visible to the pre-cutover re-verification —
        unlike ``corrupt_state_before_save``, no protective copy).
        True when the weights were changed.  Once per plan."""
        if not self.corrupt_candidate_at:
            return False
        with self._lock:
            self.candidates_prepared += 1
            fire = (self.candidates_prepared == self.corrupt_candidate_at
                    and self.candidate_corruptions == 0)
            if fire:
                self.candidate_corruptions = 1
        if not fire:
            return False
        _incident_note("corrupt_candidate",
                       candidate=self.candidates_prepared)
        return _corrupt_first_float(model)

    def sigterm_fleet(self, submits: int) -> bool:
        """Fires ``elastic.request_preemption`` once when the fleet's
        submitted-request count reaches ``sigtermFleetAt`` — the same
        flag a real SIGTERM handler sets, so every replica self-drains
        and in-flight rollouts abort exactly as under a scheduler
        preemption."""
        if not self.sigterm_fleet_at:
            return False
        with self._lock:
            fire = (submits >= self.sigterm_fleet_at and
                    self.fleet_sigterms == 0)
            if fire:
                self.fleet_sigterms = 1
        if fire:
            from bigdl_tpu.utils import elastic
            _incident_note("sigterm_fleet", submits=submits)
            elastic.request_preemption("chaos: injected fleet-wide SIGTERM")
        return fire


class CorruptRecord(ChaosError):
    """An injected corrupt ingest record — a DATA fault: the taxonomy
    must quarantine it, never retry it (re-reading corrupt bytes yields
    corrupt bytes)."""

    #: data faults are not blips — the reader's transient retry must
    #: not absorb them into a retry loop
    fatal = True

    def __init__(self, index: int):
        super().__init__(f"chaos: corrupt record at stream position "
                         f"{index}")
        self.index = index


class UndecodableImage(ChaosError):
    """An injected decode failure — a record whose bytes parse as a
    frame but not as an image (the second data-fault class)."""

    fatal = True

    def __init__(self, index: int):
        super().__init__(
            f"chaos: undecodable image at stream position {index}")
        self.index = index


class _TornWrite(ChaosError):
    """fail-the-k-th-write: carries the partial prefix so the storage
    layer can leave the torn temp behind (a hard-killed writer does not
    clean up after itself)."""

    #: a died writer is not a blip — the storage retry must not absorb it
    fatal = True

    def __init__(self, path: str, partial: bytes):
        super().__init__(f"chaos: writer died mid-write on {path}")
        self.partial = partial


def _parse_span(value) -> Tuple[int, int]:
    """``"k"`` -> (k, k); ``"k:m"`` -> (k, m); falsy -> (0, -1)."""
    if not value:
        return (0, -1)
    s = str(value)
    if ":" in s:
        lo, hi = s.split(":", 1)
        return (int(lo), int(hi))
    k = int(s)
    return (k, k)


def _parse_stall(value) -> Tuple[int, float]:
    """``"k"`` -> (k, 5.0); ``"k:seconds"`` -> (k, seconds); falsy ->
    (0, 0.0)."""
    if not value:
        return (0, 0.0)
    s = str(value)
    if ":" in s:
        k, secs = s.split(":", 1)
        return (int(k), float(secs))
    return (int(s), 5.0)


def _parse_burst(value) -> Tuple[int, int]:
    """``"k"`` -> (k, 8); ``"k:n"`` -> (k, n); falsy -> (-1, 0) — the
    position sentinel is -1 so arrival position 0 stays armable."""
    if value is None or value == "":
        return (-1, 0)
    s = str(value)
    if ":" in s:
        k, n = s.split(":", 1)
        return (int(k), int(n))
    return (int(s), 8)


def _parse_indexed(value, default_index: int) -> Tuple[int, int]:
    """``"k"`` -> (k, default); ``"k:i"`` -> (k, i); falsy -> (0, 0)."""
    if not value:
        return (0, 0)
    s = str(value)
    if ":" in s:
        k, i = s.split(":", 1)
        return (int(k), int(i))
    return (int(s), default_index)


def _corrupt_first_float(obj, _seen=None) -> bool:
    """Nudge the first reachable float array in an object graph (+1.0 —
    big enough that no fingerprint rounding hides it): mutable numpy
    buffers are nudged in place; other float arrays (immutable device
    arrays) are REPLACED inside their parent container with a nudged
    numpy copy.  True when something was changed."""
    import numpy as np

    def is_float_arr(x):
        dt = getattr(x, "dtype", None)
        if dt is None:
            return False
        kind_f = getattr(dt, "kind", "") == "f" or str(dt) in (
            "bfloat16", "float16")
        return kind_f and getattr(x, "size", 0)

    def nudge(x):
        if isinstance(x, np.ndarray):
            x.reshape(-1)[0] += 1.0
            return x
        arr = np.array(x, copy=True)
        arr.reshape(-1)[0] += 1.0
        return arr

    if _seen is None:
        _seen = set()
        if isinstance(obj, np.ndarray) and is_float_arr(obj):
            nudge(obj)
            return True
    if obj is None or isinstance(obj, (str, bytes, bool, int, float)):
        return False          # bare floats are immutable — skip, recurse on
    if id(obj) in _seen:      # containers until a float array turns up
        return False
    _seen.add(id(obj))
    if isinstance(obj, tuple):
        # immutable container: only in-place numpy members are reachable
        for v in obj:
            if isinstance(v, np.ndarray) and is_float_arr(v):
                nudge(v)
                return True
            if _corrupt_first_float(v, _seen):
                return True
        return False
    if isinstance(obj, dict):
        items, setter = list(obj.items()), obj.__setitem__
    elif isinstance(obj, list):
        items, setter = list(enumerate(obj)), obj.__setitem__
    elif isinstance(getattr(obj, "__dict__", None), dict):
        d = obj.__dict__
        items, setter = list(d.items()), d.__setitem__
    else:
        return False
    for k, v in items:
        if is_float_arr(v):
            new = nudge(v)
            if new is not v:
                setter(k, new)
            return True
        if _corrupt_first_float(v, _seen):
            return True
    return False


def _parse_disk_full(value):
    """``"k"`` / ``"k:substr"``, comma-separable — one armed entry per
    element, each with its own match counter and once-per-plan latch.
    Falsy -> []."""
    if not value:
        return []
    entries = []
    for part in str(value).split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            k, substr = part.split(":", 1)
            entries.append({"k": int(k), "substr": substr.strip(),
                            "count": 0, "fired": False})
        else:
            entries.append({"k": int(part), "substr": "",
                            "count": 0, "fired": False})
    return entries


def _parse_starve(value) -> Tuple[Optional[str], int, float]:
    """``"stage:k"`` -> (stage, k, 1.0); ``"stage:k:seconds"`` ->
    (stage, k, seconds); falsy -> (None, 0, 0.0)."""
    if not value:
        return (None, 0, 0.0)
    parts = str(value).split(":")
    stage = parts[0].strip()
    k = int(parts[1]) if len(parts) > 1 else 1
    secs = float(parts[2]) if len(parts) > 2 else 1.0
    return (stage, k, secs)


def _parse_lock_delay(value) -> Tuple[Optional[str], int, float]:
    """``"lockname:k"`` -> (lockname, k, 0.05); ``"lockname:k:seconds"``
    -> (lockname, k, seconds); falsy -> (None, 0, 0.0)."""
    if not value:
        return (None, 0, 0.0)
    parts = str(value).split(":")
    name = parts[0].strip()
    k = int(parts[1]) if len(parts) > 1 else 1
    secs = float(parts[2]) if len(parts) > 2 else 0.05
    return (name, k, secs)


def _parse_kill(value) -> Tuple[Optional[str], int]:
    """``"stage"`` -> (stage, 1); ``"stage:k"`` -> (stage, k); falsy ->
    (None, 0)."""
    if not value:
        return (None, 0)
    s = str(value)
    if ":" in s:
        stage, k = s.split(":", 1)
        return (stage.strip(), int(k))
    return (s.strip(), 1)


_state: Optional[_ChaosState] = None


def install() -> None:
    """Arm the harness from the current ``bigdl.chaos.*`` configuration.
    Re-installing resets all counters (each test/rehearsal starts a fresh
    injection plan)."""
    global _state
    _state = _ChaosState()
    # push the lockDelayAt target into the witness: its armed acquire
    # path pays one attribute compare instead of probing chaos per
    # acquisition
    from bigdl_tpu.analysis import lockwitness
    lockwitness.set_chaos_delay_target(_state.lock_delay_name or None)


def uninstall() -> None:
    global _state
    _state = None
    from bigdl_tpu.analysis import lockwitness
    lockwitness.set_chaos_delay_target(None)


def active() -> bool:
    return _state is not None


def on_write(path: str, data: bytes) -> bytes:
    """file_io payload-write hook (identity when disarmed)."""
    if _state is None:
        return data
    return _state.on_write(path, data)


def on_step(neval: int) -> bool:
    """Driver-loop hook; True means "report this iteration's loss as
    non-finite" (divergence-guard exercise)."""
    if _state is None:
        return False
    return _state.on_step(neval)


def on_compile_cache_write(key: str, payload: bytes) -> bytes:
    """Compile-cache entry-write hook (identity when disarmed): the
    ``corruptCompileCacheAt``-th entry is bit-flipped post-checksum."""
    if _state is None:
        return payload
    return _state.on_compile_cache_write(key, payload)


def on_compile(label: str) -> None:
    """Compile hook (no-op when disarmed): the ``hangCompileAt``-th
    compile wedges for the configured seconds."""
    if _state is not None:
        _state.on_compile(label)


def lock_delay_target() -> Optional[str]:
    """Name of the witness lock an armed ``lockDelayAt`` plan targets,
    or None — the lock witness's fast-path probe, so the un-chaosed
    acquire path pays one call + compare instead of per-name counting."""
    return _state.lock_delay_name if _state is not None else None


def lock_delay(name: str, n: int) -> float:
    """Lock-witness acquire hook: seconds the ``n``-th acquisition of
    the named witness lock should stall (0.0 when disarmed; once per
    position per plan)."""
    if _state is None:
        return 0.0
    return _state.lock_delay(name, n)


def on_serving_request(index: int) -> None:
    """Serving batcher per-request hook (no-op when disarmed): the
    ``slowRequestAt``-th handled request stalls the batcher."""
    if _state is not None:
        _state.on_serving_request(index)


def poison_request(index: int) -> bool:
    """Serving per-request poison test (False when disarmed): True means
    "this admission position reads as poison NOW" (once per position)."""
    if _state is None:
        return False
    return _state.poison_request(index)


def on_dispatch(label: str = "") -> None:
    """Serving batch-dispatch hook (no-op when disarmed): the
    ``hangDispatchAt``-th dispatch wedges interruptibly."""
    if _state is not None:
        _state.on_dispatch(label)


def poison_prompt(index: int) -> bool:
    """LM-serving per-prompt poison test (False when disarmed): True
    means "this admission position's prompt reads as poison NOW" (once
    per position)."""
    if _state is None:
        return False
    return _state.poison_prompt(index)


def on_decode_step(step: int) -> None:
    """LM decode-iteration hook (no-op when disarmed): the
    ``hangDecodeAt``-th decode iteration wedges interruptibly."""
    if _state is not None:
        _state.on_decode_step(step)


def evict_block(step: int) -> bool:
    """LM decode-iteration eviction hook (False when disarmed): True
    means "one active sequence's KV blocks evict NOW" (once per plan)."""
    if _state is None:
        return False
    return _state.evict_block(step)


def burst_arrivals(index: int) -> int:
    """Load-generator arrival hook: extra back-to-back arrivals to fire
    at this position (0 when disarmed; once per position)."""
    if _state is None:
        return 0
    return _state.burst_arrivals(index)


def on_record_read(index: int) -> None:
    """Ingest reader-stage hook (no-op when disarmed): transient read
    blips and corrupt-record injection by stream position."""
    if _state is not None:
        _state.on_record_read(index)


def on_decode(index: int) -> None:
    """Ingest decode-stage hook (no-op when disarmed)."""
    if _state is not None:
        _state.on_decode(index)


def kill_stage_thread(stage: str, items: int) -> bool:
    """Ingest stage-death hook: True means "die silently NOW" (once per
    plan).  Disarmed: always False."""
    if _state is None:
        return False
    return _state.kill_stage_thread(stage, items)


def starve_stage(stage: str, items: int) -> None:
    """Ingest stage-throttle hook (no-op when disarmed): from the armed
    stage's ``starveStageAt``-th item each call pauses ~50 ms for the
    window, collapsing its output rate so its downstream starves."""
    if _state is not None:
        _state.starve_stage(stage, items)


def take_bitflip() -> Optional[int]:
    """Integrity hook (None when disarmed): the float-leaf index whose
    first element should get one mantissa bit flipped NOW — marked by
    ``on_step`` at the ``bitflipParamAt`` iteration, consumed once."""
    if _state is None:
        return None
    return _state.take_bitflip()


def desync_replica() -> Tuple[int, int]:
    """Integrity hook, read at step-BUILD time: ``(iteration, replica)``
    for the traced in-step desync injection, or ``(0, 0)`` when
    disarmed.  The step perturbs that replica's updated parameters when
    its iteration tick matches — once per run, since a healed replay
    resumes past the iteration."""
    if _state is None:
        return (0, 0)
    return (_state.desync_at, _state.desync_replica)


def corrupt_state_before_save(obj):
    """Checkpoint-capture hook (identity when disarmed): returns the
    state to serialize — the ``corruptStateBeforeSaveAt``-th capture
    comes back as a corrupted deep copy whose checksums will verify."""
    if _state is None:
        return obj
    return _state.corrupt_state_before_save(obj)


def take_oom_dispatch(label: str) -> None:
    """Tracked-step dispatch hook (no-op when disarmed): the
    ``oomStepAt``-th dispatch raises a realistic RESOURCE_EXHAUSTED
    before execution (once per plan)."""
    if _state is not None:
        _state.take_oom_dispatch(label)


def take_disk_full(path: str) -> None:
    """Payload-write hook (no-op when disarmed): armed ``diskFullAt``
    entries raise a raw ``OSError(ENOSPC)`` at their k-th matching
    write (once per entry)."""
    if _state is not None:
        _state.take_disk_full(path)


def host_mem_pressure(poll_index: int) -> bool:
    """Host-memory-governor poll hook (False when disarmed): True means
    "report zero free bytes NOW" (once per plan)."""
    if _state is None:
        return False
    return _state.host_mem_pressure(poll_index)


def kill_replica(submits: int) -> Optional[int]:
    """Fleet submit hook (None when disarmed): the replica index whose
    batcher thread should be hard-killed NOW (once per plan)."""
    if _state is None:
        return None
    return _state.kill_replica(submits)


def corrupt_candidate(model) -> bool:
    """Rollout candidate-prepared hook (False when disarmed): the
    ``corruptCandidateAt``-th candidate gets one weight float nudged in
    place, post-fingerprint — True when the model was changed."""
    if _state is None:
        return False
    return _state.corrupt_candidate(model)


def sigterm_fleet(submits: int) -> bool:
    """Fleet submit hook (False when disarmed): requests fleet-wide
    preemption at the ``sigtermFleetAt``-th submitted request (once per
    plan)."""
    if _state is None:
        return False
    return _state.sigterm_fleet(submits)


def write_count() -> int:
    """Payload writes observed since install (diagnostics for tests)."""
    return _state.writes if _state is not None else 0
