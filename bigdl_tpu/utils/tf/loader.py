"""TensorFlow GraphDef importer.

Reference equivalent: ``utils/tf/TensorflowLoader.scala:38,85,126,210-230`` —
parse a GraphDef protobuf, build a directed graph of NodeDefs, greedily
pattern-match registered op subgraphs (Conv2D+BiasAdd, MatMul+BiasAdd, …)
and emit a Graph model with the pretrained weights copied in.

TPU-native notes: TF's NHWC activations and HWIO conv kernels are ALSO this
framework's native layouts (``ops/convolution.py``), so weights import
without transposition; ``format="NHWC"`` layers run the imported graph in
its original layout — no layout shims.

The protobuf parsing itself is delegated to the installed ``tensorflow``
package (proto definitions only — no TF session or runtime executes).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.graph import Graph, ModuleNode


def _tf():
    try:
        import tensorflow as tf
        return tf
    except ImportError as e:  # pragma: no cover - tf is in the image
        raise ImportError(
            "TensorFlow GraphDef import needs the tensorflow package for "
            "protobuf parsing") from e


def _const_value(node) -> np.ndarray:
    from tensorflow.python.framework import tensor_util
    return tensor_util.MakeNdarray(node.attr["value"].tensor)


def _strides_hw(node) -> tuple:
    s = list(node.attr["strides"].list.i)
    if node.attr["data_format"].s in (b"NCHW",):
        return int(s[2]), int(s[3])
    return int(s[1]), int(s[2])


def _ksize_hw(node) -> tuple:
    k = list(node.attr["ksize"].list.i)
    if node.attr["data_format"].s in (b"NCHW",):
        return int(k[2]), int(k[3])
    return int(k[1]), int(k[2])


def _data_format(node) -> str:
    return "NCHW" if node.attr["data_format"].s == b"NCHW" else "NHWC"


class _ConstPad(nn.Module):
    """Zero-pad by a static (ndim, 2) paddings table (TF Pad op)."""

    def __init__(self, pads, name=None):
        super().__init__(name)
        self.pads = tuple((int(a), int(b)) for a, b in pads)

    def apply(self, params, input, state, training=False, rng=None):
        return jnp.pad(input, self.pads), state


class _ReduceMean(nn.Module):
    """Mean over static axes (TF Mean op / global average pooling)."""

    def __init__(self, axes, keep_dims, name=None):
        super().__init__(name)
        self.axes = axes
        self.keep_dims = keep_dims

    def apply(self, params, input, state, training=False, rng=None):
        return jnp.mean(input, axis=self.axes,
                        keepdims=self.keep_dims), state


class _Permute(nn.Module):
    """Static-axis transpose (TF Transpose with Const perm)."""

    def __init__(self, perm, name=None):
        super().__init__(name)
        self.perm = tuple(int(p) for p in perm)

    def apply(self, params, input, state, training=False, rng=None):
        return jnp.transpose(input, self.perm), state


class _LRNLastAxis(nn.Module):
    """TF ``tf.nn.lrn`` semantics: window of ``2*depth_radius+1`` over the
    LAST axis (TF LRN is NHWC-only), denom = (bias + alpha*sum(sq))^beta —
    note TF's alpha is NOT divided by the window size (caffe's is)."""

    def __init__(self, depth_radius, bias, alpha, beta, name=None):
        super().__init__(name)
        self.depth_radius = int(depth_radius)
        self.bias = float(bias)
        self.alpha = float(alpha)
        self.beta = float(beta)

    def apply(self, params, input, state, training=False, rng=None):
        sq = input * input
        size = 2 * self.depth_radius + 1
        pad = [(0, 0)] * (input.ndim - 1) + [(self.depth_radius,
                                              self.depth_radius)]
        padded = jnp.pad(sq, pad)
        c = input.shape[-1]
        window = padded[..., 0:c]
        for i in range(1, size):
            window = window + padded[..., i:i + c]
        return input / (self.bias + self.alpha * window) ** self.beta, state


class _StridedSliceStatic(nn.Module):
    """TF StridedSlice with Const begin/end/strides and begin/end/shrink
    masks (no ellipsis/new_axis); all bounds static."""

    def __init__(self, begin, end, strides, begin_mask, end_mask,
                 shrink_mask, name=None):
        super().__init__(name)
        self.begin = [int(b) for b in begin]
        self.end = [int(e) for e in end]
        self.strides = [int(s) for s in strides]
        self.begin_mask = int(begin_mask)
        self.end_mask = int(end_mask)
        self.shrink_mask = int(shrink_mask)

    def apply(self, params, input, state, training=False, rng=None):
        idx = []
        for d in range(input.ndim):
            if d >= len(self.begin):
                idx.append(slice(None))
                continue
            if self.shrink_mask & (1 << d):
                idx.append(self.begin[d])
                continue
            b = None if self.begin_mask & (1 << d) else self.begin[d]
            e = None if self.end_mask & (1 << d) else self.end[d]
            idx.append(slice(b, e, self.strides[d]))
        return input[tuple(idx)], state


class TensorflowLoader:
    """Pattern-matching GraphDef → Graph converter."""

    def __init__(self, graph_def, inputs: List[str], outputs: List[str]):
        self.graph_def = graph_def
        self.inputs = [i.split(":")[0] for i in inputs]
        self.outputs = [o.split(":")[0] for o in outputs]
        self.nodes = {n.name: n for n in graph_def.node}
        self._consumers: Dict[str, int] = {}
        for n in graph_def.node:
            for i in n.input:
                self._consumers[i.split(":")[0].lstrip("^")] = \
                    self._consumers.get(i.split(":")[0].lstrip("^"), 0) + 1
        self._converted: Dict[str, ModuleNode] = {}
        self._input_nodes: List[ModuleNode] = []

    # -- public ----------------------------------------------------------

    @staticmethod
    def load(path_or_graphdef, inputs: List[str],
             outputs: List[str]) -> Graph:
        """(reference ``TensorflowLoader.load:85``)."""
        if isinstance(path_or_graphdef, str):
            tf = _tf()
            gd = tf.compat.v1.GraphDef()
            with open(path_or_graphdef, "rb") as f:
                gd.ParseFromString(f.read())
        else:
            gd = path_or_graphdef
        loader = TensorflowLoader(gd, inputs, outputs)
        return loader.build()

    def build(self) -> Graph:
        out_nodes = [self._convert(name) for name in self.outputs]
        if not self._input_nodes:
            raise ValueError("no graph inputs found among " +
                             ", ".join(self.inputs))
        g = Graph(self._input_nodes, out_nodes)
        # imported GraphDefs are inference graphs (is_training baked in):
        # eval mode keeps frozen BatchNorm statistics frozen and Dropout off
        g.evaluate()
        return g

    # -- conversion ------------------------------------------------------

    def _in(self, node, i: int):
        return self.nodes[node.input[i].split(":")[0].lstrip("^")]

    def _resolve_const(self, node):
        """Follow Identity chains to the underlying Const (frozen graphs
        wrap variable reads as Const -> Identity -> consumer); None when the
        chain ends elsewhere."""
        seen = 0
        while node.op == "Identity" and node.input and seen < 16:
            node = self._in(node, 0)
            seen += 1
        return node if node.op == "Const" else None

    def _convert(self, ref: str) -> ModuleNode:
        name, _, out_idx = ref.lstrip("^").partition(":")
        idx = int(out_idx) if out_idx else 0
        node = self.nodes[name]
        multi = node.op in ("Split", "Unpack")
        key = f"{name}:{idx}" if (idx or multi) else name
        if key in self._converted:
            return self._converted[key]
        mn = self._emit_indexed(node, idx) if multi else self._emit(node)
        self._converted[key] = mn
        return mn

    def _emit_indexed(self, node, idx: int) -> ModuleNode:
        """Multi-output ops: each ``name:idx`` reference becomes its own
        selector node over the shared upstream input."""
        if node.op == "Split":
            # Split(split_dim Const, value), attr num_split: output idx is
            # the idx-th of num_split equal slices along the axis
            dim_node = self._resolve_const(self._in(node, 0))
            if dim_node is None:
                raise ValueError(f"Split {node.name}: dynamic split_dim "
                                 "unsupported")
            axis = int(_const_value(dim_node))
            n = int(node.attr["num_split"].i)
            m = nn.SplitAndSelect(axis + 1 if axis >= 0 else axis,
                                  idx + 1, n, name=f"{node.name}_{idx}")
            return ModuleNode(m).inputs(self._convert(node.input[1]))
        # Unpack(value), attrs num/axis: removes the axis — SplitTable
        # (shared base node) + SelectTable per output index
        axis = int(node.attr["axis"].i)
        base_key = f"{node.name}:__table"
        base = self._converted.get(base_key)
        if base is None:
            st = nn.SplitTable(axis + 1 if axis >= 0 else axis,
                               name=node.name)
            base = ModuleNode(st).inputs(self._convert(node.input[0]))
            self._converted[base_key] = base
        sel = nn.SelectTable(idx + 1, name=f"{node.name}_{idx}")
        return ModuleNode(sel).inputs(base)

    def _emit(self, node) -> ModuleNode:
        op = node.op
        if node.name in self.inputs or op in ("Placeholder",
                                              "PlaceholderV2"):
            mn = ModuleNode(nn.Identity(name=node.name))
            self._input_nodes.append(mn)
            return mn
        handler = getattr(self, f"_op_{op.lower()}", None)
        if handler is None:
            raise ValueError(
                f"unsupported TF op {op!r} at node {node.name!r} "
                "(reference TensorflowToBigDL pattern not implemented)")
        return handler(node)

    def _unary(self, node, module) -> ModuleNode:
        module.name = node.name
        return ModuleNode(module).inputs(self._convert(node.input[0]))

    # -- op handlers -----------------------------------------------------

    def _op_identity(self, node):
        return self._unary(node, nn.Identity())

    def _op_relu(self, node):
        return self._unary(node, nn.ReLU())

    def _op_relu6(self, node):
        return self._unary(node, nn.ReLU6())

    def _op_tanh(self, node):
        return self._unary(node, nn.Tanh())

    def _op_sigmoid(self, node):
        return self._unary(node, nn.Sigmoid())

    def _op_softmax(self, node):
        return self._unary(node, nn.SoftMax())

    def _op_logsoftmax(self, node):
        # beyond the reference registry (TensorflowToBigDL has Softmax
        # only): the import->train journey for classifier graphs ends in
        # tf.nn.log_softmax, and ClassNLLCriterion consumes log-probs
        return self._unary(node, nn.LogSoftMax())

    def _op_squeeze(self, node):
        dims = [int(d) for d in node.attr["squeeze_dims"].list.i]
        if dims:
            raise ValueError(
                f"Squeeze {node.name}: explicit squeeze_dims unsupported "
                "(axis-numbering differs; squeeze all unit dims instead)")
        return self._unary(node, nn.Squeeze())

    def _op_reshape(self, node):
        shape_node = self._in(node, 1)
        if shape_node.op != "Const":
            raise ValueError("Reshape with dynamic shape is unsupported")
        shape = [int(s) for s in _const_value(shape_node)]
        m = (nn.InferReshape(shape) if -1 in shape[1:]
             else nn.Reshape(tuple(shape[1:]), batch_mode=True))
        m.name = node.name
        return ModuleNode(m).inputs(self._convert(node.input[0]))

    def _op_matmul(self, node, bias: Optional[np.ndarray] = None,
                   name: Optional[str] = None):
        w_node = self._resolve_const(self._in(node, 1))
        if w_node is None:
            raise ValueError(f"MatMul {node.name}: non-Const weights")
        if node.attr["transpose_a"].b:
            raise ValueError(f"MatMul {node.name}: transpose_a unsupported")
        w = _const_value(w_node)       # TF (in, out) == native layout
        if node.attr["transpose_b"].b:
            w = w.T
        lin = nn.Linear(w.shape[0], w.shape[1], with_bias=bias is not None,
                        init_weight=w, init_bias=bias,
                        name=name or node.name)
        return ModuleNode(lin).inputs(self._convert(node.input[0]))

    def _op_conv2d(self, node, bias: Optional[np.ndarray] = None,
                   name: Optional[str] = None):
        w_node = self._resolve_const(self._in(node, 1))
        if w_node is None:
            raise ValueError(f"Conv2D {node.name}: non-Const weights")
        dil = list(node.attr["dilations"].list.i)
        if dil and any(d != 1 for d in dil):
            raise ValueError(f"Conv2D {node.name}: dilations {dil} "
                             "unsupported by the import patterns")
        w = _const_value(w_node)       # HWIO == native layout
        kh, kw, n_in, n_out = w.shape
        sh, sw = _strides_hw(node)
        same = node.attr["padding"].s == b"SAME"
        conv = nn.SpatialConvolution(
            n_in, n_out, kw, kh, sw, sh,
            pad_w=-1 if same else 0, pad_h=-1 if same else 0,
            init_weight=w, init_bias=bias, with_bias=bias is not None,
            format=_data_format(node), name=name or node.name)
        return ModuleNode(conv).inputs(self._convert(node.input[0]))

    def _op_depthwiseconv2dnative(self, node, bias=None, name=None):
        """Depthwise conv = grouped conv with groups == in channels: TF
        kernel (kh, kw, C, M) reshapes to HWIO (kh, kw, 1, C*M) — XLA's
        feature_group_count assigns output block [c*M, (c+1)*M) to input
        channel c, matching TF's output ordering exactly."""
        w_node = self._resolve_const(self._in(node, 1))
        if w_node is None:
            raise ValueError(f"{node.name}: non-Const depthwise weights")
        dil = list(node.attr["dilations"].list.i)
        if dil and any(d != 1 for d in dil):
            raise ValueError(f"{node.name}: dilated depthwise conv "
                             "unsupported by the import patterns")
        w = _const_value(w_node)
        kh, kw, n_in, mult = w.shape
        sh, sw = _strides_hw(node)
        same = node.attr["padding"].s == b"SAME"
        conv = nn.SpatialConvolution(
            n_in, n_in * mult, kw, kh, sw, sh,
            pad_w=-1 if same else 0, pad_h=-1 if same else 0,
            n_group=n_in, init_weight=w.reshape(kh, kw, 1, n_in * mult),
            init_bias=bias, with_bias=bias is not None,
            format=_data_format(node), name=name or node.name)
        return ModuleNode(conv).inputs(self._convert(node.input[0]))

    def _op_biasadd(self, node):
        pre = self._in(node, 0)
        b_node = self._resolve_const(self._in(node, 1))
        if b_node is not None and pre.op in ("Conv2D", "MatMul",
                                             "DepthwiseConv2dNative"):
            # fuse: Conv2D/MatMul + BiasAdd -> one layer (reference
            # TensorflowToBigDL's Conv2D/FullConnection patterns)
            bias = _const_value(b_node)
            handler = {"Conv2D": self._op_conv2d,
                       "MatMul": self._op_matmul,
                       "DepthwiseConv2dNative":
                           self._op_depthwiseconv2dnative}[pre.op]
            mn = handler(pre, bias=bias, name=node.name)
            if self._consumers.get(pre.name, 0) == 1:
                # safe to alias only when the BiasAdd is the sole consumer
                # of the raw Conv2D/MatMul output
                self._converted[pre.name] = mn
            return mn
        return self._op_add(node)

    def _op_add(self, node):
        b = self._resolve_const(self._in(node, 1))
        if b is not None:
            v = _const_value(b)
            if v.ndim == 0:
                return self._unary(node, nn.AddConstant(float(v)))
            # tensor Const addend: the Const handler makes it a graph
            # value, the add is an ordinary CAddTable
        m = nn.CAddTable()
        m.name = node.name
        return ModuleNode(m).inputs(self._convert(node.input[0]),
                                    self._convert(node.input[1]))

    _op_addv2 = _op_add

    def _op_fusedbatchnorm(self, node):
        """FusedBatchNorm(V2/V3) inference import: (x, scale, offset, mean,
        variance) -> SpatialBatchNormalization with frozen running stats."""
        if node.attr["is_training"].b:
            raise ValueError(f"{node.name}: training-mode FusedBatchNorm "
                             "import unsupported")
        parts = [self._resolve_const(self._in(node, i)) for i in (1, 2, 3, 4)]
        if any(p is None for p in parts):
            raise ValueError(f"{node.name}: non-Const batch-norm parameters")
        scale, offset, mean, var = (_const_value(p) for p in parts)
        # a stripped/absent attr reads 0.0; the op's registered default
        eps = float(node.attr["epsilon"].f) or 1e-4
        bn = nn.SpatialBatchNormalization(
            scale.shape[0], eps=eps, init_weight=scale, init_bias=offset,
            format=_data_format(node), name=node.name)
        bn.reset()
        bn.state = {"running_mean": jnp.asarray(mean),
                    "running_var": jnp.asarray(var)}
        return ModuleNode(bn).inputs(self._convert(node.input[0]))

    _op_fusedbatchnormv2 = _op_fusedbatchnorm
    _op_fusedbatchnormv3 = _op_fusedbatchnorm

    def _op_concatv2(self, node):
        """ConcatV2(values..., axis Const) -> JoinTable (1-based dim).
        The value count comes from the 'N' attr — control inputs (^dep)
        trail the regular ones in node.input."""
        n = int(node.attr["N"].i)
        if n <= 0:
            raise ValueError(f"{node.name}: ConcatV2 without the mandatory "
                             "N attr")
        axis_node = self._resolve_const(self._in(node, n))
        if axis_node is None:
            raise ValueError(f"{node.name}: dynamic concat axis unsupported")
        axis = int(_const_value(axis_node))
        m = nn.JoinTable(axis + 1 if axis >= 0 else axis)
        m.name = node.name
        preds = [self._convert(node.input[i]) for i in range(n)]
        return ModuleNode(m).inputs(*preds)

    def _op_pad(self, node):
        """Pad with Const paddings -> SpatialZeroPadding-style padding
        (zero mode only, any rank via the generic Padding op)."""
        pad_node = self._resolve_const(self._in(node, 1))
        if pad_node is None:
            raise ValueError(f"{node.name}: dynamic paddings unsupported")
        pads = _const_value(pad_node).astype(int)   # (ndim, 2)
        m = _ConstPad(pads, name=node.name)
        return ModuleNode(m).inputs(self._convert(node.input[0]))

    def _op_mean(self, node):
        """Mean over Const reduction axes (global average pooling in
        classification heads): keep_dims honored."""
        ax_node = self._resolve_const(self._in(node, 1))
        if ax_node is None:
            raise ValueError(f"{node.name}: dynamic Mean axes unsupported")
        axes = tuple(int(a) for a in np.atleast_1d(_const_value(ax_node)))
        keep = bool(node.attr["keep_dims"].b)
        m = _ReduceMean(axes, keep, name=node.name)
        return ModuleNode(m).inputs(self._convert(node.input[0]))

    def _op_pack(self, node):
        """Pack(values..., N, axis) -> nn.Pack (stack along a new dim)."""
        n = int(node.attr["N"].i)
        axis = int(node.attr["axis"].i)
        m = nn.Pack(axis + 1 if axis >= 0 else axis)
        m.name = node.name
        return ModuleNode(m).inputs(*[self._convert(node.input[i])
                                      for i in range(n)])

    def _op_stridedslice(self, node):
        parts = [self._resolve_const(self._in(node, i)) for i in (1, 2, 3)]
        if any(p is None for p in parts):
            raise ValueError(f"{node.name}: dynamic StridedSlice bounds "
                             "unsupported")
        begin, end, strides = (_const_value(p).reshape(-1) for p in parts)
        if int(node.attr["ellipsis_mask"].i) or \
                int(node.attr["new_axis_mask"].i):
            raise ValueError(f"{node.name}: ellipsis/new_axis StridedSlice "
                             "masks unsupported")
        m = _StridedSliceStatic(begin, end, strides,
                                node.attr["begin_mask"].i,
                                node.attr["end_mask"].i,
                                node.attr["shrink_axis_mask"].i,
                                name=node.name)
        return ModuleNode(m).inputs(self._convert(node.input[0]))

    def _op_const(self, node):
        """Standalone Const reachable as a graph value (TF folds static
        shapes/fills into these).  Sourceless — Graph feeds it the graph
        input, which nn.Const ignores."""
        return ModuleNode(nn.Const(_const_value(node), name=node.name))

    def _op_selectv2(self, node):
        """SelectV2: only the modern tf.nn.dropout subgraph —
        SelectV2(GreaterEqual(RandomUniform, rate), Mul(x, 1/keep), 0)
        imports as nn.Dropout(rate)."""
        cond = self._in(node, 0)
        t = self._in(node, 1)
        if cond.op == "GreaterEqual":
            rnd = self._in(cond, 0)
            rate_node = self._resolve_const(self._in(cond, 1))
            if rnd.op == "RandomUniform" and rate_node is not None:
                rate = float(_const_value(rate_node))
                src_ref = None
                if t.op == "Mul":
                    # strip the 1/keep prescale on the kept branch
                    for i, j in ((1, 0), (0, 1)):
                        if self._resolve_const(self._in(t, i)) is not None:
                            src_ref = t.input[j]
                            break
                if src_ref is not None:
                    m = nn.Dropout(rate)
                    m.name = node.name
                    return ModuleNode(m).inputs(self._convert(src_ref))
        raise ValueError(f"SelectV2 {node.name}: only the tf.nn.dropout "
                         "subgraph pattern is supported")

    def _op_shape(self, node):
        return self._unary(node, nn.Shape())

    def _op_transpose(self, node):
        perm_node = self._resolve_const(self._in(node, 1))
        if perm_node is None:
            raise ValueError(f"{node.name}: dynamic Transpose perm "
                             "unsupported")
        return self._unary(node, _Permute(_const_value(perm_node)
                                          .reshape(-1)))

    def _op_lrn(self, node):
        # defaults apply only when the attr is ABSENT: an explicit 0 (a
        # legal, if degenerate, LRN setting) must import as written, not be
        # truthiness-coerced to the TF default
        def attr_or(name, field, default):
            if name in node.attr:
                return getattr(node.attr[name], field)
            return default

        return self._unary(node, _LRNLastAxis(
            attr_or("depth_radius", "i", 5),
            attr_or("bias", "f", 1.0),
            attr_or("alpha", "f", 1.0),
            attr_or("beta", "f", 0.5)))

    def _op_fill(self, node):
        """Fill(dims, value): folded to a Const when both are static (the
        jit-friendly form — a dynamic output shape cannot trace)."""
        dims_node = self._resolve_const(self._in(node, 0))
        val_node = self._resolve_const(self._in(node, 1))
        if dims_node is None or val_node is None:
            raise ValueError(f"{node.name}: dynamic Fill unsupported "
                             "(XLA needs a static output shape)")
        dims = tuple(int(d) for d in _const_value(dims_node).reshape(-1))
        value = _const_value(val_node)
        m = nn.Const(np.full(dims, value), name=node.name)
        return ModuleNode(m).inputs(self._convert(node.input[0]))

    def _op_mul(self, node):
        """Mul: the tf.nn.dropout(v1) subgraph
        Mul(RealDiv(x, keep), Floor(Add(RandomUniform, keep))) imports as
        nn.Dropout (the reference's DropoutTF pattern); a scalar-Const
        factor becomes MulConstant; otherwise elementwise CMulTable."""
        ins = [self._in(node, 0), self._in(node, 1)]
        ops = [n.op for n in ins]
        if "RealDiv" in ops and "Floor" in ops:
            div = ins[ops.index("RealDiv")]
            keep_node = self._resolve_const(self._in(div, 1))
            if keep_node is not None:
                keep = float(_const_value(keep_node))
                m = nn.Dropout(1.0 - keep)
                m.name = node.name
                return ModuleNode(m).inputs(self._convert(div.input[0]))
        for i, other in ((0, 1), (1, 0)):
            c = self._resolve_const(ins[i])
            if c is not None:
                v = _const_value(c)
                if v.ndim == 0:
                    m = nn.MulConstant(float(v))
                    m.name = node.name
                    return ModuleNode(m).inputs(
                        self._convert(node.input[other]))
                break   # tensor Const factor: elementwise via CMulTable
        m = nn.CMulTable()
        m.name = node.name
        return ModuleNode(m).inputs(self._convert(node.input[0]),
                                    self._convert(node.input[1]))

    def _op_maxpool(self, node):
        return self._pool(node, nn.SpatialMaxPooling)

    def _op_avgpool(self, node):
        return self._pool(node, nn.SpatialAveragePooling)

    def _pool(self, node, cls):
        kh, kw = _ksize_hw(node)
        sh, sw = _strides_hw(node)
        if node.attr["padding"].s == b"SAME":
            raise ValueError(
                f"{node.op} {node.name}: SAME pooling import is unsupported "
                "(express it as explicit padding in the source graph)")
        m = cls(kw, kh, sw, sh, format=_data_format(node))
        m.name = node.name
        if cls is nn.SpatialAveragePooling:
            m.count_include_pad = False
        return ModuleNode(m).inputs(self._convert(node.input[0]))


def load(path_or_graphdef, inputs: List[str], outputs: List[str]) -> Graph:
    return TensorflowLoader.load(path_or_graphdef, inputs, outputs)
