"""bigdl_tpu.utils.tf — TensorFlow GraphDef interop (reference ``utils/tf/``)."""

from bigdl_tpu.utils.tf.loader import TensorflowLoader, load
from bigdl_tpu.utils.tf import saver

__all__ = ["TensorflowLoader", "load", "saver"]
