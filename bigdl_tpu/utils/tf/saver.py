"""TensorFlow GraphDef exporter.

Reference equivalent: ``utils/tf/TensorflowSaver.scala`` +
``BigDLToTensorflow.scala`` — walk the model, emit one TF op (or fused op
pair) per layer with the trained weights as Const nodes, write a GraphDef
a stock TF runtime (or this package's loader) can execute.

Graph construction uses ``tf.compat.v1`` proto building only — no TF
session runs.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import bigdl_tpu.nn as nn


def save(model, input_shape: Sequence[Optional[int]], path: str) -> None:
    """Export ``model`` (Sequential or Graph over the supported layer set)
    to a binary GraphDef at ``path``.  ``input_shape`` includes the batch
    dim (None for dynamic).  The graph's input is named ``input``, output
    ``output``."""
    import tensorflow as tf

    model._ensure_init()
    g = tf.Graph()
    with g.as_default():
        x = tf.compat.v1.placeholder(tf.float32, shape=input_shape,
                                     name="input")
        out = _emit_module(tf, model, x)
        tf.identity(out, name="output")
    with open(path, "wb") as f:
        f.write(g.as_graph_def().SerializeToString())


def _np(a) -> np.ndarray:
    return np.asarray(a)


def _emit_module(tf, module, x):
    if isinstance(module, nn.Sequential):
        for child in module.children:
            x = _emit_module(tf, child, x)
        return x
    if isinstance(module, nn.module.Container) and hasattr(module,
                                                           "executions"):
        return _emit_graph(tf, module, x)

    p = module.params
    if isinstance(module, nn.Linear):
        w = tf.constant(_np(p["weight"]))          # (in, out): TF layout
        y = tf.matmul(x, w)
        if module.with_bias:
            y = tf.nn.bias_add(y, tf.constant(_np(p["bias"])))
        return y
    if isinstance(module, nn.SpatialConvolution):
        if module.n_group != 1:
            raise ValueError("grouped conv export unsupported")
        w = tf.constant(_np(p["weight"]))          # HWIO: TF layout
        pad = ("SAME" if module.pad_w == -1 else
               ("VALID" if (module.pad_w, module.pad_h) == (0, 0) else None))
        if pad is None:
            raise ValueError(
                f"conv {module.name}: explicit padding export unsupported")
        if module.format == "NHWC":
            strides = [1, module.stride_h, module.stride_w, 1]
        else:
            strides = [1, 1, module.stride_h, module.stride_w]
        y = tf.nn.conv2d(x, w, strides=strides, padding=pad,
                         data_format=module.format)
        if module.with_bias:
            y = tf.nn.bias_add(y, tf.constant(_np(p["bias"])),
                               data_format=module.format)
        return y
    if isinstance(module, nn.SpatialMaxPooling):
        return _pool(tf, module, x, tf.nn.max_pool2d)
    if isinstance(module, nn.SpatialAveragePooling):
        return _pool(tf, module, x, tf.nn.avg_pool2d)
    if isinstance(module, nn.ReLU):
        return tf.nn.relu(x)
    if isinstance(module, nn.ReLU6):
        return tf.nn.relu6(x)
    if isinstance(module, nn.Tanh):
        return tf.tanh(x)
    if isinstance(module, nn.Sigmoid):
        return tf.sigmoid(x)
    if isinstance(module, nn.SoftMax):
        return tf.nn.softmax(x)
    if isinstance(module, nn.LogSoftMax):
        return tf.nn.log_softmax(x)
    if isinstance(module, (nn.Reshape, nn.View)):
        size = module.size if isinstance(module, nn.Reshape) else module.sizes
        return tf.reshape(x, [-1] + [int(s) for s in size])
    if isinstance(module, nn.Squeeze):
        if module.dim is not None:
            raise ValueError("per-dim Squeeze export unsupported")
        return tf.squeeze(x)
    if isinstance(module, (nn.Identity, nn.Dropout)):
        return tf.identity(x)   # Dropout exports as inference-time identity
    if isinstance(module, nn.BatchNormalization):
        # fused inference form (FusedBatchNormV3) — the same op this
        # package's loader imports, so the round trip is exact
        st = module.state
        n = module.n_output
        scale = _np(p["weight"]) if module.affine else np.ones(n, np.float32)
        offset = _np(p["bias"]) if module.affine else np.zeros(n, np.float32)
        fmt = "NCHW" if getattr(module, "channel_axis", 1) == 1 else "NHWC"
        y, _, _ = tf.compat.v1.nn.fused_batch_norm(
            x, scale.astype(np.float32), offset.astype(np.float32),
            mean=_np(st["running_mean"]).astype(np.float32),
            variance=_np(st["running_var"]).astype(np.float32),
            epsilon=module.eps, data_format=fmt, is_training=False)
        return y
    if isinstance(module, nn.SpatialCrossMapLRN):
        # tf.nn.lrn is NHWC-only and its alpha is per-element (caffe's is
        # divided by the window size): transpose around the op and rescale
        if module.size % 2 == 0:
            raise ValueError(f"LRN {module.name}: even window size has no "
                             "TF depth_radius equivalent")
        xt = tf.transpose(x, [0, 2, 3, 1])
        y = tf.nn.lrn(xt, depth_radius=(module.size - 1) // 2,
                      bias=module.k, alpha=module.alpha / module.size,
                      beta=module.beta)
        return tf.transpose(y, [0, 3, 1, 2])
    raise ValueError(
        f"layer {type(module).__name__} has no GraphDef export mapping "
        "(reference BigDLToTensorflow scope)")


def _pool(tf, module, x, op):
    if module.pad_w or module.pad_h:
        raise ValueError("padded pooling export unsupported")
    if module.format == "NHWC":
        ksize = [1, module.kh, module.kw, 1]
        strides = [1, module.dh, module.dw, 1]
    else:
        ksize = [1, 1, module.kh, module.kw]
        strides = [1, 1, module.dh, module.dw]
    return op(x, ksize=ksize, strides=strides, padding="VALID",
              data_format=module.format)


def _emit_graph(tf, graph, x):
    outputs = {}
    for node in graph.executions:
        if node in graph.input_nodes or not node.prev:
            outputs[id(node)] = _emit_module(tf, node.element, x)
            continue
        ins = [outputs[id(p)] for p in node.prev]
        m = node.element
        if isinstance(m, nn.CAddTable):
            outputs[id(node)] = tf.add_n(ins)
        elif isinstance(m, nn.CMulTable):
            y = ins[0]
            for extra in ins[1:]:
                y = tf.multiply(y, extra)
            outputs[id(node)] = y
        elif isinstance(m, nn.JoinTable):
            # our JoinTable dimension is 1-based over the full tensor
            outputs[id(node)] = tf.concat(ins, axis=m.dimension - 1)
        else:
            if len(ins) != 1:
                raise ValueError(
                    f"multi-input layer {type(m).__name__} unsupported")
            outputs[id(node)] = _emit_module(tf, m, ins[0])
    return outputs[id(graph.output_nodes[0])]
