"""bigdl_tpu.utils.caffe — Caffe model interop (reference ``utils/caffe/``)."""

from bigdl_tpu.utils.caffe.loader import CaffeLoader, load_caffe
from bigdl_tpu.utils.caffe import persister

__all__ = ["CaffeLoader", "load_caffe", "persister"]
