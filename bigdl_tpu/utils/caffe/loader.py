"""Caffe model importer.

Reference equivalent: ``utils/caffe/CaffeLoader.scala:56,267`` — parse a
prototxt (text) + caffemodel (binary) pair, convert layer-by-layer through
registered converters into a Graph, and copy the trained blobs.

The protobuf schema is a trimmed transcription of BVLC caffe.proto with the
original field numbers (``caffe_minimal.proto``; the reference vendors the
generated ``caffe/Caffe.java``).  Caffe's NCHW activations and OIHW conv
kernels map onto the native layers via one transpose to HWIO.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.graph import Graph, ModuleNode
from bigdl_tpu.utils.caffe import caffe_minimal_pb2 as pb


def _blob_array(blob) -> np.ndarray:
    data = np.asarray(blob.data, dtype=np.float32)
    if blob.HasField("shape"):
        return data.reshape(tuple(blob.shape.dim))
    dims = [d for d in (blob.num, blob.channels, blob.height, blob.width)
            if d > 0]
    return data.reshape(tuple(dims) if dims else (-1,))


class _CaffeSlice(nn.Module):
    """caffe Slice: split ``axis`` into ``n_out`` groups (keeping the
    dim), at explicit ``slice_point`` boundaries or equally.  Emits a
    Table; the loader wires one SelectTable per top.  (The reference maps
    Slice to SplitTable, which removes the dim — this keeps caffe's
    actual blob shapes.)"""

    def __init__(self, axis: int, n_out: int, points=(), name=None):
        super().__init__(name)
        self.axis = axis
        self.n_out = n_out
        self.points = tuple(int(p) for p in points)

    def apply(self, params, input, state, training=False, rng=None):
        size = input.shape[self.axis]
        if self.points:
            bounds = (0,) + self.points + (size,)
        else:
            if size % self.n_out != 0:
                raise ValueError(
                    f"{self.name}: axis {self.axis} size {size} does not "
                    f"split equally into {self.n_out} tops")
            step = size // self.n_out
            bounds = tuple(range(0, size + 1, step))
        outs = []
        for i in range(self.n_out):
            idx = [slice(None)] * input.ndim
            idx[self.axis] = slice(bounds[i], bounds[i + 1])
            outs.append(input[tuple(idx)])
        return outs, state


class _ChannelSoftMax(nn.Module):
    """Softmax over axis 1 — caffe's default normalization axis for any
    blob rank (our ``nn.SoftMax`` normalizes the last axis, which only
    coincides for 2-D blobs)."""

    def apply(self, params, input, state, training=False, rng=None):
        import jax
        return jax.nn.softmax(input, axis=1), state


def _conv_geom(cp):
    kh = cp.kernel_h if cp.HasField("kernel_h") else (
        cp.kernel_size[0] if cp.kernel_size else 1)
    kw = cp.kernel_w if cp.HasField("kernel_w") else (
        cp.kernel_size[-1] if cp.kernel_size else 1)
    sh = cp.stride_h if cp.HasField("stride_h") else (
        cp.stride[0] if cp.stride else 1)
    sw = cp.stride_w if cp.HasField("stride_w") else (
        cp.stride[-1] if cp.stride else 1)
    ph = cp.pad_h if cp.HasField("pad_h") else (cp.pad[0] if cp.pad else 0)
    pw = cp.pad_w if cp.HasField("pad_w") else (cp.pad[-1] if cp.pad else 0)
    return kh, kw, sh, sw, ph, pw


# V1 LayerType enum -> V2 type string (the upgrade caffe itself performs
# in upgrade_proto.cpp; reference handles V1 via V1LayerConverter.scala)
_V1_TYPE = {
    "CONCAT": "Concat", "CONVOLUTION": "Convolution", "DROPOUT": "Dropout",
    "ELTWISE": "Eltwise", "FLATTEN": "Flatten",
    "INNER_PRODUCT": "InnerProduct", "LRN": "LRN", "POOLING": "Pooling",
    "RELU": "ReLU", "SIGMOID": "Sigmoid", "SOFTMAX": "Softmax",
    "SOFTMAX_LOSS": "SoftmaxWithLoss", "SPLIT": "Split", "TANH": "TanH",
    "DATA": "Data", "ACCURACY": "Accuracy",
    "ABSVAL": "AbsVal", "EXP": "Exp", "POWER": "Power", "SLICE": "Slice",
    "THRESHOLD": "Threshold", "EUCLIDEAN_LOSS": "EuclideanLoss",
}
_V1_PARAMS = ("concat_param", "convolution_param", "dropout_param",
              "eltwise_param", "inner_product_param", "lrn_param",
              "pooling_param", "power_param", "slice_param",
              "threshold_param", "exp_param", "softmax_param")

# loss-layer -> criterion channel (reference CaffeLoader.tryAddCriterion,
# ``CaffeLoader.scala:401-418``).  value = (criterion factory,
# criterion_only): criterion-only loss layers contribute NO module to the
# inference graph (their bottoms are just consumed), while the others keep
# an inference-view module (channel softmax / sigmoid head)
_LOSS_CRITERIONS = {
    "SoftmaxWithLoss": (lambda: nn.ClassNLLCriterion(), False),
    "EuclideanLoss": (lambda: nn.MSECriterion(), True),
    "HingeLoss": (lambda: nn.HingeEmbeddingCriterion(), True),
    "SigmoidCrossEntropyLoss": (lambda: nn.CrossEntropyCriterion(), False),
    "ContrastiveLoss": (lambda: nn.CosineEmbeddingCriterion(), True),
}


def _upgrade_v1(net, strict: bool = True) -> None:
    """Rewrite legacy ``layers`` (V1LayerParameter) into ``layer`` entries
    so every converter below sees one format.  ``strict=False`` (the
    weights pass) skips unsupported layer types instead of raising —
    only blobs are read from a caffemodel, and data/solver-era layers
    never carry blobs the importer needs."""
    if net.layers and net.layer:
        raise ValueError("net mixes legacy 'layers' and new 'layer' "
                         "entries — upgrade the prototxt to one format "
                         "(caffe's own upgrader rejects mixed nets)")
    for v1 in net.layers:
        tname = pb.V1LayerParameter.LayerType.Name(v1.type)
        if tname not in _V1_TYPE:
            if not strict:
                continue
            raise ValueError(f"{v1.name}: unsupported legacy layer type "
                             f"{tname}")
        layer = net.layer.add()
        layer.name = v1.name
        layer.type = _V1_TYPE[tname]
        layer.bottom.extend(v1.bottom)
        layer.top.extend(v1.top)
        layer.include.extend(v1.include)
        layer.blobs.extend(v1.blobs)
        for p in _V1_PARAMS:
            if v1.HasField(p):
                getattr(layer, p).CopyFrom(getattr(v1, p))
    del net.layers[:]


class CaffeLoader:
    """(reference ``CaffeLoader.scala:56,267`` + ``V1LayerConverter.scala``:
    legacy ``layers``-format prototxts/caffemodels are upgraded in place)."""

    def __init__(self, def_path: str, model_path: Optional[str] = None):
        from google.protobuf import text_format
        self.net = pb.NetParameter()
        with open(def_path) as f:
            text_format.Merge(f.read(), self.net)
        _upgrade_v1(self.net)
        self.blobs: Dict[str, List[np.ndarray]] = {}
        # criterions detected from train-protocol loss layers (reference
        # ``tryAddCriterion``); read via ``criterion()`` after load()
        self.criterions: List[nn.Criterion] = []
        if model_path:
            weights = pb.NetParameter()
            with open(model_path, "rb") as f:
                weights.ParseFromString(f.read())
            _upgrade_v1(weights, strict=False)
            for layer in weights.layer:
                if layer.blobs:
                    self.blobs[layer.name] = [_blob_array(b)
                                              for b in layer.blobs]

    # -- graph construction ----------------------------------------------

    def load(self) -> Graph:
        """Convert to a Graph following bottom/top blob topology
        (reference ``CaffeLoader.createCaffeGraph:267``)."""
        tops: Dict[str, ModuleNode] = {}   # blob name -> producing node
        inputs: List[ModuleNode] = []
        produced: List[str] = []           # blob names, production order
        last_prod: Dict[str, int] = {}     # blob -> layer index of producer
        last_cons: Dict[str, int] = {}     # blob -> layer index of consumer

        for name in self.net.input:
            node = ModuleNode(nn.Identity(name=name))
            tops[name] = node
            inputs.append(node)
        for idx, layer in enumerate(self.net.layer):
            if any(rule.phase == pb.TRAIN for rule in layer.include):
                # TRAIN-only layer: alias its tops to the bottom so TEST
                # consumers of an in-place top still resolve
                for top in layer.top:
                    if layer.bottom:
                        tops[top] = tops[layer.bottom[0]]
                continue
            if layer.type in ("Input", "Data"):
                # legacy DATA layers are the V1 ingest tier: each top
                # (data/label) becomes a graph input
                for top in layer.top:
                    node = ModuleNode(nn.Identity(name=f"{layer.name}_{top}"))
                    tops[top] = node
                    inputs.append(node)
                continue
            if layer.type == "Accuracy":
                # eval-only metric layer: no module, but its bottoms are
                # consumed (they must not dangle into spurious outputs)
                for b in layer.bottom:
                    last_cons[b] = idx
                continue
            if layer.type == "Split":
                # V1 explicit fan-out: all tops alias the bottom (and are
                # produced here, so a dangling branch can be an output)
                src = tops[layer.bottom[0]]
                last_cons[layer.bottom[0]] = idx
                for top in layer.top:
                    tops[top] = src
                    produced.append(top)
                    last_prod[top] = idx
                continue
            if layer.type in _LOSS_CRITERIONS:
                factory, criterion_only = _LOSS_CRITERIONS[layer.type]
                self.criterions.append(factory())
                if criterion_only:
                    # pure training-loss layer: no inference module.  Only
                    # the LABEL bottoms are consumed — the prediction
                    # bottom stays dangling so the inference graph keeps
                    # its natural output (the reference drops the loss
                    # layer the same way)
                    for b in layer.bottom[1:]:
                        last_cons[b] = idx
                    continue
            if layer.type == "Slice":
                # one slice node feeding a SelectTable per top (caffe's
                # multi-top split along an axis, slice_point supported)
                sp = layer.slice_param
                split = ModuleNode(_CaffeSlice(
                    int(sp.axis), len(layer.top),
                    points=list(sp.slice_point), name=layer.name))
                split.inputs(tops[layer.bottom[0]])
                last_cons[layer.bottom[0]] = idx
                for i, top in enumerate(layer.top):
                    sel = ModuleNode(nn.SelectTable(
                        i + 1, name=f"{layer.name}_{top}"))
                    sel.inputs(split)
                    tops[top] = sel
                    produced.append(top)
                    last_prod[top] = idx
                continue
            node = ModuleNode(self._convert(layer))
            bottoms = list(layer.bottom)
            if (layer.type in ("SoftmaxWithLoss", "SigmoidCrossEntropyLoss")
                    and len(bottoms) > 1):
                bottoms = bottoms[:1]       # drop the label bottom
            preds = [self._pred(tops, layer, i, bottoms[i])
                     for i in range(len(bottoms))]
            if preds:
                node.inputs(*preds)
            for b in layer.bottom:
                last_cons[b] = idx
            # the canonical pre-2014 train prototxt ends in a TOPLESS loss
            # layer; give it a synthetic top so the net keeps an output
            layer_tops = list(layer.top) or [layer.name]
            for top in layer_tops:
                tops[top] = node
                produced.append(top)
                last_prod[top] = idx

        if not inputs:
            raise ValueError("prototxt declares no inputs "
                             "(need input:/Input layers)")
        # outputs = dangling tops: a blob is an output when its final
        # producer is not followed by a consumer.  In-place layers
        # (bottom == top) consume and re-produce the same name at the same
        # index, so >= keeps a trailing in-place layer's blob alive while a
        # mid-chain one (consumed by a later layer) is dropped.
        out_nodes, seen = [], set()
        for name in produced:
            if name in seen:
                continue
            seen.add(name)
            if name in last_cons and last_prod[name] < last_cons[name]:
                continue
            out_nodes.append(tops[name])
        if not out_nodes:
            raise ValueError("prototxt has no output layer (every top is "
                             "consumed, or the net is input-only)")
        return Graph(inputs, out_nodes)

    def _pred(self, tops, layer, i: int,
              bottom: Optional[str] = None) -> ModuleNode:
        """Predecessor node for bottom i, inserting a scale node for
        Eltwise SUM coefficients (a - b imports as a + (-1)*b)."""
        node = tops[bottom if bottom is not None else layer.bottom[i]]
        if layer.type == "Eltwise":
            ep = layer.eltwise_param
            coeffs = list(ep.coeff)
            if coeffs and ep.operation == pb.EltwiseParameter.SUM:
                c = coeffs[i] if i < len(coeffs) else 1.0
                if c != 1.0:
                    scaled = ModuleNode(nn.MulConstant(
                        float(c), name=f"{layer.name}_coeff{i}"))
                    scaled.inputs(node)
                    return scaled
        return node

    # -- layer converters (reference Converter/LayerConverter) -----------

    def _convert(self, layer) -> Optional[nn.Module]:
        t = layer.type
        name = layer.name
        blobs = self.blobs.get(name, [])
        if t == "Convolution":
            cp = layer.convolution_param
            kh, kw, sh, sw, ph, pw = _conv_geom(cp)
            if any(d != 1 for d in cp.dilation):
                raise ValueError(f"{name}: dilated caffe conv unsupported")
            w = b = None
            n_in = None
            if blobs:
                w = blobs[0]                       # OIHW
                n_in = w.shape[1] * cp.group
                w = np.transpose(w, (2, 3, 1, 0))  # -> HWIO
                if cp.bias_term and len(blobs) > 1:
                    b = blobs[1].reshape(-1)
            if n_in is None:
                raise ValueError(
                    f"{name}: cannot infer input planes without a "
                    "caffemodel blob")
            return nn.SpatialConvolution(
                n_in, int(cp.num_output), kw, kh, sw, sh, pw, ph,
                n_group=int(cp.group), with_bias=bool(cp.bias_term),
                init_weight=w, init_bias=b, name=name)
        if t == "InnerProduct":
            ip = layer.inner_product_param
            if not blobs:
                raise ValueError(f"{name}: InnerProduct needs weights")
            w = blobs[0]                           # (out, in)
            if w.ndim == 4:
                # genuine V1-era caffemodels predate BlobShape and store IP
                # weights via legacy dims (1, 1, out, in)
                w = w.reshape(w.shape[-2], w.shape[-1])
            if ip.transpose:
                w = w.T
            b = blobs[1].reshape(-1) if (ip.bias_term and
                                         len(blobs) > 1) else None
            flat_in = int(w.shape[1])
            lin = nn.Linear(flat_in, int(ip.num_output),
                            with_bias=bool(ip.bias_term),
                            init_weight=np.ascontiguousarray(w.T),
                            init_bias=b, name=name)
            # caffe flattens (N, C, H, W) implicitly at axis 1
            seq = nn.Sequential(name=f"{name}_flatten")
            seq.add(nn.InferReshape([0, -1])).add(lin)
            return seq
        if t == "Pooling":
            pp = layer.pooling_param
            kh = int(pp.kernel_h or pp.kernel_size)
            kw = int(pp.kernel_w or pp.kernel_size)
            sh = int(pp.stride_h or pp.stride)
            sw = int(pp.stride_w or pp.stride)
            ph = int(pp.pad_h or pp.pad)
            pw = int(pp.pad_w or pp.pad)
            if pp.global_pooling:
                raise ValueError(f"{name}: global pooling unsupported")
            if pp.pool == pb.PoolingParameter.MAX:
                m = nn.SpatialMaxPooling(kw, kh, sw, sh, pw, ph, name=name)
            else:
                m = nn.SpatialAveragePooling(kw, kh, sw, sh, pw, ph,
                                             name=name)
            # caffe default is ceil-mode output sizing; round_mode: FLOOR
            # (BVLC PoolingParameter field 13) selects floor
            if pp.round_mode == pb.PoolingParameter.FLOOR:
                return m
            return m.ceil()
        if t == "ReLU":
            return nn.ReLU(name=name)
        if t == "TanH":
            return nn.Tanh(name=name)
        if t == "Sigmoid":
            return nn.Sigmoid(name=name)
        if t == "SoftmaxWithLoss":
            # inference view of the training loss head: channel softmax
            # over the prediction bottom (the label bottom was dropped)
            return _ChannelSoftMax(name=name)
        if t == "Softmax":
            axis = int(layer.softmax_param.axis) if layer.HasField(
                "softmax_param") else 1
            if axis == -1:
                return nn.SoftMax(name=name)    # last-axis (our exporter)
            if axis != 1:
                raise ValueError(f"{name}: Softmax axis {axis} unsupported")
            return _ChannelSoftMax(name=name)
        if t == "LRN":
            lp = layer.lrn_param
            if lp.norm_region == pb.LRNParameter.WITHIN_CHANNEL:
                if abs(float(lp.k) - 1.0) > 1e-9:
                    raise ValueError(
                        f"{name}: within-channel LRN with k={lp.k} "
                        "unsupported (k is fixed at 1)")
                return nn.SpatialWithinChannelLRN(
                    int(lp.local_size), float(lp.alpha), float(lp.beta),
                    name=name)
            return nn.SpatialCrossMapLRN(int(lp.local_size), float(lp.alpha),
                                         float(lp.beta), float(lp.k),
                                         name=name)
        if t == "Dropout":
            return nn.Dropout(float(layer.dropout_param.dropout_ratio),
                              name=name)
        if t == "Concat":
            axis = int(layer.concat_param.axis)
            return nn.JoinTable(axis + 1, name=name)   # 0-based -> 1-based
        if t == "Eltwise":
            ep = layer.eltwise_param
            if list(ep.coeff) and ep.operation != pb.EltwiseParameter.SUM:
                raise ValueError(f"{name}: Eltwise coeff is only defined "
                                 "for SUM")
            if ep.operation == pb.EltwiseParameter.SUM:
                return nn.CAddTable(name=name)
            if ep.operation == pb.EltwiseParameter.MAX:
                return nn.CMaxTable(name=name)
            return nn.CMulTable(name=name)
        if t == "Flatten":
            return nn.InferReshape([0, -1], name=name)
        if t == "BatchNorm":
            # blobs = [mean, variance, scale_factor]; BVLC stores the
            # UNSCALED sums — divide by the scale factor for the running
            # statistics.  No affine params (that is the paired Scale
            # layer's job, like caffe itself).
            if not blobs:
                raise ValueError(f"{name}: BatchNorm needs a caffemodel "
                                 "(mean/var blobs)")
            eps = float(layer.batch_norm_param.eps) if layer.HasField(
                "batch_norm_param") else 1e-5
            mean, var = blobs[0].reshape(-1), blobs[1].reshape(-1)
            sf = float(blobs[2].reshape(-1)[0]) if len(blobs) > 2 else 1.0
            if sf == 0.0:
                sf = 1.0
            return nn.SpatialBatchNormalization(
                int(mean.shape[0]), eps=eps, affine=False,
                init_running_mean=mean / sf, init_running_var=var / sf,
                name=name)
        if t == "Scale":
            # blobs = [gamma(, beta if bias_term)] — the affine half of a
            # caffe BatchNorm+Scale pair (channel-wise when 1-D)
            sp = (layer.scale_param if layer.HasField("scale_param")
                  else pb.ScaleParameter())
            if not blobs:
                raise ValueError(f"{name}: Scale without caffemodel blobs "
                                 "unsupported (size is only recorded in "
                                 "the blob shapes)")
            gamma = blobs[0]
            beta = (blobs[1] if (sp.bias_term and len(blobs) > 1)
                    else np.zeros_like(gamma))
            return nn.Scale(gamma.shape, init_weight=gamma, init_bias=beta,
                            name=name)
        if t == "Bias":
            # learnable per-element bias (reference maps to nn.Add)
            if not blobs:
                raise ValueError(f"{name}: Bias without caffemodel blobs "
                                 "unsupported")
            b = blobs[0].reshape(-1)
            return nn.Add(int(b.shape[0]), init_bias=b, name=name)
        if t == "PReLU":
            # blob = per-channel slopes (shared -> one element)
            if blobs:
                slopes = blobs[0].reshape(-1)
                return nn.PReLU(int(slopes.shape[0])
                                if slopes.shape[0] > 1 else 0,
                                init_weight=slopes, name=name)
            return nn.PReLU(name=name)
        if t == "ELU":
            alpha = float(layer.elu_param.alpha) if layer.HasField(
                "elu_param") else 1.0
            return nn.ELU(alpha, name=name)
        if t == "Power":
            pp = (layer.power_param if layer.HasField("power_param")
                  else pb.PowerParameter())
            return nn.Power(float(pp.power), float(pp.scale),
                            float(pp.shift), name=name)
        if t == "Log":
            # reference imports LOG as plain nn.Log (base/scale/shift
            # defaults); reject the parameterized form honestly
            lp = (layer.log_param if layer.HasField("log_param")
                  else pb.LogParameter())
            if (lp.base != -1.0 or lp.scale != 1.0 or lp.shift != 0.0):
                raise ValueError(f"{name}: parameterized Log "
                                 "(base/scale/shift) unsupported")
            return nn.Log(name=name)
        if t == "Exp":
            ep = (layer.exp_param if layer.HasField("exp_param")
                  else pb.ExpParameter())
            if (ep.base != -1.0 or ep.scale != 1.0 or ep.shift != 0.0):
                raise ValueError(f"{name}: parameterized Exp "
                                 "(base/scale/shift) unsupported")
            return nn.Exp(name=name)
        if t == "AbsVal":
            return nn.Abs(name=name)
        if t == "Threshold":
            th = (float(layer.threshold_param.threshold)
                  if layer.HasField("threshold_param") else 0.0)
            return nn.Threshold(th, name=name)
        if t == "Reshape":
            rp = layer.reshape_param
            dims = [int(d) for d in rp.shape.dim]
            if int(rp.axis) != 0 or int(rp.num_axes) != -1:
                raise ValueError(f"{name}: Reshape axis/num_axes "
                                 "unsupported (whole-blob reshape only)")
            return nn.InferReshape(dims, name=name)
        if t == "Tile":
            tp = layer.tile_param
            return nn.Replicate(int(tp.tiles), int(tp.axis), name=name)
        if t in ("Recurrent", "RNN"):
            # parity with the reference's placeholder import
            # (``Converter.fromCaffeRecurrent`` constructs a bare
            # Recurrent container; the user adds the cell)
            return nn.Recurrent(name=name)
        if t == "SigmoidCrossEntropyLoss":
            # inference view of the sigmoid-cross-entropy head (the
            # criterion channel captured CrossEntropyCriterion)
            return nn.Sigmoid(name=name)
        raise ValueError(f"unsupported caffe layer type {t!r} at {name!r} "
                         "(reference CaffeLoader converter not implemented)")

    def criterion(self) -> Optional[nn.Criterion]:
        """The criterion detected from the train prototxt's loss layers
        (reference ``CaffeLoader.tryAddCriterion``): None when the
        prototxt is inference-only, the single criterion when one loss
        layer exists, a ParallelCriterion over all of them otherwise."""
        if not self.criterions:
            return None
        if len(self.criterions) == 1:
            return self.criterions[0]
        pc = nn.ParallelCriterion()
        for c in self.criterions:
            pc.add(c)
        return pc


def load_caffe(def_path: str, model_path: Optional[str] = None) -> Graph:
    """(reference ``Module.loadCaffe``)."""
    return CaffeLoader(def_path, model_path).load()
