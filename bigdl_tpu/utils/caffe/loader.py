"""Caffe model importer.

Reference equivalent: ``utils/caffe/CaffeLoader.scala:56,267`` — parse a
prototxt (text) + caffemodel (binary) pair, convert layer-by-layer through
registered converters into a Graph, and copy the trained blobs.

The protobuf schema is a trimmed transcription of BVLC caffe.proto with the
original field numbers (``caffe_minimal.proto``; the reference vendors the
generated ``caffe/Caffe.java``).  Caffe's NCHW activations and OIHW conv
kernels map onto the native layers via one transpose to HWIO.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.graph import Graph, ModuleNode
from bigdl_tpu.utils.caffe import caffe_minimal_pb2 as pb


def _blob_array(blob) -> np.ndarray:
    data = np.asarray(blob.data, dtype=np.float32)
    if blob.HasField("shape"):
        return data.reshape(tuple(blob.shape.dim))
    dims = [d for d in (blob.num, blob.channels, blob.height, blob.width)
            if d > 0]
    return data.reshape(tuple(dims) if dims else (-1,))


class _ChannelSoftMax(nn.Module):
    """Softmax over axis 1 — caffe's default normalization axis for any
    blob rank (our ``nn.SoftMax`` normalizes the last axis, which only
    coincides for 2-D blobs)."""

    def apply(self, params, input, state, training=False, rng=None):
        import jax
        return jax.nn.softmax(input, axis=1), state


def _conv_geom(cp):
    kh = cp.kernel_h if cp.HasField("kernel_h") else (
        cp.kernel_size[0] if cp.kernel_size else 1)
    kw = cp.kernel_w if cp.HasField("kernel_w") else (
        cp.kernel_size[-1] if cp.kernel_size else 1)
    sh = cp.stride_h if cp.HasField("stride_h") else (
        cp.stride[0] if cp.stride else 1)
    sw = cp.stride_w if cp.HasField("stride_w") else (
        cp.stride[-1] if cp.stride else 1)
    ph = cp.pad_h if cp.HasField("pad_h") else (cp.pad[0] if cp.pad else 0)
    pw = cp.pad_w if cp.HasField("pad_w") else (cp.pad[-1] if cp.pad else 0)
    return kh, kw, sh, sw, ph, pw


# V1 LayerType enum -> V2 type string (the upgrade caffe itself performs
# in upgrade_proto.cpp; reference handles V1 via V1LayerConverter.scala)
_V1_TYPE = {
    "CONCAT": "Concat", "CONVOLUTION": "Convolution", "DROPOUT": "Dropout",
    "ELTWISE": "Eltwise", "FLATTEN": "Flatten",
    "INNER_PRODUCT": "InnerProduct", "LRN": "LRN", "POOLING": "Pooling",
    "RELU": "ReLU", "SIGMOID": "Sigmoid", "SOFTMAX": "Softmax",
    "SOFTMAX_LOSS": "SoftmaxWithLoss", "SPLIT": "Split", "TANH": "TanH",
    "DATA": "Data", "ACCURACY": "Accuracy",
}
_V1_PARAMS = ("concat_param", "convolution_param", "dropout_param",
              "eltwise_param", "inner_product_param", "lrn_param",
              "pooling_param", "softmax_param")


def _upgrade_v1(net, strict: bool = True) -> None:
    """Rewrite legacy ``layers`` (V1LayerParameter) into ``layer`` entries
    so every converter below sees one format.  ``strict=False`` (the
    weights pass) skips unsupported layer types instead of raising —
    only blobs are read from a caffemodel, and data/solver-era layers
    never carry blobs the importer needs."""
    if net.layers and net.layer:
        raise ValueError("net mixes legacy 'layers' and new 'layer' "
                         "entries — upgrade the prototxt to one format "
                         "(caffe's own upgrader rejects mixed nets)")
    for v1 in net.layers:
        tname = pb.V1LayerParameter.LayerType.Name(v1.type)
        if tname not in _V1_TYPE:
            if not strict:
                continue
            raise ValueError(f"{v1.name}: unsupported legacy layer type "
                             f"{tname}")
        layer = net.layer.add()
        layer.name = v1.name
        layer.type = _V1_TYPE[tname]
        layer.bottom.extend(v1.bottom)
        layer.top.extend(v1.top)
        layer.include.extend(v1.include)
        layer.blobs.extend(v1.blobs)
        for p in _V1_PARAMS:
            if v1.HasField(p):
                getattr(layer, p).CopyFrom(getattr(v1, p))
    del net.layers[:]


class CaffeLoader:
    """(reference ``CaffeLoader.scala:56,267`` + ``V1LayerConverter.scala``:
    legacy ``layers``-format prototxts/caffemodels are upgraded in place)."""

    def __init__(self, def_path: str, model_path: Optional[str] = None):
        from google.protobuf import text_format
        self.net = pb.NetParameter()
        with open(def_path) as f:
            text_format.Merge(f.read(), self.net)
        _upgrade_v1(self.net)
        self.blobs: Dict[str, List[np.ndarray]] = {}
        if model_path:
            weights = pb.NetParameter()
            with open(model_path, "rb") as f:
                weights.ParseFromString(f.read())
            _upgrade_v1(weights, strict=False)
            for layer in weights.layer:
                if layer.blobs:
                    self.blobs[layer.name] = [_blob_array(b)
                                              for b in layer.blobs]

    # -- graph construction ----------------------------------------------

    def load(self) -> Graph:
        """Convert to a Graph following bottom/top blob topology
        (reference ``CaffeLoader.createCaffeGraph:267``)."""
        tops: Dict[str, ModuleNode] = {}   # blob name -> producing node
        inputs: List[ModuleNode] = []
        produced: List[str] = []           # blob names, production order
        last_prod: Dict[str, int] = {}     # blob -> layer index of producer
        last_cons: Dict[str, int] = {}     # blob -> layer index of consumer

        for name in self.net.input:
            node = ModuleNode(nn.Identity(name=name))
            tops[name] = node
            inputs.append(node)
        for idx, layer in enumerate(self.net.layer):
            if any(rule.phase == pb.TRAIN for rule in layer.include):
                # TRAIN-only layer: alias its tops to the bottom so TEST
                # consumers of an in-place top still resolve
                for top in layer.top:
                    if layer.bottom:
                        tops[top] = tops[layer.bottom[0]]
                continue
            if layer.type in ("Input", "Data"):
                # legacy DATA layers are the V1 ingest tier: each top
                # (data/label) becomes a graph input
                for top in layer.top:
                    node = ModuleNode(nn.Identity(name=f"{layer.name}_{top}"))
                    tops[top] = node
                    inputs.append(node)
                continue
            if layer.type == "Accuracy":
                # eval-only metric layer: no module, but its bottoms are
                # consumed (they must not dangle into spurious outputs)
                for b in layer.bottom:
                    last_cons[b] = idx
                continue
            if layer.type == "Split":
                # V1 explicit fan-out: all tops alias the bottom (and are
                # produced here, so a dangling branch can be an output)
                src = tops[layer.bottom[0]]
                last_cons[layer.bottom[0]] = idx
                for top in layer.top:
                    tops[top] = src
                    produced.append(top)
                    last_prod[top] = idx
                continue
            node = ModuleNode(self._convert(layer))
            bottoms = list(layer.bottom)
            if layer.type == "SoftmaxWithLoss" and len(bottoms) > 1:
                bottoms = bottoms[:1]       # drop the label bottom
            preds = [self._pred(tops, layer, i, bottoms[i])
                     for i in range(len(bottoms))]
            if preds:
                node.inputs(*preds)
            for b in layer.bottom:
                last_cons[b] = idx
            # the canonical pre-2014 train prototxt ends in a TOPLESS loss
            # layer; give it a synthetic top so the net keeps an output
            layer_tops = list(layer.top) or [layer.name]
            for top in layer_tops:
                tops[top] = node
                produced.append(top)
                last_prod[top] = idx

        if not inputs:
            raise ValueError("prototxt declares no inputs "
                             "(need input:/Input layers)")
        # outputs = dangling tops: a blob is an output when its final
        # producer is not followed by a consumer.  In-place layers
        # (bottom == top) consume and re-produce the same name at the same
        # index, so >= keeps a trailing in-place layer's blob alive while a
        # mid-chain one (consumed by a later layer) is dropped.
        out_nodes, seen = [], set()
        for name in produced:
            if name in seen:
                continue
            seen.add(name)
            if name in last_cons and last_prod[name] < last_cons[name]:
                continue
            out_nodes.append(tops[name])
        if not out_nodes:
            raise ValueError("prototxt has no output layer (every top is "
                             "consumed, or the net is input-only)")
        return Graph(inputs, out_nodes)

    def _pred(self, tops, layer, i: int,
              bottom: Optional[str] = None) -> ModuleNode:
        """Predecessor node for bottom i, inserting a scale node for
        Eltwise SUM coefficients (a - b imports as a + (-1)*b)."""
        node = tops[bottom if bottom is not None else layer.bottom[i]]
        if layer.type == "Eltwise":
            ep = layer.eltwise_param
            coeffs = list(ep.coeff)
            if coeffs and ep.operation == pb.EltwiseParameter.SUM:
                c = coeffs[i] if i < len(coeffs) else 1.0
                if c != 1.0:
                    scaled = ModuleNode(nn.MulConstant(
                        float(c), name=f"{layer.name}_coeff{i}"))
                    scaled.inputs(node)
                    return scaled
        return node

    # -- layer converters (reference Converter/LayerConverter) -----------

    def _convert(self, layer) -> Optional[nn.Module]:
        t = layer.type
        name = layer.name
        blobs = self.blobs.get(name, [])
        if t == "Convolution":
            cp = layer.convolution_param
            kh, kw, sh, sw, ph, pw = _conv_geom(cp)
            if any(d != 1 for d in cp.dilation):
                raise ValueError(f"{name}: dilated caffe conv unsupported")
            w = b = None
            n_in = None
            if blobs:
                w = blobs[0]                       # OIHW
                n_in = w.shape[1] * cp.group
                w = np.transpose(w, (2, 3, 1, 0))  # -> HWIO
                if cp.bias_term and len(blobs) > 1:
                    b = blobs[1].reshape(-1)
            if n_in is None:
                raise ValueError(
                    f"{name}: cannot infer input planes without a "
                    "caffemodel blob")
            return nn.SpatialConvolution(
                n_in, int(cp.num_output), kw, kh, sw, sh, pw, ph,
                n_group=int(cp.group), with_bias=bool(cp.bias_term),
                init_weight=w, init_bias=b, name=name)
        if t == "InnerProduct":
            ip = layer.inner_product_param
            if not blobs:
                raise ValueError(f"{name}: InnerProduct needs weights")
            w = blobs[0]                           # (out, in)
            if w.ndim == 4:
                # genuine V1-era caffemodels predate BlobShape and store IP
                # weights via legacy dims (1, 1, out, in)
                w = w.reshape(w.shape[-2], w.shape[-1])
            if ip.transpose:
                w = w.T
            b = blobs[1].reshape(-1) if (ip.bias_term and
                                         len(blobs) > 1) else None
            flat_in = int(w.shape[1])
            lin = nn.Linear(flat_in, int(ip.num_output),
                            with_bias=bool(ip.bias_term),
                            init_weight=np.ascontiguousarray(w.T),
                            init_bias=b, name=name)
            # caffe flattens (N, C, H, W) implicitly at axis 1
            seq = nn.Sequential(name=f"{name}_flatten")
            seq.add(nn.InferReshape([0, -1])).add(lin)
            return seq
        if t == "Pooling":
            pp = layer.pooling_param
            kh = int(pp.kernel_h or pp.kernel_size)
            kw = int(pp.kernel_w or pp.kernel_size)
            sh = int(pp.stride_h or pp.stride)
            sw = int(pp.stride_w or pp.stride)
            ph = int(pp.pad_h or pp.pad)
            pw = int(pp.pad_w or pp.pad)
            if pp.global_pooling:
                raise ValueError(f"{name}: global pooling unsupported")
            if pp.pool == pb.PoolingParameter.MAX:
                m = nn.SpatialMaxPooling(kw, kh, sw, sh, pw, ph, name=name)
            else:
                m = nn.SpatialAveragePooling(kw, kh, sw, sh, pw, ph,
                                             name=name)
            # caffe default is ceil-mode output sizing; round_mode: FLOOR
            # (BVLC PoolingParameter field 13) selects floor
            if pp.round_mode == pb.PoolingParameter.FLOOR:
                return m
            return m.ceil()
        if t == "ReLU":
            return nn.ReLU(name=name)
        if t == "TanH":
            return nn.Tanh(name=name)
        if t == "Sigmoid":
            return nn.Sigmoid(name=name)
        if t == "SoftmaxWithLoss":
            # inference view of the training loss head: channel softmax
            # over the prediction bottom (the label bottom was dropped)
            return _ChannelSoftMax(name=name)
        if t == "Softmax":
            axis = int(layer.softmax_param.axis) if layer.HasField(
                "softmax_param") else 1
            if axis == -1:
                return nn.SoftMax(name=name)    # last-axis (our exporter)
            if axis != 1:
                raise ValueError(f"{name}: Softmax axis {axis} unsupported")
            return _ChannelSoftMax(name=name)
        if t == "LRN":
            lp = layer.lrn_param
            if lp.norm_region == pb.LRNParameter.WITHIN_CHANNEL:
                if abs(float(lp.k) - 1.0) > 1e-9:
                    raise ValueError(
                        f"{name}: within-channel LRN with k={lp.k} "
                        "unsupported (k is fixed at 1)")
                return nn.SpatialWithinChannelLRN(
                    int(lp.local_size), float(lp.alpha), float(lp.beta),
                    name=name)
            return nn.SpatialCrossMapLRN(int(lp.local_size), float(lp.alpha),
                                         float(lp.beta), float(lp.k),
                                         name=name)
        if t == "Dropout":
            return nn.Dropout(float(layer.dropout_param.dropout_ratio),
                              name=name)
        if t == "Concat":
            axis = int(layer.concat_param.axis)
            return nn.JoinTable(axis + 1, name=name)   # 0-based -> 1-based
        if t == "Eltwise":
            ep = layer.eltwise_param
            if list(ep.coeff) and ep.operation != pb.EltwiseParameter.SUM:
                raise ValueError(f"{name}: Eltwise coeff is only defined "
                                 "for SUM")
            if ep.operation == pb.EltwiseParameter.SUM:
                return nn.CAddTable(name=name)
            if ep.operation == pb.EltwiseParameter.MAX:
                return nn.CMaxTable(name=name)
            return nn.CMulTable(name=name)
        if t == "Flatten":
            return nn.InferReshape([0, -1], name=name)
        raise ValueError(f"unsupported caffe layer type {t!r} at {name!r} "
                         "(reference CaffeLoader converter not implemented)")


def load_caffe(def_path: str, model_path: Optional[str] = None) -> Graph:
    """(reference ``Module.loadCaffe``)."""
    return CaffeLoader(def_path, model_path).load()
