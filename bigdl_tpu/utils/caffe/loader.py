"""Caffe model importer.

Reference equivalent: ``utils/caffe/CaffeLoader.scala:56,267`` — parse a
prototxt (text) + caffemodel (binary) pair, convert layer-by-layer through
registered converters into a Graph, and copy the trained blobs.

The protobuf schema is a trimmed transcription of BVLC caffe.proto with the
original field numbers (``caffe_minimal.proto``; the reference vendors the
generated ``caffe/Caffe.java``).  Caffe's NCHW activations and OIHW conv
kernels map onto the native layers via one transpose to HWIO.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.graph import Graph, ModuleNode
from bigdl_tpu.utils.caffe import caffe_minimal_pb2 as pb


def _blob_array(blob) -> np.ndarray:
    data = np.asarray(blob.data, dtype=np.float32)
    if blob.HasField("shape"):
        return data.reshape(tuple(blob.shape.dim))
    dims = [d for d in (blob.num, blob.channels, blob.height, blob.width)
            if d > 0]
    return data.reshape(tuple(dims) if dims else (-1,))


class _ChannelSoftMax(nn.Module):
    """Softmax over axis 1 — caffe's default normalization axis for any
    blob rank (our ``nn.SoftMax`` normalizes the last axis, which only
    coincides for 2-D blobs)."""

    def apply(self, params, input, state, training=False, rng=None):
        import jax
        return jax.nn.softmax(input, axis=1), state


def _conv_geom(cp):
    kh = cp.kernel_h if cp.HasField("kernel_h") else (
        cp.kernel_size[0] if cp.kernel_size else 1)
    kw = cp.kernel_w if cp.HasField("kernel_w") else (
        cp.kernel_size[-1] if cp.kernel_size else 1)
    sh = cp.stride_h if cp.HasField("stride_h") else (
        cp.stride[0] if cp.stride else 1)
    sw = cp.stride_w if cp.HasField("stride_w") else (
        cp.stride[-1] if cp.stride else 1)
    ph = cp.pad_h if cp.HasField("pad_h") else (cp.pad[0] if cp.pad else 0)
    pw = cp.pad_w if cp.HasField("pad_w") else (cp.pad[-1] if cp.pad else 0)
    return kh, kw, sh, sw, ph, pw


class CaffeLoader:
    """(reference ``CaffeLoader.scala:56``)."""

    def __init__(self, def_path: str, model_path: Optional[str] = None):
        from google.protobuf import text_format
        self.net = pb.NetParameter()
        with open(def_path) as f:
            text_format.Merge(f.read(), self.net)
        self.blobs: Dict[str, List[np.ndarray]] = {}
        if model_path:
            weights = pb.NetParameter()
            with open(model_path, "rb") as f:
                weights.ParseFromString(f.read())
            for layer in weights.layer:
                if layer.blobs:
                    self.blobs[layer.name] = [_blob_array(b)
                                              for b in layer.blobs]

    # -- graph construction ----------------------------------------------

    def load(self) -> Graph:
        """Convert to a Graph following bottom/top blob topology
        (reference ``CaffeLoader.createCaffeGraph:267``)."""
        tops: Dict[str, ModuleNode] = {}   # blob name -> producing node
        inputs: List[ModuleNode] = []
        produced: List[str] = []           # blob names, production order
        last_prod: Dict[str, int] = {}     # blob -> layer index of producer
        last_cons: Dict[str, int] = {}     # blob -> layer index of consumer

        for name in self.net.input:
            node = ModuleNode(nn.Identity(name=name))
            tops[name] = node
            inputs.append(node)
        for idx, layer in enumerate(self.net.layer):
            if any(rule.phase == pb.TRAIN for rule in layer.include):
                # TRAIN-only layer: alias its tops to the bottom so TEST
                # consumers of an in-place top still resolve
                for top in layer.top:
                    if layer.bottom:
                        tops[top] = tops[layer.bottom[0]]
                continue
            if layer.type == "Input":
                node = ModuleNode(nn.Identity(name=layer.name))
                for top in layer.top:
                    tops[top] = node
                inputs.append(node)
                continue
            node = ModuleNode(self._convert(layer))
            preds = [self._pred(tops, layer, i)
                     for i in range(len(layer.bottom))]
            if preds:
                node.inputs(*preds)
            for b in layer.bottom:
                last_cons[b] = idx
            for top in layer.top:
                tops[top] = node
                produced.append(top)
                last_prod[top] = idx

        if not inputs:
            raise ValueError("prototxt declares no inputs "
                             "(need input:/Input layers)")
        # outputs = dangling tops: a blob is an output when its final
        # producer is not followed by a consumer.  In-place layers
        # (bottom == top) consume and re-produce the same name at the same
        # index, so >= keeps a trailing in-place layer's blob alive while a
        # mid-chain one (consumed by a later layer) is dropped.
        out_nodes, seen = [], set()
        for name in produced:
            if name in seen:
                continue
            seen.add(name)
            if name in last_cons and last_prod[name] < last_cons[name]:
                continue
            out_nodes.append(tops[name])
        if not out_nodes:
            raise ValueError("prototxt has no output layer (every top is "
                             "consumed, or the net is input-only)")
        return Graph(inputs, out_nodes)

    def _pred(self, tops, layer, i: int) -> ModuleNode:
        """Predecessor node for bottom i, inserting a scale node for
        Eltwise SUM coefficients (a - b imports as a + (-1)*b)."""
        node = tops[layer.bottom[i]]
        if layer.type == "Eltwise":
            ep = layer.eltwise_param
            coeffs = list(ep.coeff)
            if coeffs and ep.operation == pb.EltwiseParameter.SUM:
                c = coeffs[i] if i < len(coeffs) else 1.0
                if c != 1.0:
                    scaled = ModuleNode(nn.MulConstant(
                        float(c), name=f"{layer.name}_coeff{i}"))
                    scaled.inputs(node)
                    return scaled
        return node

    # -- layer converters (reference Converter/LayerConverter) -----------

    def _convert(self, layer) -> Optional[nn.Module]:
        t = layer.type
        name = layer.name
        blobs = self.blobs.get(name, [])
        if t == "Convolution":
            cp = layer.convolution_param
            kh, kw, sh, sw, ph, pw = _conv_geom(cp)
            if any(d != 1 for d in cp.dilation):
                raise ValueError(f"{name}: dilated caffe conv unsupported")
            w = b = None
            n_in = None
            if blobs:
                w = blobs[0]                       # OIHW
                n_in = w.shape[1] * cp.group
                w = np.transpose(w, (2, 3, 1, 0))  # -> HWIO
                if cp.bias_term and len(blobs) > 1:
                    b = blobs[1].reshape(-1)
            if n_in is None:
                raise ValueError(
                    f"{name}: cannot infer input planes without a "
                    "caffemodel blob")
            return nn.SpatialConvolution(
                n_in, int(cp.num_output), kw, kh, sw, sh, pw, ph,
                n_group=int(cp.group), with_bias=bool(cp.bias_term),
                init_weight=w, init_bias=b, name=name)
        if t == "InnerProduct":
            ip = layer.inner_product_param
            if not blobs:
                raise ValueError(f"{name}: InnerProduct needs weights")
            w = blobs[0]                           # (out, in)
            if ip.transpose:
                w = w.T
            b = blobs[1].reshape(-1) if (ip.bias_term and
                                         len(blobs) > 1) else None
            flat_in = int(w.shape[1])
            lin = nn.Linear(flat_in, int(ip.num_output),
                            with_bias=bool(ip.bias_term),
                            init_weight=np.ascontiguousarray(w.T),
                            init_bias=b, name=name)
            # caffe flattens (N, C, H, W) implicitly at axis 1
            seq = nn.Sequential(name=f"{name}_flatten")
            seq.add(nn.InferReshape([0, -1])).add(lin)
            return seq
        if t == "Pooling":
            pp = layer.pooling_param
            kh = int(pp.kernel_h or pp.kernel_size)
            kw = int(pp.kernel_w or pp.kernel_size)
            sh = int(pp.stride_h or pp.stride)
            sw = int(pp.stride_w or pp.stride)
            ph = int(pp.pad_h or pp.pad)
            pw = int(pp.pad_w or pp.pad)
            if pp.global_pooling:
                raise ValueError(f"{name}: global pooling unsupported")
            if pp.pool == pb.PoolingParameter.MAX:
                m = nn.SpatialMaxPooling(kw, kh, sw, sh, pw, ph, name=name)
            else:
                m = nn.SpatialAveragePooling(kw, kh, sw, sh, pw, ph,
                                             name=name)
            # caffe default is ceil-mode output sizing; round_mode: FLOOR
            # (BVLC PoolingParameter field 13) selects floor
            if pp.round_mode == pb.PoolingParameter.FLOOR:
                return m
            return m.ceil()
        if t == "ReLU":
            return nn.ReLU(name=name)
        if t == "TanH":
            return nn.Tanh(name=name)
        if t == "Sigmoid":
            return nn.Sigmoid(name=name)
        if t == "Softmax":
            axis = int(layer.softmax_param.axis) if layer.HasField(
                "softmax_param") else 1
            if axis == -1:
                return nn.SoftMax(name=name)    # last-axis (our exporter)
            if axis != 1:
                raise ValueError(f"{name}: Softmax axis {axis} unsupported")
            return _ChannelSoftMax(name=name)
        if t == "LRN":
            lp = layer.lrn_param
            if lp.norm_region == pb.LRNParameter.WITHIN_CHANNEL:
                if abs(float(lp.k) - 1.0) > 1e-9:
                    raise ValueError(
                        f"{name}: within-channel LRN with k={lp.k} "
                        "unsupported (k is fixed at 1)")
                return nn.SpatialWithinChannelLRN(
                    int(lp.local_size), float(lp.alpha), float(lp.beta),
                    name=name)
            return nn.SpatialCrossMapLRN(int(lp.local_size), float(lp.alpha),
                                         float(lp.beta), float(lp.k),
                                         name=name)
        if t == "Dropout":
            return nn.Dropout(float(layer.dropout_param.dropout_ratio),
                              name=name)
        if t == "Concat":
            axis = int(layer.concat_param.axis)
            return nn.JoinTable(axis + 1, name=name)   # 0-based -> 1-based
        if t == "Eltwise":
            ep = layer.eltwise_param
            if list(ep.coeff) and ep.operation != pb.EltwiseParameter.SUM:
                raise ValueError(f"{name}: Eltwise coeff is only defined "
                                 "for SUM")
            if ep.operation == pb.EltwiseParameter.SUM:
                return nn.CAddTable(name=name)
            if ep.operation == pb.EltwiseParameter.MAX:
                return nn.CMaxTable(name=name)
            return nn.CMulTable(name=name)
        if t == "Flatten":
            return nn.InferReshape([0, -1], name=name)
        raise ValueError(f"unsupported caffe layer type {t!r} at {name!r} "
                         "(reference CaffeLoader converter not implemented)")


def load_caffe(def_path: str, model_path: Optional[str] = None) -> Graph:
    """(reference ``Module.loadCaffe``)."""
    return CaffeLoader(def_path, model_path).load()
