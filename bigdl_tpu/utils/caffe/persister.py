"""Caffe model exporter.

Reference equivalent: ``utils/caffe/CaffePersister.scala`` — walk the model
and emit a prototxt (structure) + caffemodel (structure + trained blobs)
pair for the supported layer subset.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.utils.caffe import caffe_minimal_pb2 as pb


def _blob(arr: np.ndarray):
    b = pb.BlobProto()
    b.shape.dim.extend(int(s) for s in arr.shape)
    b.data.extend(float(v) for v in np.asarray(arr, np.float32).ravel())
    return b


def _flatten_chain(model) -> List[nn.Module]:
    if isinstance(model, nn.Sequential):
        out = []
        for c in model.children:
            out.extend(_flatten_chain(c))
        return out
    return [model]


def save(model, def_path: str, model_path: str,
         input_shape: Optional[List[int]] = None) -> None:
    """Export a Sequential chain to prototxt + caffemodel
    (reference ``CaffePersister.persist``)."""
    from google.protobuf import text_format

    model._ensure_init()
    net = pb.NetParameter()
    net.name = getattr(model, "name", "bigdl_tpu")
    if input_shape is not None:
        net.input.append("data")
        shape = net.input_shape.add()
        shape.dim.extend(int(s) for s in input_shape)

    bottom = "data"
    for i, m in enumerate(_flatten_chain(model)):
        layer = net.layer.add()
        layer.name = m.name
        layer.bottom.append(bottom)
        top = f"blob{i}"
        layer.top.append(top)
        bottom = top
        _fill(layer, m)
        if isinstance(m, nn.BatchNormalization) and m.affine:
            # caffe factors affine BN into a BatchNorm + Scale pair
            scale = net.layer.add()
            scale.name = f"{m.name}_scale"
            scale.type = "Scale"
            scale.bottom.append(top)
            stop = f"{top}_scale"
            scale.top.append(stop)
            bottom = stop
            scale.scale_param.bias_term = True
            p = m.params
            scale.blobs.append(_blob(np.asarray(p["weight"])))
            scale.blobs.append(_blob(np.asarray(p["bias"])))

    with open(def_path, "w") as f:
        # blobs stay out of the prototxt (structure only)
        structure = pb.NetParameter()
        structure.CopyFrom(net)
        for layer in structure.layer:
            del layer.blobs[:]
        f.write(text_format.MessageToString(structure))
    with open(model_path, "wb") as f:
        f.write(net.SerializeToString())


def _fill(layer, m) -> None:
    p = m.params if m._params is not None else {}
    if isinstance(m, nn.SpatialConvolution):
        layer.type = "Convolution"
        cp = layer.convolution_param
        cp.num_output = m.n_output_plane
        cp.bias_term = m.with_bias
        cp.kernel_h, cp.kernel_w = m.kernel_h, m.kernel_w
        cp.stride_h, cp.stride_w = m.stride_h, m.stride_w
        if m.pad_w == -1 or m.pad_h == -1:
            raise ValueError(f"{m.name}: caffe has no SAME padding")
        cp.pad_h, cp.pad_w = m.pad_h, m.pad_w
        cp.group = m.n_group
        w = np.transpose(np.asarray(p["weight"]), (3, 2, 0, 1))  # HWIO->OIHW
        layer.blobs.append(_blob(w))
        if m.with_bias:
            layer.blobs.append(_blob(np.asarray(p["bias"])))
    elif isinstance(m, nn.Linear):
        layer.type = "InnerProduct"
        ip = layer.inner_product_param
        ip.num_output = m.output_size
        ip.bias_term = m.with_bias
        layer.blobs.append(_blob(np.asarray(p["weight"]).T))  # -> (out, in)
        if m.with_bias:
            layer.blobs.append(_blob(np.asarray(p["bias"])))
    elif isinstance(m, (nn.SpatialMaxPooling, nn.SpatialAveragePooling)):
        layer.type = "Pooling"
        pp = layer.pooling_param
        pp.pool = (pb.PoolingParameter.MAX
                   if isinstance(m, nn.SpatialMaxPooling)
                   else pb.PoolingParameter.AVE)
        pp.kernel_h, pp.kernel_w = m.kh, m.kw
        pp.stride_h, pp.stride_w = m.dh, m.dw
        pp.pad_h, pp.pad_w = m.pad_h, m.pad_w
        pp.round_mode = (pb.PoolingParameter.CEIL if m.ceil_mode
                         else pb.PoolingParameter.FLOOR)
    elif isinstance(m, nn.ReLU):
        layer.type = "ReLU"
    elif isinstance(m, nn.Tanh):
        layer.type = "TanH"
    elif isinstance(m, nn.Sigmoid):
        layer.type = "Sigmoid"
    elif isinstance(m, nn.SoftMax):
        layer.type = "Softmax"
        # our SoftMax normalizes the LAST axis; record that explicitly so
        # the round-trip (and axis-aware caffe) keeps the semantics
        layer.softmax_param.axis = -1
    elif type(m).__name__ == "_ChannelSoftMax":
        layer.type = "Softmax"      # caffe default axis 1 == this module
    elif isinstance(m, nn.SpatialCrossMapLRN):
        layer.type = "LRN"
        lp = layer.lrn_param
        lp.local_size = m.size
        lp.alpha, lp.beta, lp.k = m.alpha, m.beta, m.k
    elif isinstance(m, nn.Dropout):
        layer.type = "Dropout"
        layer.dropout_param.dropout_ratio = m.p
    elif isinstance(m, nn.BatchNormalization):
        # stats half only; save() appends the Scale half when affine
        layer.type = "BatchNorm"
        layer.batch_norm_param.eps = m.eps
        st = m.state
        layer.blobs.append(_blob(np.asarray(st["running_mean"])))
        layer.blobs.append(_blob(np.asarray(st["running_var"])))
        layer.blobs.append(_blob(np.ones((1,), np.float32)))  # scale factor
    elif isinstance(m, nn.Scale):
        layer.type = "Scale"
        layer.scale_param.bias_term = True
        layer.blobs.append(_blob(np.asarray(p["weight"])))
        layer.blobs.append(_blob(np.asarray(p["bias"])))
    elif isinstance(m, nn.Add):
        layer.type = "Bias"
        layer.blobs.append(_blob(np.asarray(p["bias"])))
    elif isinstance(m, nn.PReLU):
        layer.type = "PReLU"
        layer.prelu_param.channel_shared = m.n_output_plane == 0
        layer.blobs.append(_blob(np.asarray(p["weight"])))
    elif isinstance(m, nn.ELU):
        layer.type = "ELU"
        layer.elu_param.alpha = m.alpha
    elif isinstance(m, nn.Power):
        layer.type = "Power"
        pw = layer.power_param
        pw.power, pw.scale, pw.shift = m.power, m.scale, m.shift
    elif isinstance(m, nn.Log):
        layer.type = "Log"
    elif isinstance(m, nn.Exp):
        layer.type = "Exp"
    elif isinstance(m, nn.Abs):
        layer.type = "AbsVal"
    elif isinstance(m, nn.Threshold):
        layer.type = "Threshold"
        layer.threshold_param.threshold = m.th
    elif isinstance(m, nn.Replicate):
        layer.type = "Tile"
        layer.tile_param.axis = m.dim
        layer.tile_param.tiles = m.n_features
    elif isinstance(m, nn.Recurrent):
        layer.type = "Recurrent"
    elif isinstance(m, (nn.Reshape, nn.View, nn.InferReshape)):
        size = (m.size if not isinstance(m, nn.View) else m.sizes)
        if len([s for s in size if s != 0]) == 1:
            # per-sample flatten has a dedicated caffe type
            layer.type = "Flatten"
        elif isinstance(m, nn.InferReshape):
            layer.type = "Reshape"
            layer.reshape_param.shape.dim.extend(int(s) for s in size)
        else:
            raise ValueError(
                f"{m.name}: reshape to {tuple(size)} has no caffe mapping "
                "(InferReshape exports as Reshape; View/Reshape only as "
                "per-sample Flatten)")
    elif isinstance(m, nn.Identity):
        layer.type = "Input"
    else:
        raise ValueError(
            f"layer {type(m).__name__} has no caffe export mapping "
            "(reference CaffePersister scope)")
