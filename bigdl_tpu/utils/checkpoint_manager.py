"""Verified, committed, garbage-collected training snapshots.

The reference's retry-from-snapshot loop (``optim/DistriOptimizer.scala:
750-816``) assumes every ``model.N``/``optimMethod.N`` pair on disk is
loadable — a crash between the two saves, or one torn/corrupt object,
turns recovery itself into the fatal error.  Production checkpoint
managers (Orbax-style, as used by large JAX training systems) instead
treat a snapshot as a *unit* that is only eligible for restore once it is
proven complete:

- every payload is written with a CRC checksum recorded in a
  per-snapshot ``manifest.N`` (the seqfile/TFRecord CRC idiom,
  ``visualization/crc32c.py``).  The payload algorithm is
  CRC32C when a native implementation is installed and C-speed
  ``zlib.crc32`` otherwise (the pure-Python CRC32C table walk runs at
  ~2 MB/s — unusable against multi-GB snapshots); the manifest records
  which (``algo``) so snapshots verify across hosts.  The manifest↔commit
  cross-check itself stays CRC32C: the manifest is tiny;
- a ``commit.N`` marker is written LAST — its presence is the atomic
  "this snapshot is whole" bit;
- restore scans newest → oldest and takes the first snapshot that is
  committed AND checksum-clean, so one torn write can never brick
  recovery;
- ``keep_last=N`` garbage-collects older committed snapshots (the commit
  marker is removed first, so a crash mid-GC leaves an uncommitted —
  ignored — snapshot, never a half-deleted committed one);
- writes optionally happen on a background thread (async checkpointing):
  the train step pays only the device→host fetch + in-memory
  serialization; checksumming and (possibly remote) IO run off the
  critical path, with writer errors re-raised at the next save and at
  exit.

Snapshots written by older releases (bare ``model.N``/``optimMethod.N``
pairs, no manifest) stay restorable: they are accepted as *legacy*
candidates when the pair is complete, and the load-time fallback walks to
the next-older snapshot if unpickling fails.
"""

from __future__ import annotations

import atexit
import json
import logging
import pickle
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from bigdl_tpu import telemetry
from bigdl_tpu.visualization.crc32c import crc32c

logger = logging.getLogger("bigdl_tpu")

#: manifest schema: 2 added the saved-topology record (``topology`` key,
#: ``utils/elastic.py``); 3 added per-payload SEMANTIC fingerprints
#: (``fingerprint`` key per file — ``integrity.host_fingerprint`` over
#: the live state BEFORE serialization, recomputed at restore so
#: corruption between compute and pickle — which the payload CRC can NOT
#: see, being taken over the already-corrupt bytes — refuses the
#: snapshot).  Version-1/2 manifests (and pre-manifest legacy pairs)
#: stay restorable — their files simply carry no fingerprint to check.
#: A manifest from a NEWER release than this reader fails restore with a
#: structured :class:`SnapshotSchemaError`, never an unpickle crash.
MANIFEST_VERSION = 3


def _native_crc32c():
    """A C-speed CRC32C implementation, or None."""
    try:
        import google_crc32c
        return lambda data: int.from_bytes(
            google_crc32c.Checksum(data).digest(), "big")
    except ImportError:
        pass
    try:
        import crc32c as _c
        return _c.crc32c
    except ImportError:
        return None


_CRC32C_FAST = _native_crc32c()


def payload_checksum(data: bytes) -> Tuple[str, int]:
    """(algo, value) for a snapshot payload: CRC32C when a native
    implementation exists, else zlib's C-speed CRC32 — the pure-Python
    CRC32C table walk would hold the writer (and a sync save, the train
    loop) hostage for seconds per 100 MB."""
    if _CRC32C_FAST is not None:
        return "crc32c", int(_CRC32C_FAST(data))
    import zlib
    return "crc32", zlib.crc32(data) & 0xFFFFFFFF


def checksum_by_algo(algo: str, data: bytes) -> int:
    """Recompute a payload checksum under the manifest's recorded
    algorithm — snapshots must verify on hosts whose installed CRC
    libraries differ from the writer's."""
    if algo == "crc32c":
        if _CRC32C_FAST is not None:
            return int(_CRC32C_FAST(data))
        return crc32c(data)     # pure-python fallback: restore-time only
    if algo == "crc32":
        import zlib
        return zlib.crc32(data) & 0xFFFFFFFF
    raise SnapshotCorruptError(f"unknown manifest checksum algo {algo!r}")


class SnapshotWriteError(RuntimeError):
    """A (possibly deferred, async) snapshot write failed."""


class SnapshotCorruptError(RuntimeError):
    """A snapshot payload failed its manifest checksum."""


class SnapshotSchemaError(RuntimeError):
    """A snapshot manifest declares a schema newer than this reader —
    restoring it would mean unpickling payloads whose layout this
    release cannot vouch for.  Raised with the versions named, instead
    of whatever exception the unpickler would eventually hit."""

    def __init__(self, neval: int, found: Any):
        self.neval = neval
        self.found = found
        super().__init__(
            f"snapshot {neval}: manifest schema version {found!r} is newer "
            f"than this release understands (<= {MANIFEST_VERSION}) — "
            "restore it with the release that wrote it")


def _capture(model, optim, neval: int
             ) -> Tuple[Dict[str, bytes], Dict[str, str]]:
    """Serialize the live model/optim into detached byte payloads, on the
    caller's thread; returns ``(blobs, fingerprints)``.

    Two hazards force the capture to be synchronous: (1) the jitted step
    DONATES its carries, so a device array read after the next dispatch
    may be deleted — pickling (whose ``__getstate__`` fetches every leaf
    to host) must complete before the loop moves on; (2) the driver
    mutates the live shells between trigger points (``publish`` reassigns
    param trees, ``step_done`` bumps ``state`` counters), so a background
    pickle of the live objects could observe a torn snapshot.  Bytes are
    unambiguously detached; what moves to the writer thread is the part
    with unbounded latency — checksumming and (possibly remote) IO.

    The semantic fingerprint is taken from the clean serialization of
    the TRUE state — recomputing it on an unpickled copy, because the
    restore-time walk sees the pickle-NORMALIZED object graph (shared
    parameter subtrees come back as per-module copies, ``__setstate__``
    may rebuild dicts in a different order) and the two fingerprints
    must be comparable bit-for-bit.  The ``corrupt_state_before_save``
    chaos hook sits AFTER the fingerprint and re-serializes, modelling
    in-RAM rot between state capture and write — which the payload CRC
    is blind to (it checksums the already-corrupt bytes); only the
    fingerprint recomputation at restore refuses such a snapshot."""
    from bigdl_tpu.integrity import fingerprint_key, host_fingerprint
    from bigdl_tpu.utils import chaos
    with telemetry.span("checkpoint/capture", neval=neval):
        blobs: Dict[str, bytes] = {}
        fps: Dict[str, str] = {}
        for name, obj in ((f"model.{neval}", model),
                          (f"optimMethod.{neval}", optim)):
            data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
            fps[name] = fingerprint_key(
                host_fingerprint(pickle.loads(data)))
            if chaos.active():
                corrupted = chaos.corrupt_state_before_save(obj)
                if corrupted is not obj:
                    data = pickle.dumps(
                        corrupted, protocol=pickle.HIGHEST_PROTOCOL)
            blobs[name] = data
        return blobs, fps


class _AsyncWriter:
    """One background write in flight at a time (Orbax's
    ``wait_until_finished`` discipline): ``submit`` joins the previous
    job first, so writer errors surface at the NEXT save, and memory for
    detached snapshots is bounded to one."""

    def __init__(self):
        from bigdl_tpu import analysis
        self._thread: Optional[threading.Thread] = None
        self._lock = analysis.make_lock("checkpoint.writer")
        self._error: Optional[BaseException] = None    # guarded-by: _lock

    def submit(self, job) -> None:
        self.join()

        def run():
            try:
                job()
            except BaseException as e:  # noqa: BLE001 — re-raised at join
                with self._lock:
                    self._error = e

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="bigdl-ckpt-writer")
        self._thread.start()

    def join(self, raise_errors: bool = True,
             timeout: Optional[float] = None) -> None:
        t = self._thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                # bounded drain gave up (exit path): leave the thread
                # for a later join — do NOT report a deferred error that
                # hasn't happened yet
                logger.warning(
                    "checkpoint writer still running after %.1fs drain "
                    "timeout — abandoning the wait", timeout)
                return
            self._thread = None
        with self._lock:
            err = self._error
            self._error = None
        if err is not None:
            if raise_errors:
                raise SnapshotWriteError(
                    "background checkpoint write failed") from err
            logger.warning("background checkpoint write failed: %r", err)


#: async-writing managers still alive — drained once more at interpreter
#: shutdown (see ``_register_for_exit_drain``)
_LIVE_ASYNC_MANAGERS: "weakref.WeakSet" = weakref.WeakSet()
_EXIT_HOOK_INSTALLED = [False]


def drain_all_async_writers() -> None:
    """Join every live async checkpoint writer (errors logged, not
    raised — shutdown must proceed).  Registered with ``atexit`` by the
    first async manager and also invoked by the elastic preemption
    drain, so a snapshot submitted moments before SIGTERM/exit always
    reaches its commit marker.  The join is BOUNDED by
    ``bigdl.elastic.gracePeriod``: a wedged remote write (hung fsspec
    network call) must not block interpreter exit forever — before the
    exit hook existed such threads were simply abandoned, and past the
    bound they still are."""
    from bigdl_tpu.utils import elastic
    timeout = elastic.grace_period()
    for mgr in list(_LIVE_ASYNC_MANAGERS):
        try:
            mgr.join(raise_errors=False, timeout=timeout)
        except Exception as e:  # pragma: no cover - defensive shutdown
            logger.warning("checkpoint writer drain at exit failed: %r", e)


def _register_for_exit_drain(manager: "CheckpointManager") -> None:
    _LIVE_ASYNC_MANAGERS.add(manager)
    if not _EXIT_HOOK_INSTALLED[0]:
        _EXIT_HOOK_INSTALLED[0] = True
        atexit.register(drain_all_async_writers)


class CheckpointManager:
    """The snapshot store for one checkpoint directory (local or any
    fsspec scheme — ``hdfs://``, ``s3://``, ``memory://``, …)."""

    #: seconds a ``.tmp_bigdl`` temp must sit untouched before the sweep
    #: may reclaim it (see ``Checkpoint.TEMP_SWEEP_AGE_S``).
    TEMP_SWEEP_AGE_S = 3600.0

    def __init__(self, path: str, keep_last: Optional[int] = None,
                 async_write: Optional[bool] = None,
                 overwrite: bool = True):
        from bigdl_tpu.utils import config
        self.path = path
        self.overwrite = overwrite
        self.keep_last = (keep_last if keep_last is not None
                          else config.get_int("bigdl.checkpoint.keepLast", 0))
        self.async_write = (async_write if async_write is not None else
                            config.get_bool("bigdl.checkpoint.asyncWrite",
                                            False))
        self._writer = _AsyncWriter() if self.async_write else None
        #: disk-full degradation: once storage is exhausted (and an
        #: emergency oldest-first GC could not free enough), snapshots
        #: are kept in host memory only — newest one, restorable — and
        #: no further disk writes are attempted.  Both fields are
        #: written by the async writer thread AND read/written from the
        #: submitting thread, so they share a state lock.
        from bigdl_tpu import analysis
        self._state_lock = analysis.make_lock("checkpoint.state")
        self._storage_degraded = False                            # guarded-by: _state_lock
        self._memory_snapshot: Optional[Dict[str, Any]] = None    # guarded-by: _state_lock
        #: watch_latest() poll cache: directory mtime at the last scan,
        #: the answer it produced, and the snapshots already
        #: shallow-verified (so an unstable-mtime window re-lists names
        #: but never re-reads manifests)
        self._watch_mtime: Optional[float] = None
        self._watch_latest: Optional[int] = None
        self._watch_scanned = False
        self._watch_verified: set = set()
        #: manifest of the snapshot load_latest most recently restored
        self.last_loaded_manifest: Optional[Dict[str, Any]] = None
        #: topology decision of that load: "same", "reshard", or None
        #: (nothing loaded yet) — restore paths gate the reshard timing
        #: on it, so a same-topology retry is never reported as one
        self.last_restore_mode: Optional[str] = None
        if self._writer is not None:
            # interpreter-shutdown flush: the writer thread is a daemon,
            # so without this a clean exit (or an un-drained SIGINT path)
            # would strand the newest snapshot in the queue behind a
            # stale on-disk one
            _register_for_exit_drain(self)

    # ---- save -----------------------------------------------------------

    def save(self, model, optim, neval: int,
             topology: Optional[Dict[str, Any]] = None) -> None:
        """Write snapshot ``neval`` as a verified unit.  Synchronous mode
        blocks until the commit marker lands; async mode blocks only for
        the host fetch + in-memory serialization (and for a still-in-flight
        PREVIOUS write, whose errors re-raise here) — directory creation
        and the orphan-temp sweep are filesystem round-trips and belong
        on the writer thread.

        ``topology`` (``elastic.describe_topology``) records the saving
        mesh in the manifest so a restore onto a different device count
        can reshard the ZeRO-1 slots — or refuse with the mismatch
        named — instead of discovering the change as a shape error."""
        blobs, fps = _capture(model, optim, neval)
        if self._writer is not None:
            self._writer.submit(
                lambda: self._write_snapshot(blobs, neval, topology, fps))
        else:
            self._write_snapshot(blobs, neval, topology, fps)

    def _write_snapshot(self, blobs: Dict[str, bytes], neval: int,
                        topology: Optional[Dict[str, Any]] = None,
                        fps: Optional[Dict[str, str]] = None) -> None:
        with telemetry.span("checkpoint/write", neval=neval):
            self._write_snapshot_inner(blobs, neval, topology, fps)

    def _write_snapshot_inner(self, blobs: Dict[str, bytes], neval: int,
                              topology: Optional[Dict[str, Any]] = None,
                              fps: Optional[Dict[str, str]] = None
                              ) -> None:
        from bigdl_tpu.resources.errors import StorageExhaustedError
        if self._storage_degraded:
            self._keep_memory_snapshot(blobs, neval, topology, fps)
            return
        try:
            self._write_snapshot_files(blobs, neval, topology, fps)
            return
        except StorageExhaustedError as e:
            # the disk is full mid-save: free space oldest-first beyond
            # keep_last and retry ONCE — retention is exactly the state
            # the run can afford to lose
            if self._emergency_gc():
                try:
                    self._write_snapshot_files(blobs, neval, topology, fps)
                    logger.warning(
                        "checkpoint storage exhausted at snapshot %d — "
                        "emergency oldest-first GC freed space and the "
                        "save landed", neval)
                    return
                except StorageExhaustedError as e2:
                    e = e2
            # no space to be found: degrade to in-memory-only snapshots
            # (one warning + Resources/storage_degraded) — training NEVER
            # crashes on a full disk
            with self._state_lock:
                self._storage_degraded = True
            from bigdl_tpu.resources import storage as _rstorage
            _rstorage.note_degraded("checkpoints", e)
            self._keep_memory_snapshot(blobs, neval, topology, fps)

    def _keep_memory_snapshot(self, blobs: Dict[str, bytes], neval: int,
                              topology: Optional[Dict[str, Any]],
                              fps: Optional[Dict[str, str]]) -> None:
        """Degraded mode: retain the newest snapshot as detached bytes in
        host RAM (bounded to ONE — the blobs were already captured, so
        this costs no extra serialization work)."""
        with self._state_lock:
            self._memory_snapshot = {
                "blobs": blobs, "neval": int(neval), "topology": topology,
                "fps": dict(fps or {}),
            }
        telemetry.counter(
            "Resources/memory_snapshots",
            help="snapshots retained in RAM only (disk full)").inc()

    def _emergency_gc(self) -> bool:
        """Oldest-first deletion beyond ``keep_last`` (at least the
        newest snapshot is always kept), regardless of whether retention
        was configured — run only on storage exhaustion.  True when
        anything was removed (worth retrying the save)."""
        from bigdl_tpu.utils import file_io
        keep = max(1, self.keep_last)
        victims = self.candidates()[keep:]
        removed = False
        for n, has_manifest in reversed(victims):     # oldest first
            names = ((f"commit.{n}", f"model.{n}", f"optimMethod.{n}",
                      f"manifest.{n}") if has_manifest else
                     (f"model.{n}", f"optimMethod.{n}"))
            for name in names:      # commit first: never a committed
                try:                # half-snapshot, even mid-crash
                    file_io.remove(file_io.join(self.path, name))
                    removed = True
                except Exception as e:
                    logger.warning(
                        "emergency checkpoint GC could not remove %s: %r",
                        name, e)
        if removed:
            telemetry.counter(
                "Resources/emergency_gc",
                help="emergency oldest-first checkpoint GCs on "
                     "storage exhaustion").inc()
        return removed

    def _write_snapshot_files(self, blobs: Dict[str, bytes], neval: int,
                              topology: Optional[Dict[str, Any]] = None,
                              fps: Optional[Dict[str, str]] = None
                              ) -> None:
        from bigdl_tpu.utils import file_io
        file_io.makedirs(self.path)
        self._sweep_orphan_temps()
        algo = None
        files = {}
        for name, data in blobs.items():
            algo, value = payload_checksum(data)
            files[name] = {"checksum": value, "bytes": len(data)}
            if fps and name in fps:
                files[name]["fingerprint"] = fps[name]
        manifest = {
            "version": MANIFEST_VERSION,
            "neval": int(neval),
            "algo": algo,
            "files": files,
        }
        if topology is not None:
            manifest["topology"] = topology
        for name, data in blobs.items():
            file_io.write_bytes(file_io.join(self.path, name), data,
                                self.overwrite)
        mbytes = json.dumps(manifest, sort_keys=True).encode("utf-8")
        file_io.write_bytes(file_io.join(self.path, f"manifest.{neval}"),
                            mbytes, self.overwrite)
        # the commit marker goes LAST: its presence is the atomic
        # "snapshot is whole" bit restore keys on.  Content cross-checks
        # the manifest itself.
        file_io.write_bytes(file_io.join(self.path, f"commit.{neval}"),
                            (f"{crc32c(mbytes):08x}\n").encode("ascii"),
                            self.overwrite)
        self.gc()

    def _sweep_orphan_temps(self) -> None:
        """Reclaim atomic-write temps orphaned by a hard-killed earlier
        writer, age-gated: a recent temp (or one whose store reports no
        mtime) may be a concurrent writer's in-flight write."""
        from bigdl_tpu.utils import file_io
        now = time.time()
        for f in file_io.listdir(self.path):
            if ".tmp_bigdl" in f:
                full = file_io.join(self.path, f)
                mtime = file_io.modified_time(full)
                if mtime is None or now - mtime < self.TEMP_SWEEP_AGE_S:
                    continue
                try:
                    file_io.remove(full)
                except Exception:
                    pass

    # ---- scan / verify --------------------------------------------------

    def candidates(self) -> List[Tuple[int, bool]]:
        """(neval, has_manifest) for every restore-eligible snapshot,
        newest first.  Eligible means: the ``model.N``/``optimMethod.N``
        PAIR exists (a crash between the two saves leaves a model-only
        snapshot that must never be selected), and — for manifest-era
        snapshots — the commit marker landed.  A snapshot with a manifest
        or commit but not both is a torn write in progress or a crashed
        writer's debris: skipped."""
        from bigdl_tpu.utils import file_io

        def ns(prefix: str, names) -> set:
            out = set()
            for f in names:
                if f.startswith(prefix) and ".tmp_bigdl" not in f:
                    try:
                        out.add(int(f[len(prefix):]))
                    except ValueError:
                        pass
            return out

        names = file_io.listdir(self.path)
        models = ns("model.", names)
        optims = ns("optimMethod.", names)
        manifests = ns("manifest.", names)
        commits = ns("commit.", names)
        out: List[Tuple[int, bool]] = []
        for n in sorted(models & optims, reverse=True):
            if n in commits and n in manifests:
                out.append((n, True))
            elif n in commits or n in manifests:
                continue
            else:
                out.append((n, False))   # legacy pre-manifest snapshot
        return out

    def _read_manifest(self, n: int) -> Optional[Dict[str, Any]]:
        from bigdl_tpu.utils import file_io
        data = file_io.read_bytes(file_io.join(self.path, f"manifest.{n}"))
        manifest = json.loads(data.decode("utf-8"))
        commit = file_io.read_bytes(
            file_io.join(self.path, f"commit.{n}")).strip()
        if commit != f"{crc32c(data):08x}".encode("ascii"):
            raise SnapshotCorruptError(
                f"snapshot {n}: commit marker does not match manifest "
                f"checksum")
        version = manifest.get("version", 1)
        if not isinstance(version, int) or version > MANIFEST_VERSION:
            raise SnapshotSchemaError(n, version)
        return manifest

    def _read_verified(self, name: str,
                       manifest: Optional[Dict[str, Any]]) -> bytes:
        from bigdl_tpu.utils import file_io
        data = file_io.read_bytes(file_io.join(self.path, name))
        if manifest is not None:
            meta = manifest["files"][name]
            algo = manifest.get("algo", "crc32c")
            if (len(data) != meta["bytes"] or
                    checksum_by_algo(algo, data) != meta["checksum"]):
                raise SnapshotCorruptError(
                    f"{name}: payload fails its manifest {algo} checksum "
                    f"({len(data)} bytes)")
        return data

    def _check_fingerprint(self, name: str, obj: Any,
                           manifest: Optional[Dict[str, Any]]) -> None:
        """Semantic verification: recompute the state fingerprint on the
        UNPICKLED object and compare with the save-time record.  The
        payload bytes already passed their checksum — a mismatch here
        means the state rotted BEFORE serialization (the CRC faithfully
        protects corrupt bytes), so the snapshot is refused and restore
        walks to the next-older one."""
        if manifest is None:
            return
        expected = manifest["files"].get(name, {}).get("fingerprint")
        if expected is None:
            return    # pre-v3 manifest: nothing semantic to check
        from bigdl_tpu.integrity import fingerprint_key, host_fingerprint
        got = fingerprint_key(host_fingerprint(obj))
        if got != expected:
            raise SnapshotCorruptError(
                f"{name}: semantic state fingerprint mismatch — payload "
                f"checksums verify but the save-time fingerprint "
                f"{expected} does not match the recomputed {got}; the "
                "state was corrupted in memory before serialization")

    def verify(self, n: int, has_manifest: bool,
               deep: bool = False) -> bool:
        """True when snapshot ``n``'s payloads match their manifest.

        The default check is SHALLOW — manifest↔commit cross-check plus a
        size stat per payload — one metadata round-trip instead of a full
        multi-GB transfer, catching the realistic torn-write mode
        (truncation committed by the rename).  ``deep=True`` reads and
        checksums every payload; :meth:`load_latest` gets that for free
        since it must read the bytes anyway.  Legacy snapshots have
        nothing to verify against and pass (the load-time fallback still
        protects restore).

        A :class:`SnapshotSchemaError` (manifest from a NEWER release)
        is a deliberate rejection, not corruption, and PROPAGATES — the
        same semantics as :meth:`load_latest` — so a supervisor probing
        :meth:`latest_valid` cannot silently plan around stale state the
        actual restore path would refuse (:meth:`gc` catches it and
        treats the snapshot as untouchable)."""
        if not has_manifest:
            return True
        from bigdl_tpu.utils import file_io
        try:
            manifest = self._read_manifest(n)
            for name in (f"model.{n}", f"optimMethod.{n}"):
                if deep:
                    data = self._read_verified(name, manifest)
                    if manifest["files"].get(name, {}).get("fingerprint"):
                        self._check_fingerprint(name, pickle.loads(data),
                                                manifest)
                else:
                    sz = file_io.size(file_io.join(self.path, name))
                    if sz != manifest["files"][name]["bytes"]:
                        raise SnapshotCorruptError(
                            f"{name}: size {sz} does not match the "
                            f"manifest ({manifest['files'][name]['bytes']}"
                            " bytes)")
            return True
        except SnapshotSchemaError:
            raise
        except Exception as e:
            logger.warning("snapshot %d fails verification (%s) — "
                           "skipping to an older snapshot", n, e)
            return False

    def latest_valid(self) -> Optional[Tuple[str, str, int]]:
        """Newest snapshot that is committed and shallow-verified
        (manifest↔commit cross-check + payload sizes), as
        ``(model_path, optimMethod_path, neval)`` — the drop-in shape of
        the old ``Checkpoint.latest()``.  Full checksums run when the
        payloads are actually read (:meth:`load_latest`), which also
        falls back to older snapshots on a deep-verification failure.
        Like :meth:`load_latest`, a newer-schema newest snapshot raises
        :class:`SnapshotSchemaError` instead of silently answering with
        older state."""
        from bigdl_tpu.utils import file_io
        for n, has_manifest in self.candidates():
            if self.verify(n, has_manifest):
                return (file_io.join(self.path, f"model.{n}"),
                        file_io.join(self.path, f"optimMethod.{n}"), n)
        return None

    def watch_latest(self) -> Optional[int]:
        """O(1)-per-tick poll for newly COMMITTED snapshots — the fleet
        promotion watcher's fast path.

        :meth:`latest_valid` lists the directory and stats payloads on
        every call; at a supervisor cadence of tens of hertz that is
        thousands of metadata round trips a minute against a usually
        idle directory.  This helper keys on the directory's mtime —
        every ``commit.N`` marker rename touches the parent directory —
        so while the mtime holds steady the cached answer returns after
        ONE stat: no listing, no manifest reads.  When the mtime moves,
        the names-only candidate scan reruns and any snapshot not
        already known good is shallow-verified once, then remembered.
        Because directory mtimes on some stores carry whole-second
        granularity, a scan taken while the directory is "hot" (mtime
        within the last ~2 s) is not trusted as a fast-path anchor — the
        next tick re-lists names, but the verified-set cache still keeps
        manifest reads at one per NEW snapshot.

        Returns the N of the newest committed, shallow-verified
        snapshot, or None when there is none.  Deep verification —
        payload checksums plus the semantic fingerprint — stays where
        the bytes are read anyway: the :meth:`load_latest` call the
        watcher makes when it decides to promote.  Disk-full degraded
        in-memory snapshots are deliberately invisible here: they are
        not committed durable state and must not trigger a promotion."""
        from bigdl_tpu.utils import file_io
        mtime = file_io.modified_time(self.path)
        stable = (mtime is not None and (time.time() - mtime) >= 2.0)
        if self._watch_scanned and stable and mtime == self._watch_mtime:
            return self._watch_latest
        latest: Optional[int] = None
        cands = self.candidates()
        self._watch_verified &= {n for n, _ in cands}
        for n, has_manifest in cands:
            if n in self._watch_verified or self.verify(n, has_manifest):
                self._watch_verified.add(n)
                latest = n
                break
        self._watch_mtime = mtime if stable else None
        self._watch_latest = latest
        self._watch_scanned = stable
        return latest

    def load_latest(self, expected_topology: Optional[Dict[str, Any]] = None
                    ) -> Optional[Tuple[Any, Any, int]]:
        """Load the newest restorable snapshot, walking to the next-older
        one when verification OR deserialization fails (a corrupt legacy
        pickle has no manifest to fail against — the unpickler is its
        verifier).

        ``expected_topology``: the RESUMING trainer's topology
        (``elastic.describe_topology``).  When the newest snapshot's
        recorded topology differs, the elastic policy decides — reshard
        (``bigdl.elastic.reshardOnRestore``) or a structured
        :class:`~bigdl_tpu.utils.elastic.TopologyMismatchError`.  Both
        that error and :class:`SnapshotSchemaError` are deliberate
        REJECTIONS and propagate instead of falling back: an older
        snapshot would carry the same incompatibility, and silently
        restoring older state would masquerade as progress loss.  The
        manifest of the snapshot actually loaded (None for legacy pairs)
        is left in :attr:`last_loaded_manifest`."""
        mem = self._restore_memory_snapshot(expected_topology)
        if mem is not None:
            return mem
        for n, has_manifest in self.candidates():
            try:
                manifest = self._read_manifest(n) if has_manifest else None
                mode = "same"
                if expected_topology is not None and manifest is not None:
                    from bigdl_tpu.utils import elastic
                    mode = elastic.check_restore_topology(
                        manifest.get("topology"), expected_topology)
                model = pickle.loads(
                    self._read_verified(f"model.{n}", manifest))
                self._check_fingerprint(f"model.{n}", model, manifest)
                optim = pickle.loads(
                    self._read_verified(f"optimMethod.{n}", manifest))
                self._check_fingerprint(f"optimMethod.{n}", optim,
                                        manifest)
                self.last_loaded_manifest = manifest
                self.last_restore_mode = mode
                if mode == "reshard":
                    # counted here, after the load succeeded: a fallback
                    # walk past a corrupt newest snapshot is ONE restore
                    from bigdl_tpu.utils import elastic
                    elastic.count_reshard()
                return model, optim, n
            except Exception as e:
                if isinstance(e, SnapshotSchemaError):
                    raise
                from bigdl_tpu.utils import elastic
                if isinstance(e, elastic.TopologyMismatchError):
                    raise
                logger.warning(
                    "snapshot %d failed to restore (%s: %s) — falling "
                    "back to the next-older snapshot", n,
                    type(e).__name__, e)
        return None

    def _restore_memory_snapshot(
            self, expected_topology: Optional[Dict[str, Any]] = None
            ) -> Optional[Tuple[Any, Any, int]]:
        """The degraded-mode candidate: the in-RAM snapshot, taken only
        when it is NEWER than every committed disk snapshot (a disk
        snapshot that landed after degradation would be newer truth).
        Fingerprint-verified like a disk restore; an unusable memory
        snapshot falls back to the disk walk."""
        mem = self._memory_snapshot
        if mem is None:
            return None
        disk = self.candidates()
        if disk and disk[0][0] >= mem["neval"]:
            return None
        n = mem["neval"]
        try:
            mode = "same"
            if expected_topology is not None and mem.get("topology"):
                from bigdl_tpu.utils import elastic
                mode = elastic.check_restore_topology(
                    mem["topology"], expected_topology)
            fake_manifest = {"files": {
                name: {"fingerprint": fp}
                for name, fp in mem.get("fps", {}).items()}}
            model = pickle.loads(mem["blobs"][f"model.{n}"])
            self._check_fingerprint(f"model.{n}", model, fake_manifest)
            optim = pickle.loads(mem["blobs"][f"optimMethod.{n}"])
            self._check_fingerprint(f"optimMethod.{n}", optim,
                                    fake_manifest)
            self.last_loaded_manifest = None
            self.last_restore_mode = mode
            logger.warning(
                "restoring snapshot %d from the in-memory store "
                "(checkpoint storage is degraded — disk full)", n)
            return model, optim, n
        except Exception as e:
            from bigdl_tpu.utils import elastic
            if isinstance(e, (SnapshotSchemaError,
                              elastic.TopologyMismatchError)):
                raise
            logger.warning(
                "in-memory snapshot %d failed to restore (%s: %s) — "
                "falling back to the disk walk", n, type(e).__name__, e)
            return None

    # ---- retention ------------------------------------------------------

    def gc(self) -> None:
        """Retention: keep the newest ``keep_last`` restorable snapshots
        (manifest-era AND legacy pairs — a directory carried over from
        before the manifest era must still be bounded), delete the rest
        plus torn-write debris older than the newest restorable one
        (pair-incomplete leftovers can never become whole — a writer
        only moves forward).

        Deletion order is load-bearing: the commit marker goes FIRST (an
        interrupted GC leaves an uncommitted — ignored — snapshot, never
        a committed half-snapshot) and the manifest goes LAST (a crash
        after the payloads-but-before-the-manifest must not leave a bare
        ``model.N``/``optimMethod.N`` pair that ``candidates()`` would
        resurrect as a verification-exempt legacy snapshot)."""
        if not self.keep_last or self.keep_last <= 0:
            return
        from bigdl_tpu.utils import file_io

        def _rm(name: str) -> None:
            try:
                file_io.remove(file_io.join(self.path, name))
            except Exception as e:
                logger.warning("checkpoint GC could not remove %s: %r",
                               name, e)

        cands = self.candidates()
        if not cands:
            return
        # only snapshots that pass the shallow verification count toward
        # the retention quota: a committed-but-truncated newest snapshot
        # must not occupy a keep_last slot and push the last VALID
        # snapshot out of the window — that would brick the very
        # recovery path the manifest machinery exists to protect
        keepers: List[int] = []
        drop: List[Tuple[int, bool]] = []
        protected: set = set()
        for n, has_manifest in cands:
            if len(keepers) >= self.keep_last:
                drop.append((n, has_manifest))
                continue
            try:
                ok = self.verify(n, has_manifest)
            except SnapshotSchemaError:
                # a NEWER release's snapshot (mixed-version rollout):
                # not loadable here, but absolutely not debris — GC of
                # another release's data would be destructive
                protected.add(n)
                continue
            if ok:
                keepers.append(n)
            # verification failures are left in place here and swept as
            # debris below only once something newer AND valid exists
        for n, has_manifest in drop:
            if has_manifest:
                try:
                    self._read_manifest(n)
                except SnapshotSchemaError:
                    protected.add(n)   # shields the debris sweep below too
                    continue
                except Exception:
                    pass   # torn/corrupt past the quota: normal debris
            names = ((f"commit.{n}", f"model.{n}", f"optimMethod.{n}",
                      f"manifest.{n}") if has_manifest else
                     (f"model.{n}", f"optimMethod.{n}"))
            for name in names:
                _rm(name)
        if not keepers:
            return
        newest = keepers[0]
        kept = set(keepers)
        for f in file_io.listdir(self.path):
            if ".tmp_bigdl" in f:
                continue
            prefix, _, tail = f.partition(".")
            if prefix not in ("model", "optimMethod", "manifest", "commit"):
                continue
            try:
                n = int(tail)
            except ValueError:
                continue
            if n < newest and n not in kept and n not in protected:
                _rm(f)

    # ---- async lifecycle ------------------------------------------------

    def join(self, raise_errors: bool = True,
             timeout: Optional[float] = None) -> None:
        """Drain the background writer (no-op in sync mode).  Deferred
        write errors re-raise here unless ``raise_errors`` is False (used
        on paths already unwinding an exception).  ``timeout`` bounds the
        wait (exit paths); an expired bound abandons the thread."""
        if self._writer is not None:
            self._writer.join(raise_errors=raise_errors, timeout=timeout)

    close = join
