"""Decoded-epoch cache: pay JPEG decode once across repeated epochs.

On the real-data path the decode pool is the measured bottleneck
(bench_ingest.json: ~1.3k img/s per core against a multi-k img/s
assemble ceiling), and epochs 2..N decode the SAME compressed records
epoch 1 already decoded.  :class:`DecodedEpochCache` interposes at the
decode stage (``StreamingIngest``'s ``timed_decode``): a hit returns
the decoded uint8 HWC frame without touching libjpeg, so second-epoch
throughput is bounded by assemble/upload instead of decode.  Crop/flip
draws happen AFTER the cache (on the assembler, per record, as always),
so a cached epoch's augmentation stream is bit-identical to a decoded
one.

Structure — a segmented ring, newest-kept:

- records append to an OPEN segment; at ``segment_records`` entries the
  segment seals.  Sealed segments either stay in host RAM or, when
  ``cache_dir`` is set, serialize to disk and release their RAM.
- the disk leg rides :func:`bigdl_tpu.utils.file_io.write_bytes` — the
  single payload-write choke point, so chaos disk-full injection and
  the transient-retry machinery apply.  A failed spill (ENOSPC, dead
  mount) DEGRADES: the segment stays in RAM, disk spilling disarms, the
  run continues.
- every sealed blob carries a CRC32 over its payload.  A mismatch on
  read (bit rot, a torn write behind our atomic rename's back)
  QUARANTINES the segment — its index entries drop, the reader decodes
  those records from bytes as if never cached — and never crashes the
  run (the PR 7 data-vs-infrastructure taxonomy: corrupt cache contents
  are data damage with a decode-from-source repair path).
- bytes are governor-accounted (``ingest_epoch_cache`` →
  ``Resources/host_bytes``); a registered shrinker evicts the OLDEST
  RAM segments under host-memory pressure, and ``budget_mb`` (or the
  governor budget when 0) caps growth ring-style: when full, the oldest
  segment evicts to admit the new one — a partially-cached epoch still
  saves its hit fraction.

Thread safety: decode workers call :meth:`get`/:meth:`put`
concurrently; one lock serializes index/segment mutation.  Disk reads
parse a whole segment and keep the most recent parsed segment cached —
stream-order consumption makes that a sequential-hit pattern, so the
read amplification is ~1x.
"""

from __future__ import annotations

import json
import struct
import weakref
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu import analysis, telemetry
from bigdl_tpu.resources import GOVERNOR as _governor
from bigdl_tpu.utils import file_io

_HEADER_LEN = struct.Struct("<I")


def _serialize_segment(keys: List[str], arrays: List[np.ndarray]) -> bytes:
    payload = b"".join(np.ascontiguousarray(a).tobytes() for a in arrays)
    header = json.dumps({
        "keys": keys,
        "shapes": [list(a.shape) for a in arrays],
        "dtypes": [str(a.dtype) for a in arrays],
        "crc": zlib.crc32(payload) & 0xFFFFFFFF,
    }).encode("utf-8")
    return _HEADER_LEN.pack(len(header)) + header + payload


def _deserialize_segment(blob: bytes) -> Tuple[List[str], List[np.ndarray]]:
    """Parse + CRC-verify a sealed segment blob; raises ``ValueError``
    on any corruption (truncation, bit flips, junk headers) so the
    caller can quarantine instead of crash."""
    try:
        (hlen,) = _HEADER_LEN.unpack_from(blob, 0)
        header = json.loads(blob[4:4 + hlen].decode("utf-8"))
        payload = blob[4 + hlen:]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != header["crc"]:
            raise ValueError("payload CRC mismatch")
        arrays, off = [], 0
        for shape, dtype in zip(header["shapes"], header["dtypes"]):
            a = np.frombuffer(payload, np.dtype(dtype),
                              count=int(np.prod(shape)) if shape else 1,
                              offset=off).reshape(shape)
            off += a.nbytes
            arrays.append(a)
        if off != len(payload):
            raise ValueError("payload length mismatch")
        return header["keys"], arrays
    except ValueError:
        raise
    except Exception as e:
        raise ValueError(f"unparseable cache segment: {e!r}") from e


class DecodedEpochCache:
    """Keyed decoded-frame store (key = record name).  See module doc."""

    def __init__(self, name: str, cache_dir: Optional[str] = None,
                 budget_mb: int = 0, segment_records: int = 256):
        self.name = name
        self.cache_dir = cache_dir
        self.budget_bytes = max(0, int(budget_mb)) * (1 << 20)
        self.segment_records = max(1, int(segment_records))
        self._lock = analysis.make_lock("epoch_cache")
        #: key -> (segment_id, slot); dropped entries mean "not cached"
        self._index: Dict[str, Tuple[int, int]] = {}
        #: sealed RAM segments + the open one, oldest-first insertion
        self._ram: Dict[int, Tuple[List[str], List[np.ndarray]]] = {}
        #: sealed disk segments: id -> path
        self._disk: Dict[int, str] = {}
        self._open_keys: List[str] = []
        self._open_arrays: List[np.ndarray] = []
        self._open_bytes = 0
        self._seg_seq = 0
        self._ram_bytes = 0
        self._disk_ok = cache_dir is not None
        self._parsed: Optional[Tuple[int, Dict[str, int],
                                     List[np.ndarray]]] = None
        self.hits = 0
        self.misses = 0
        self.corrupt_segments = 0
        self.evicted_segments = 0
        self._acct = _governor.account(f"ingest_epoch_cache:{name}")
        self._shrink_key = f"epoch_cache:{name}:{id(self)}"
        # weak self-reference: the governor's shrinker registry must not
        # pin the cache (and every frame it holds) past its engine
        ref = weakref.ref(self)

        def _shrink_hook() -> None:
            cache = ref()
            if cache is not None:
                cache.shrink()

        _governor.register_shrinker(self._shrink_key, _shrink_hook)

    # -- capacity ---------------------------------------------------------

    def _cap(self) -> int:
        """Byte cap: the explicit budget, else half the governor's whole
        host budget (the cache must never be the reason training
        buffers cannot breathe), else unbounded-by-cap (the governor's
        pressure shrinker is still live)."""
        if self.budget_bytes:
            return self.budget_bytes
        gb = _governor.budget_bytes()
        return max(1, gb // 2) if gb > 0 else (1 << 62)

    def _evict_oldest_ram(self) -> bool:
        """Drop the oldest sealed RAM segment (ring semantics)."""
        if not self._ram:
            return False
        seg_id = next(iter(self._ram))
        keys, arrays = self._ram.pop(seg_id)
        n = sum(a.nbytes for a in arrays)
        self._ram_bytes -= n
        self._acct.sub(n)
        for k in keys:
            self._index.pop(k, None)
        self.evicted_segments += 1
        return True

    def shrink(self) -> None:
        """Governor pressure hook: evict half the sealed RAM segments,
        oldest first — Resources/host_bytes drops on the next poll."""
        with self._lock:
            for _ in range(max(1, len(self._ram) // 2)):
                if not self._evict_oldest_ram():
                    break

    def close(self) -> None:
        _governor.unregister_shrinker(self._shrink_key)
        with self._lock:
            while self._evict_oldest_ram():
                pass
            if self._open_bytes:
                self._acct.sub(self._open_bytes)
            self._open_keys, self._open_arrays = [], []
            self._open_bytes = 0
            self._index.clear()
            self._disk.clear()
            self._parsed = None

    # -- write path -------------------------------------------------------

    def put(self, key: Optional[str], img: np.ndarray) -> None:
        """Admit one decoded frame.  No-ops on unnamed records, on
        already-cached keys (a second epoch's redundant decode — the
        resubmit path after a dead worker), and when the ring cannot
        make room."""
        if key is None:
            return
        n = int(img.nbytes)
        with self._lock:
            if key in self._index:
                return
            cap = self._cap()
            while (self._ram_bytes + self._open_bytes + n > cap and
                   self._evict_oldest_ram()):
                pass
            if self._ram_bytes + self._open_bytes + n > cap:
                return          # one open segment already fills the cap
            seg_id = self._seg_seq
            self._index[key] = (seg_id, len(self._open_keys))
            self._open_keys.append(key)
            self._open_arrays.append(img)
            self._open_bytes += n
            self._acct.add(n)
            if len(self._open_keys) >= self.segment_records:
                self._seal()

    def _seal(self) -> None:
        """Seal the open segment (lock held).  Disk when armed — via the
        write_bytes choke point, degrading to RAM on failure."""
        seg_id = self._seg_seq
        self._seg_seq += 1
        keys, arrays = self._open_keys, self._open_arrays
        nbytes = self._open_bytes
        self._open_keys, self._open_arrays = [], []
        self._open_bytes = 0
        if self._disk_ok:
            path = f"{self.cache_dir.rstrip('/')}/" \
                   f"{self.name}_seg{seg_id:06d}.bin"
            try:
                file_io.write_bytes(path, _serialize_segment(keys, arrays))
                self._disk[seg_id] = path
                self._acct.sub(nbytes)     # RAM released, disk holds it
                return
            except BaseException as e:
                # disk-full / dead mount: DEGRADE to RAM-only, keep the
                # already-decoded work, never crash the run
                self._disk_ok = False
                telemetry.counter(
                    "Ingest/epoch_cache_spill_failures", summary=True,
                    help="epoch-cache disk spills abandoned (cache "
                         "degraded to RAM-only)").inc()
                import logging
                logging.getLogger("bigdl_tpu").warning(
                    "epoch cache '%s' disk spill failed (%r) — "
                    "degrading to RAM-only", self.name, e)
        self._ram[seg_id] = (keys, arrays)
        self._ram_bytes += nbytes

    # -- read path --------------------------------------------------------

    def _quarantine(self, seg_id: int, path: str, err: Exception) -> None:
        """Corrupt disk segment: drop its index entries so every record
        it held re-decodes from source bytes (lock held)."""
        self._disk.pop(seg_id, None)
        dropped = [k for k, (s, _i) in self._index.items() if s == seg_id]
        for k in dropped:
            del self._index[k]
        self.corrupt_segments += 1
        telemetry.counter(
            "Ingest/epoch_cache_corrupt_segments", summary=True,
            help="checksum-failed epoch-cache segments quarantined "
                 "(records re-decode from source)").inc()
        import logging
        logging.getLogger("bigdl_tpu").warning(
            "epoch cache '%s' segment %s failed verification (%s) — "
            "quarantined, %d records will re-decode", self.name, path,
            err, len(dropped))

    def get(self, key: Optional[str]) -> Optional[np.ndarray]:
        """Decoded frame for ``key``, or None (miss / evicted /
        quarantined) — the caller decodes from bytes on None."""
        if key is None:
            return None
        with self._lock:
            loc = self._index.get(key)
            if loc is None:
                self.misses += 1
                return None
            seg_id, slot = loc
            if seg_id == self._seg_seq:            # still open
                self.hits += 1
                return self._open_arrays[slot]
            if seg_id in self._ram:
                self.hits += 1
                return self._ram[seg_id][1][slot]
            path = self._disk.get(seg_id)
            if path is None:                        # evicted meanwhile
                del self._index[key]
                self.misses += 1
                return None
            if self._parsed is not None and self._parsed[0] == seg_id:
                _sid, bykey, arrays = self._parsed
                idx = bykey.get(key)
                if idx is not None:
                    self.hits += 1
                    return arrays[idx]
            try:
                blob = file_io.read_bytes(path)
                keys, arrays = _deserialize_segment(blob)
            except (ValueError, OSError) as e:
                self._quarantine(seg_id, path, e)
                self.misses += 1
                return None
            self._parsed = (seg_id, {k: i for i, k in enumerate(keys)},
                            arrays)
            idx = self._parsed[1].get(key)
            if idx is None:                         # header/key drift
                self._quarantine(seg_id, path,
                                 ValueError("key missing from segment"))
                self.misses += 1
                return None
            self.hits += 1
            return arrays[idx]

    # -- diagnostics ------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits, "misses": self.misses,
                "ram_segments": len(self._ram),
                "disk_segments": len(self._disk),
                "open_records": len(self._open_keys),
                "ram_bytes": self._ram_bytes + self._open_bytes,
                "corrupt_segments": self.corrupt_segments,
                "evicted_segments": self.evicted_segments,
                "disk_ok": self._disk_ok,
            }
