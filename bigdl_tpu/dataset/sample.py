"""Sample and MiniBatch: the record and batch abstractions.

Reference equivalents: ``dataset/Sample.scala:31`` (one record =
feature tensor(s) + label tensor(s), backed by one flat array) and
``dataset/MiniBatch.scala:33`` (a batch with ``slice`` for splitting across
model-replica threads, plus padding strategies).

TPU-native notes: host-side records are numpy (cheap, mutable, pipelined);
they become device arrays only at the jit boundary.  The reference's
``slice()`` existed to split a batch across intra-node replica threads — on
TPU that tier disappears (one big per-chip batch under jit), but ``slice`` is
kept for API parity and for sharding a global batch across data-parallel
devices.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import numpy as np


def _to_list(x) -> List[np.ndarray]:
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return [np.asarray(t) for t in x]
    return [np.asarray(x)]


class Sample:
    """One record: feature array(s) + label array(s)
    (reference ``ArraySample``, ``dataset/Sample.scala:129``)."""

    __slots__ = ("features", "labels")

    def __init__(self, features, labels=None):
        self.features: List[np.ndarray] = _to_list(features)
        self.labels: List[np.ndarray] = _to_list(labels)

    @property
    def feature(self) -> np.ndarray:
        return self.features[0]

    @property
    def label(self) -> np.ndarray:
        return self.labels[0]

    def feature_size(self):
        return [f.shape for f in self.features]

    def label_size(self):
        return [l.shape for l in self.labels]

    def num_feature(self) -> int:
        return len(self.features)

    def num_label(self) -> int:
        return len(self.labels)

    def __repr__(self):
        return (f"Sample(features={[f.shape for f in self.features]}, "
                f"labels={[l.shape for l in self.labels]})")


class PaddingParam:
    """Padding strategy for variable-length samples
    (reference ``dataset/MiniBatch.scala:522-566``).

    ``padding_value``: scalar fill; ``fixed_length``: per-tensor target lengths
    (None → pad to the longest in the batch, the reference's default).
    """

    def __init__(self, padding_value: float = 0.0,
                 fixed_length: Optional[Sequence[int]] = None):
        self.padding_value = padding_value
        self.fixed_length = fixed_length


def _stack_padded(arrays: List[np.ndarray],
                  param: Optional[PaddingParam]) -> np.ndarray:
    """Stack along a new batch dim, padding dim 0 of each record if ragged."""
    shapes = {a.shape for a in arrays}
    if len(shapes) == 1 and (param is None or param.fixed_length is None):
        return np.stack(arrays)
    if param is None:
        param = PaddingParam()
    ndim = arrays[0].ndim
    max_per_dim = [max(a.shape[d] for a in arrays) for d in range(ndim)]
    if param.fixed_length is not None:
        for d, fl in enumerate(param.fixed_length[:ndim]):
            if fl is not None and fl > 0:
                if fl < max_per_dim[d]:
                    raise ValueError(
                        f"fixed_length {fl} < longest sample {max_per_dim[d]}")
                max_per_dim[d] = fl
    out = np.full([len(arrays)] + max_per_dim, param.padding_value,
                  dtype=arrays[0].dtype)
    for i, a in enumerate(arrays):
        out[(i,) + tuple(slice(0, s) for s in a.shape)] = a
    return out


class MiniBatch:
    """A batch of samples (reference ``ArrayTensorMiniBatch``,
    ``dataset/MiniBatch.scala:33``).

    ``inputs``/``targets`` are lists of numpy arrays whose dim 0 is the batch
    dimension.  ``get_input()``/``get_target()`` return a single array when
    there is exactly one (the reference's Tensor-vs-Table Activity collapse).
    """

    def __init__(self, inputs, targets=None):
        self.inputs: List[np.ndarray] = _to_list(inputs)
        self.targets: List[np.ndarray] = _to_list(targets)

    @staticmethod
    def from_samples(samples: Sequence[Sample],
                     feature_padding: Optional[PaddingParam] = None,
                     label_padding: Optional[PaddingParam] = None) -> "MiniBatch":
        n_feat = samples[0].num_feature()
        n_lab = samples[0].num_label()
        inputs = [_stack_padded([s.features[i] for s in samples],
                                feature_padding) for i in range(n_feat)]
        targets = [_stack_padded([s.labels[i] for s in samples],
                                 label_padding) for i in range(n_lab)]
        return MiniBatch(inputs, targets)

    def size(self) -> int:
        return self.inputs[0].shape[0] if self.inputs else 0

    @property
    def nbytes(self) -> int:
        """Host bytes this batch pins — what the resource governor's
        ring/queue accounts charge per buffered batch."""
        return int(sum(int(getattr(a, "nbytes", 0))
                       for a in self.inputs + self.targets))

    def slice(self, offset: int, length: int) -> "MiniBatch":
        """Sub-batch [offset, offset+length) — 0-based, unlike the 1-based
        reference (reference ``MiniBatch.slice``)."""
        return MiniBatch([a[offset:offset + length] for a in self.inputs],
                         [a[offset:offset + length] for a in self.targets])

    def get_input(self) -> Union[np.ndarray, List[np.ndarray]]:
        return self.inputs[0] if len(self.inputs) == 1 else self.inputs

    def get_target(self) -> Union[np.ndarray, List[np.ndarray]]:
        return self.targets[0] if len(self.targets) == 1 else self.targets

    def __repr__(self):
        return (f"MiniBatch(inputs={[a.shape for a in self.inputs]}, "
                f"targets={[a.shape for a in self.targets]})")
