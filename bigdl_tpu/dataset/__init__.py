"""bigdl_tpu.dataset — host-side data pipeline (SURVEY §2.5).

Records and transforms are numpy on the host; arrays cross to the device only
at the jit boundary inside the optimizers.
"""

from bigdl_tpu.dataset.sample import Sample, MiniBatch, PaddingParam
from bigdl_tpu.dataset.transformer import (Transformer, ChainedTransformer,
                                           FuncTransformer, SampleToMiniBatch,
                                           SampleToBatch)
from bigdl_tpu.dataset.dataset import (AbstractDataSet, LocalDataSet,
                                       ShardedDataSet, DataSet)
from bigdl_tpu.dataset.ingest import ShardedSeqFileReader, StreamingIngest
from bigdl_tpu.dataset import image
from bigdl_tpu.dataset import text
from bigdl_tpu.dataset import datasets

__all__ = ["Sample", "MiniBatch", "PaddingParam", "Transformer",
           "ChainedTransformer", "FuncTransformer", "SampleToMiniBatch",
           "SampleToBatch", "AbstractDataSet", "LocalDataSet",
           "ShardedDataSet", "DataSet", "ShardedSeqFileReader",
           "StreamingIngest", "image", "text", "datasets"]
