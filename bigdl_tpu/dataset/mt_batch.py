"""Multi-threaded image-to-batch assembly + prefetching transformer.

Reference equivalent: ``dataset/image/MTLabeledBGRImgToBatch.scala:46`` —
the parallel CPU path that crops/flips/normalizes decoded images into the
training batch concurrently with compute.

Two pieces:
- :func:`assemble_batch` — pack N HWC uint8 images into one float32 NCHW
  batch (normalize + crop + optional hflip), dispatched to the native
  std::thread implementation (``native/batch.cc``) when built, else numpy.
- :class:`Prefetch` — a transformer that runs its upstream iterator in a
  background thread with a bounded queue, so host-side batch prep overlaps
  device steps (the reference's MT pipeline role in the driver loop).
"""

from __future__ import annotations

import ctypes
import queue
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.dataset.native import load_native
from bigdl_tpu.dataset.transformer import Transformer


def _check_crop_fits(images: Sequence[np.ndarray],
                     crop: Tuple[int, int], describe=None) -> None:
    """Every image must be at least crop-sized: the native assembler
    (``native/batch.cc``) does no bounds checks, so an undersized image
    would turn into a negative offset and an out-of-bounds read.
    ``describe(i)`` customizes how the offending image is named (the MT
    transformer names the record and label)."""
    ch, cw = crop
    for i, im in enumerate(images):
        h, w = im.shape[:2]
        if h < ch or w < cw:
            who = describe(i) if describe else f"assemble_batch: image {i}"
            raise ValueError(
                f"{who} is {h}x{w}, smaller than the {ch}x{cw} crop; "
                "resize images to at least the crop size first "
                "(reference pipelines feed pre-resized 256x256 records)")


def assemble_batch(images: Sequence[np.ndarray],
                   crop: Tuple[int, int],
                   offsets: np.ndarray,
                   flips: np.ndarray,
                   mean: Sequence[float],
                   std: Sequence[float],
                   n_threads: int = 4) -> np.ndarray:
    """images: HWC uint8 arrays (any sizes >= crop, enforced); offsets:
    (N, 2) int32 (y, x) crop origins; flips: (N,) uint8.  Returns
    (N, C, crop_h, crop_w) float32: out = (crop(img) - mean) / std,
    optionally h-flipped."""
    _check_crop_fits(images, crop)
    n = len(images)
    ch, cw = crop
    channels = images[0].shape[2] if images[0].ndim == 3 else 1
    imgs = [np.ascontiguousarray(
        im if im.ndim == 3 else im[:, :, None], dtype=np.uint8)
        for im in images]
    offsets = np.ascontiguousarray(offsets, dtype=np.int32)
    flips = np.ascontiguousarray(flips, dtype=np.uint8)
    mean_a = np.asarray(mean, np.float32)
    std_a = np.asarray(std, np.float32)
    out = np.empty((n, channels, ch, cw), np.float32)

    lib = load_native()
    if lib is not None:
        ptrs = (ctypes.c_void_p * n)(
            *[im.ctypes.data_as(ctypes.c_void_p) for im in imgs])
        heights = np.asarray([im.shape[0] for im in imgs], np.int32)
        widths = np.asarray([im.shape[1] for im in imgs], np.int32)
        lib.assemble_batch(
            ptrs,
            heights.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            widths.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            n, channels, ch, cw,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            flips.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
            mean_a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            std_a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            int(n_threads))
        return out

    for i, im in enumerate(imgs):
        oy, ox = int(offsets[i, 0]), int(offsets[i, 1])
        patch = im[oy:oy + ch, ox:ox + cw].astype(np.float32)
        if flips[i]:
            patch = patch[:, ::-1]
        out[i] = ((patch - mean_a) / std_a).transpose(2, 0, 1)
    return out


def assemble_batch_u8(images: Sequence[np.ndarray],
                      crop: Tuple[int, int],
                      offsets: np.ndarray,
                      flips: np.ndarray,
                      n_threads: int = 4) -> np.ndarray:
    """Raw-uint8 sibling of :func:`assemble_batch`: crop + flip + HWC→CHW
    pack WITHOUT normalization — the device-normalize ingest layout (pair
    with ``nn.ChannelNormalize`` on device).  Native std::thread path when
    built; numpy fallback."""
    _check_crop_fits(images, crop)
    n = len(images)
    ch, cw = crop
    channels = images[0].shape[2] if images[0].ndim == 3 else 1
    imgs = [np.ascontiguousarray(
        im if im.ndim == 3 else im[:, :, None], dtype=np.uint8)
        for im in images]
    offsets = np.ascontiguousarray(offsets, dtype=np.int32)
    flips = np.ascontiguousarray(flips, dtype=np.uint8)
    out = np.empty((n, channels, ch, cw), np.uint8)

    lib = load_native()
    if lib is not None and hasattr(lib, "assemble_batch_u8"):
        ptrs = (ctypes.c_void_p * n)(
            *[im.ctypes.data_as(ctypes.c_void_p) for im in imgs])
        heights = np.asarray([im.shape[0] for im in imgs], np.int32)
        widths = np.asarray([im.shape[1] for im in imgs], np.int32)
        lib.assemble_batch_u8(
            ptrs,
            heights.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            widths.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            n, channels, ch, cw,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            flips.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
            int(n_threads))
        return out

    for i, im in enumerate(imgs):
        oy, ox = int(offsets[i, 0]), int(offsets[i, 1])
        patch = im[oy:oy + ch, ox:ox + cw]
        if flips[i]:
            patch = patch[:, ::-1]
        out[i] = patch.transpose(2, 0, 1)
    return out


def crop_flip_host(images: Sequence[np.ndarray],
                   crop: Tuple[int, int],
                   offsets: np.ndarray,
                   flips: np.ndarray) -> np.ndarray:
    """Host-fallback leg of device-augment ingest: apply crop + flip on
    the host and return a UNIFORM (N, crop_h, crop_w, C) uint8 NHWC
    stack.  Full-frame packing needs one static frame shape per batch;
    when a batch mixes source sizes the packer pre-crops here (this
    module is a declared host-fallback for the ``host-augment-in-hot-
    path`` lint rule) and ships identity ride-alongs — zero offsets,
    zero flips — so ``nn.DeviceAugment`` reduces to the NHWC->NCHW
    transpose and the trained weights stay bit-identical."""
    _check_crop_fits(images, crop)
    ch, cw = crop
    n = len(images)
    channels = images[0].shape[2] if images[0].ndim == 3 else 1
    out = np.empty((n, ch, cw, channels), np.uint8)
    for i, im in enumerate(images):
        if im.ndim != 3:
            im = im[:, :, None]
        oy, ox = int(offsets[i, 0]), int(offsets[i, 1])
        patch = im[oy:oy + ch, ox:ox + cw]
        if flips[i]:
            patch = patch[:, ::-1]
        out[i] = patch
    return out


class MTLabeledBGRImgToBatch(Transformer):
    """Compressed byte records → training MiniBatches, multi-threaded.

    Reference equivalent: ``dataset/image/MTLabeledBGRImgToBatch.scala:46``
    — the production ImageNet ingest stage: JPEG decode + crop + flip +
    normalize + pack, parallel on the host, overlapping device compute.

    Consumes :class:`~bigdl_tpu.dataset.image.LabeledImageBytes` records
    (what ``DataSet.seq_file_folder`` holds — compressed bytes, decoded per
    pass) and emits ``MiniBatch(NCHW float32, labels)``.  JPEG decode runs
    on a thread pool (PIL's libjpeg decompression releases the GIL, so the
    pool scales with host cores); crop/flip/normalize/pack runs in the
    native std::thread assembler (``native/batch.cc``) when built.  Crop
    offsets/flips draw from ``RandomGenerator.RNG()`` on the CALLING
    thread (random crop semantics of the reference's CropRandom + HFlip);
    ``random_crop=False`` center-crops deterministically for eval.
    """

    def __init__(self, batch_size: int, crop: Tuple[int, int] = (224, 224),
                 mean: Sequence[float] = (104.0, 117.0, 123.0),
                 std: Sequence[float] = (1.0, 1.0, 1.0),
                 random_crop: bool = True, hflip: bool = True,
                 n_threads: Optional[int] = None,
                 device_normalize: bool = False,
                 rng=None):
        import os
        self.batch_size = batch_size
        self.crop = crop
        self.mean, self.std = mean, std
        self.random_crop, self.hflip = random_crop, hflip
        self.n_threads = n_threads or max(1, os.cpu_count() or 1)
        # device_normalize: emit RAW uint8 NCHW (crop/flip/pack only) and
        # leave (x - mean)/std to an nn.ChannelNormalize module on device —
        # quarters the host->device bytes (the TPU-first ingest layout)
        self.device_normalize = device_normalize
        # rng: draw crop/flip from THIS RandomGenerator instead of the
        # calling thread's stream — the single-drawer contract made
        # explicit, so a mid-epoch fallback (or a parity oracle) can
        # continue another pipeline's drawer at its exact position
        self._rng = rng

    @staticmethod
    def _decode(data: bytes) -> np.ndarray:
        """JPEG/PNG bytes → BGR uint8 HWC (the reference's BGR layout).

        cv2 when available: measured ~26% faster than PIL on this image's
        libjpeg and emits BGR natively (no channel-reversal copy); PIL
        fallback keeps the path dependency-light."""
        try:
            import cv2
            img = cv2.imdecode(np.frombuffer(data, np.uint8),
                               cv2.IMREAD_COLOR)
            if img is not None:
                return img
        except ImportError:
            pass
        import io
        from PIL import Image
        rgb = np.asarray(Image.open(io.BytesIO(data)).convert("RGB"))
        return rgb[:, :, ::-1]

    def __call__(self, it: Iterator) -> Iterator:
        from concurrent.futures import ThreadPoolExecutor
        from bigdl_tpu.dataset.sample import MiniBatch
        from bigdl_tpu.utils.random_generator import RandomGenerator

        rng = self._rng if self._rng is not None else RandomGenerator.RNG()
        ch, cw = self.crop
        pool = ThreadPoolExecutor(self.n_threads)
        try:
            while True:
                recs = []
                for rec in it:
                    recs.append(rec)
                    if len(recs) == self.batch_size:
                        break
                if not recs:
                    return
                images = list(pool.map(self._decode,
                                       [r.bytes for r in recs]))
                n = len(images)
                offsets = np.empty((n, 2), np.int32)
                flips = np.zeros((n,), np.uint8)
                _check_crop_fits(
                    images, self.crop,
                    describe=lambda i: (
                        f"MTLabeledBGRImgToBatch: record {i} of the "
                        f"current batch (label {recs[i].label})"))
                for i, im in enumerate(images):
                    h, w = im.shape[:2]
                    if self.random_crop:
                        offsets[i] = (rng.random_int(0, h - ch + 1),
                                      rng.random_int(0, w - cw + 1))
                    else:
                        offsets[i] = ((h - ch) // 2, (w - cw) // 2)
                    if self.hflip:
                        flips[i] = rng.uniform() < 0.5
                if self.device_normalize:
                    x = assemble_batch_u8(images, self.crop, offsets, flips,
                                          n_threads=self.n_threads)
                else:
                    x = assemble_batch(images, self.crop, offsets, flips,
                                       self.mean, self.std,
                                       n_threads=self.n_threads)
                y = np.asarray([r.label for r in recs], np.float32)
                yield MiniBatch(x, y)
        finally:
            # cancel_futures: a consumer exiting mid-batch (or a decode
            # error propagating out of pool.map) leaves queued decode
            # futures behind — without cancellation they keep running and
            # pin their records/outputs after the generator is gone
            pool.shutdown(wait=False, cancel_futures=True)


class Prefetch(Transformer):
    """Run the upstream iterator in a daemon thread with a bounded queue
    (the MT producer half of MTLabeledBGRImgToBatch)."""

    def __init__(self, depth: int = 4):
        self.depth = depth

    def __call__(self, it: Iterator) -> Iterator:
        from bigdl_tpu.utils.random_generator import RandomGenerator

        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        _END = object()
        # the upstream iterator (and any randomness it draws — MT crop/flip
        # offsets) executes on the producer thread: it must continue the
        # CONSUMING thread's RandomGenerator stream, same contract as
        # Engine.BatchPrefetcher, or a user's set_seed silently stops
        # governing augmentation whenever Prefetch is in the chain.
        # SINGLE-DRAWER CONTRACT: the RandomState is handed off, not
        # shared — for the lifetime of this iterator the producer is the
        # stream's only drawer.  A consumer that keeps drawing host RNG
        # concurrently (a second pipeline on the same thread-local) gets
        # nondeterministic interleaving; run such pipelines on distinct
        # threads (each thread-local RNG is per-thread) or seed a separate
        # RandomGenerator instance for them.
        rng = RandomGenerator.RNG()

        def put(item) -> bool:
            """Bounded put that gives up when the consumer is gone."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            RandomGenerator.adopt(rng)
            try:
                for item in it:
                    if not put(item):
                        return        # consumer abandoned the generator
                put(_END)
            except BaseException as e:  # surface upstream errors downstream
                put(e)

        t = threading.Thread(target=producer, daemon=True)
        # kept on the instance for diagnostics/tests: the teardown
        # contract below (producer joined, queue left empty) is observable
        self._q, self._producer = q, t
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            # early exit (break/exception/GeneratorExit): release the
            # producer so it does not pin the upstream iterator forever
            stop.set()

            def drain():
                try:
                    while True:
                        q.get_nowait()
                except queue.Empty:
                    pass

            # drain → JOIN → drain: the producer may have passed its stop
            # check and be blocked in put() when we drain — that put lands
            # AFTER the first drain and would pin a full batch in memory
            # forever.  Joining (bounded: the producer exits at its next
            # stop check once the put lands) and draining again guarantees
            # nothing stays queued.
            drain()
            t.join(timeout=5)
            drain()
