"""Multi-threaded image-to-batch assembly + prefetching transformer.

Reference equivalent: ``dataset/image/MTLabeledBGRImgToBatch.scala:46`` —
the parallel CPU path that crops/flips/normalizes decoded images into the
training batch concurrently with compute.

Two pieces:
- :func:`assemble_batch` — pack N HWC uint8 images into one float32 NCHW
  batch (normalize + crop + optional hflip), dispatched to the native
  std::thread implementation (``native/batch.cc``) when built, else numpy.
- :class:`Prefetch` — a transformer that runs its upstream iterator in a
  background thread with a bounded queue, so host-side batch prep overlaps
  device steps (the reference's MT pipeline role in the driver loop).
"""

from __future__ import annotations

import ctypes
import queue
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.dataset.native import load_native
from bigdl_tpu.dataset.transformer import Transformer


def assemble_batch(images: Sequence[np.ndarray],
                   crop: Tuple[int, int],
                   offsets: np.ndarray,
                   flips: np.ndarray,
                   mean: Sequence[float],
                   std: Sequence[float],
                   n_threads: int = 4) -> np.ndarray:
    """images: HWC uint8 arrays (any sizes >= crop); offsets: (N, 2) int32
    (y, x) crop origins; flips: (N,) uint8.  Returns (N, C, crop_h, crop_w)
    float32: out = (crop(img) - mean) / std, optionally h-flipped."""
    n = len(images)
    ch, cw = crop
    channels = images[0].shape[2] if images[0].ndim == 3 else 1
    imgs = [np.ascontiguousarray(
        im if im.ndim == 3 else im[:, :, None], dtype=np.uint8)
        for im in images]
    offsets = np.ascontiguousarray(offsets, dtype=np.int32)
    flips = np.ascontiguousarray(flips, dtype=np.uint8)
    mean_a = np.asarray(mean, np.float32)
    std_a = np.asarray(std, np.float32)
    out = np.empty((n, channels, ch, cw), np.float32)

    lib = load_native()
    if lib is not None:
        ptrs = (ctypes.c_void_p * n)(
            *[im.ctypes.data_as(ctypes.c_void_p) for im in imgs])
        heights = np.asarray([im.shape[0] for im in imgs], np.int32)
        widths = np.asarray([im.shape[1] for im in imgs], np.int32)
        lib.assemble_batch(
            ptrs,
            heights.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            widths.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            n, channels, ch, cw,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            flips.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
            mean_a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            std_a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            int(n_threads))
        return out

    for i, im in enumerate(imgs):
        oy, ox = int(offsets[i, 0]), int(offsets[i, 1])
        patch = im[oy:oy + ch, ox:ox + cw].astype(np.float32)
        if flips[i]:
            patch = patch[:, ::-1]
        out[i] = ((patch - mean_a) / std_a).transpose(2, 0, 1)
    return out


class Prefetch(Transformer):
    """Run the upstream iterator in a daemon thread with a bounded queue
    (the MT producer half of MTLabeledBGRImgToBatch)."""

    def __init__(self, depth: int = 4):
        self.depth = depth

    def __call__(self, it: Iterator) -> Iterator:
        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        _END = object()

        def put(item) -> bool:
            """Bounded put that gives up when the consumer is gone."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for item in it:
                    if not put(item):
                        return        # consumer abandoned the generator
                put(_END)
            except BaseException as e:  # surface upstream errors downstream
                put(e)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            # early exit (break/exception/GeneratorExit): release the
            # producer so it does not pin the upstream iterator forever
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
