"""Hadoop SequenceFile records: the reference's ImageNet storage format.

Reference equivalents: ``dataset/DataSet.scala:500-558`` (``SeqFileFolder``
— training reads Hadoop SequenceFiles of JPEG bytes) and the seq-file
reader/writer in ``dataset/image/``.

Reading prefers the native C++ implementation (``native/seqfile.cc`` via
ctypes); a pure-Python reader/writer covers toolchain-less environments and
fixture generation.  Keys are Hadoop ``Text`` payloads (here: "path label"
strings), values are raw byte blobs (the JPEG), with the ``BytesWritable``
4-byte length prefix the reference's writer produces.

Corruption guard: both readers sanity-cap the per-record length before
allocating — a flipped bit in the 4-byte length field must surface as
"corrupt", not a ~2 GB allocation.  The cap defaults to
``MAX_RECORD_BYTES`` (1 GiB, far beyond any JPEG frame) and is
configurable for legitimately larger records (e.g. a file produced by
:func:`py_write_records` holding multi-GB blobs): either set the module
level ``MAX_RECORD_BYTES`` or pass ``max_record_bytes=`` to
:func:`read_records` / :func:`py_read_records`.  A cap different from the
native reader's compiled-in 1 GiB automatically routes reads through the
Python implementation, so a raised cap can't be misreported as corrupt by
the native path.
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, List, Optional, Tuple

from bigdl_tpu.dataset.native import load_native

SYNC = bytes(range(16))          # fixed sync marker for files we write

#: default per-record sanity cap (bytes); the native reader's is fixed at
#: this value, the Python reader's is configurable per call
_NATIVE_MAX_RECORD_BYTES = 1 << 30
MAX_RECORD_BYTES = _NATIVE_MAX_RECORD_BYTES


# ---------------------------------------------------------------------------
# pure-Python implementation
# ---------------------------------------------------------------------------

def _read_vlong(f) -> Optional[int]:
    b = f.read(1)
    if not b:
        return None
    first = struct.unpack("b", b)[0]
    if first >= -112:
        return first
    neg = first < -120
    n = -(first + 120) if neg else -(first + 112)
    v = 0
    for byte in f.read(n):
        v = (v << 8) | byte
    return ~v if neg else v


def _write_vlong(f, v: int) -> None:
    if -112 <= v <= 127:
        f.write(struct.pack("b", v))
        return
    length = -112
    if v < 0:
        v = ~v
        length = -120
    tmp = v
    n = 0
    while tmp:
        tmp >>= 8
        n += 1
    f.write(struct.pack("b", length - n))
    for i in range(n - 1, -1, -1):
        f.write(bytes([(v >> (8 * i)) & 0xFF]))


def _write_text(f, s: bytes) -> None:
    _write_vlong(f, len(s))
    f.write(s)


def _read_text(f) -> bytes:
    n = _read_vlong(f)
    if n is None or n < 0:
        raise IOError("truncated Text")
    return f.read(n)


def py_read_records(path: str, max_record_bytes: Optional[int] = None
                    ) -> Iterator[Tuple[bytes, bytes]]:
    """(key, value) byte pairs from an uncompressed SequenceFile.

    ``max_record_bytes`` overrides the module-level ``MAX_RECORD_BYTES``
    corruption cap for files with legitimately huge records."""
    cap = MAX_RECORD_BYTES if max_record_bytes is None else max_record_bytes
    with open(path, "rb") as f:
        if f.read(3) != b"SEQ":
            raise IOError(f"{path} is not a SequenceFile")
        version = f.read(1)[0]
        if version < 5:
            raise IOError(f"unsupported SequenceFile version {version}")
        _read_text(f)            # key class
        _read_text(f)            # value class
        compressed, block = f.read(1)[0], f.read(1)[0]
        if compressed or block:
            raise IOError("compressed SequenceFiles are unsupported")
        (meta,) = struct.unpack(">i", f.read(4))
        for _ in range(meta):
            _read_text(f)
            _read_text(f)
        sync = f.read(16)
        while True:
            raw = f.read(4)
            if not raw:          # clean EOF: zero bytes at a boundary
                return
            if len(raw) < 4:     # cut inside the length field
                raise IOError(f"corrupt SequenceFile record in {path}")
            (rec_len,) = struct.unpack(">i", raw)
            if rec_len == -1:
                marker = f.read(16)
                if marker != sync:
                    # includes a SHORT read: a file cut inside the sync
                    # escape is truncation, not clean EOF (the native
                    # reader agrees, native/seqfile.cc)
                    raise IOError(
                        f"corrupt SequenceFile: bad sync marker in {path}")
                continue
            # sanity cap (see module docstring): a flipped length byte
            # must not become a giant read or a silent short record
            if rec_len < 0 or rec_len > cap:
                raise IOError(f"corrupt SequenceFile record in {path}")
            raw_kl = f.read(4)
            if len(raw_kl) < 4:
                raise IOError(f"corrupt SequenceFile record in {path}")
            (key_len,) = struct.unpack(">i", raw_kl)
            if key_len < 0 or key_len > rec_len:
                raise IOError(f"corrupt SequenceFile record in {path}")
            key = f.read(key_len)
            value = f.read(rec_len - key_len)
            if len(key) != key_len or len(value) != rec_len - key_len:
                raise IOError(f"corrupt SequenceFile record in {path}")
            yield key, value


def py_write_records(path: str, records, key_class: str = "org.apache.hadoop.io.Text",
                     value_class: str = "org.apache.hadoop.io.BytesWritable"
                     ) -> None:
    with open(path, "wb") as f:
        f.write(b"SEQ")
        f.write(bytes([6]))
        _write_text(f, key_class.encode())
        _write_text(f, value_class.encode())
        f.write(b"\x00\x00")
        f.write(struct.pack(">i", 0))
        f.write(SYNC)
        since = 0
        for key, value in records:
            if since > 2000:
                f.write(struct.pack(">i", -1))
                f.write(SYNC)
                since = 0
            f.write(struct.pack(">i", len(key) + len(value)))
            f.write(struct.pack(">i", len(key)))
            f.write(key)
            f.write(value)
            since += len(key) + len(value) + 8


# ---------------------------------------------------------------------------
# native-preferred public API
# ---------------------------------------------------------------------------

def read_records(path: str, max_record_bytes: Optional[int] = None
                 ) -> Iterator[Tuple[bytes, bytes]]:
    """(key, value) pairs; native reader when available.

    ``max_record_bytes`` (default: module-level ``MAX_RECORD_BYTES``)
    adjusts the corruption cap; any value other than the native reader's
    compiled-in 1 GiB falls back to the Python reader so the cap is
    actually honoured."""
    import ctypes
    cap = MAX_RECORD_BYTES if max_record_bytes is None else max_record_bytes
    lib = load_native()
    if lib is None or cap != _NATIVE_MAX_RECORD_BYTES:
        yield from py_read_records(path, max_record_bytes=cap)
        return
    handle = lib.seqfile_open(path.encode())
    if not handle:
        raise IOError(f"cannot open SequenceFile {path}")
    try:
        key_p = ctypes.c_char_p()
        val_p = ctypes.c_char_p()
        klen = ctypes.c_int()
        vlen = ctypes.c_int()
        while True:
            rc = lib.seqfile_next(handle, ctypes.byref(key_p),
                                  ctypes.byref(klen), ctypes.byref(val_p),
                                  ctypes.byref(vlen))
            if rc == 0:
                return
            if rc < 0:
                raise IOError(f"corrupt SequenceFile {path}")
            yield (ctypes.string_at(key_p, klen.value),
                   ctypes.string_at(val_p, vlen.value))
    finally:
        lib.seqfile_close(handle)


def write_records(path: str, records) -> None:
    """Write (key, value) byte pairs; native writer when available."""
    lib = load_native()
    if lib is None:
        py_write_records(path, records)
        return
    handle = lib.seqfile_create(path.encode(),
                                b"org.apache.hadoop.io.Text",
                                b"org.apache.hadoop.io.BytesWritable", SYNC)
    if not handle:
        raise IOError(f"cannot create SequenceFile {path}")
    try:
        for key, value in records:
            lib.seqfile_append(handle, key, len(key), value, len(value))
    finally:
        lib.seqfile_close_writer(handle)


# ---------------------------------------------------------------------------
# image-folder convenience (reference SeqFileFolder protocol)
# ---------------------------------------------------------------------------

def _text_frame(payload: bytes) -> bytes:
    """Hadoop ``Text`` serialization: vint length + utf8 bytes (delegates
    to the module's vint helpers)."""
    import io as _io
    buf = _io.BytesIO()
    _write_text(buf, payload)
    return buf.getvalue()


def _text_unframe(raw: bytes) -> bytes:
    import io as _io
    return _read_text(_io.BytesIO(raw))


def write_image_seqfile(path: str, entries: List[Tuple[str, float, bytes]]
                        ) -> None:
    """entries: (name, label, image bytes).  Key = Text("name label") with
    the vint length prefix Hadoop's ``Text.readFields`` expects; value =
    BytesWritable framing (4-byte BE length + data) — byte-compatible with
    the reference's ImageNet seq-file writer."""
    def gen():
        for name, label, data in entries:
            key = _text_frame(f"{name} {label:g}".encode())
            value = struct.pack(">i", len(data)) + data
            yield key, value
    write_records(path, gen())


def read_image_seqfile(path: str) -> Iterator[Tuple[str, float, bytes]]:
    for key, value in read_records(path):
        text = _text_unframe(key).decode()
        name, _, label = text.rpartition(" ")
        (n,) = struct.unpack(">i", value[:4])
        yield name, float(label), value[4:4 + n]
