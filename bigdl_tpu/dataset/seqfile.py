"""Hadoop SequenceFile records: the reference's ImageNet storage format.

Reference equivalents: ``dataset/DataSet.scala:500-558`` (``SeqFileFolder``
— training reads Hadoop SequenceFiles of JPEG bytes) and the seq-file
reader/writer in ``dataset/image/``.

Reading prefers the native C++ implementation (``native/seqfile.cc`` via
ctypes); a pure-Python reader/writer covers toolchain-less environments and
fixture generation.  Keys are Hadoop ``Text`` payloads (here: "path label"
strings), values are raw byte blobs (the JPEG), with the ``BytesWritable``
4-byte length prefix the reference's writer produces.

Corruption guard: both readers sanity-cap the per-record length before
allocating — a flipped bit in the 4-byte length field must surface as
"corrupt", not a ~2 GB allocation.  The cap defaults to
``MAX_RECORD_BYTES`` (1 GiB, far beyond any JPEG frame) and is
configurable for legitimately larger records (e.g. a file produced by
:func:`py_write_records` holding multi-GB blobs): either set the module
level ``MAX_RECORD_BYTES`` or pass ``max_record_bytes=`` to
:func:`read_records` / :func:`py_read_records`.  A cap different from the
native reader's compiled-in 1 GiB automatically routes reads through the
Python implementation, so a raised cap can't be misreported as corrupt by
the native path.
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, List, Optional, Tuple

from bigdl_tpu.dataset.native import load_native

SYNC = bytes(range(16))          # fixed sync marker for files we write

#: default per-record sanity cap (bytes); the native reader's is fixed at
#: this value, the Python reader's is configurable per call
_NATIVE_MAX_RECORD_BYTES = 1 << 30
MAX_RECORD_BYTES = _NATIVE_MAX_RECORD_BYTES


# ---------------------------------------------------------------------------
# pure-Python implementation
# ---------------------------------------------------------------------------

def _read_vlong(f) -> Optional[int]:
    b = f.read(1)
    if not b:
        return None
    first = struct.unpack("b", b)[0]
    if first >= -112:
        return first
    neg = first < -120
    n = -(first + 120) if neg else -(first + 112)
    v = 0
    for byte in f.read(n):
        v = (v << 8) | byte
    return ~v if neg else v


def _write_vlong(f, v: int) -> None:
    if -112 <= v <= 127:
        f.write(struct.pack("b", v))
        return
    length = -112
    if v < 0:
        v = ~v
        length = -120
    tmp = v
    n = 0
    while tmp:
        tmp >>= 8
        n += 1
    f.write(struct.pack("b", length - n))
    for i in range(n - 1, -1, -1):
        f.write(bytes([(v >> (8 * i)) & 0xFF]))


def _write_text(f, s: bytes) -> None:
    _write_vlong(f, len(s))
    f.write(s)


def _read_text(f) -> bytes:
    n = _read_vlong(f)
    if n is None or n < 0:
        raise IOError("truncated Text")
    return f.read(n)


class CorruptRecordError(IOError):
    """A structurally corrupt SequenceFile record (bad length field,
    short read, bad sync marker).  Carries the byte offset of the record
    whose framing broke plus its 0-based record index, so a resilient
    reader can :func:`find_next_sync` past the damage and skip ONE
    record's worth of bytes instead of abandoning the whole shard."""

    #: corrupt bytes re-read as corrupt bytes: a transient-IO retry
    #: (``utils.file_io``) must never absorb this as a blip
    fatal = True

    def __init__(self, path: str, offset: int, record_index: int,
                 detail: str = "corrupt record"):
        super().__init__(
            f"corrupt SequenceFile record {record_index} at offset "
            f"{offset} in {path}: {detail}")
        self.path = path
        self.offset = int(offset)
        self.record_index = int(record_index)


def _read_header(f, path: str) -> bytes:
    """Consume the SequenceFile header, returning the file's sync
    marker; the stream is left positioned at the first record."""
    if f.read(3) != b"SEQ":
        raise IOError(f"{path} is not a SequenceFile")
    version = f.read(1)[0]
    if version < 5:
        raise IOError(f"unsupported SequenceFile version {version}")
    _read_text(f)            # key class
    _read_text(f)            # value class
    compressed, block = f.read(1)[0], f.read(1)[0]
    if compressed or block:
        raise IOError("compressed SequenceFiles are unsupported")
    (meta,) = struct.unpack(">i", f.read(4))
    for _ in range(meta):
        _read_text(f)
        _read_text(f)
    return f.read(16)


def _py_read_from(f, path: str, sync: bytes, cap: int, start_index: int
                  ) -> Iterator[Tuple[bytes, bytes]]:
    """Record loop shared by the plain and resilient Python readers;
    ``f`` is positioned at a record boundary.  Corruption raises
    :class:`CorruptRecordError` carrying the record's offset + index."""
    index = start_index
    while True:
        rec_off = f.tell()
        raw = f.read(4)
        if not raw:          # clean EOF: zero bytes at a boundary
            return
        if len(raw) < 4:     # cut inside the length field
            raise CorruptRecordError(path, rec_off, index,
                                     "truncated length field")
        (rec_len,) = struct.unpack(">i", raw)
        if rec_len == -1:
            marker = f.read(16)
            if marker != sync:
                # includes a SHORT read: a file cut inside the sync
                # escape is truncation, not clean EOF (the native
                # reader agrees, native/seqfile.cc)
                raise CorruptRecordError(path, rec_off, index,
                                         "bad sync marker")
            continue
        # sanity cap (see module docstring): a flipped length byte
        # must not become a giant read or a silent short record
        if rec_len < 0 or rec_len > cap:
            raise CorruptRecordError(
                path, rec_off, index,
                f"implausible record length {rec_len} (cap {cap})")
        raw_kl = f.read(4)
        if len(raw_kl) < 4:
            raise CorruptRecordError(path, rec_off, index,
                                     "truncated key-length field")
        (key_len,) = struct.unpack(">i", raw_kl)
        if key_len < 0 or key_len > rec_len:
            raise CorruptRecordError(
                path, rec_off, index,
                f"key length {key_len} outside record length {rec_len}")
        key = f.read(key_len)
        value = f.read(rec_len - key_len)
        if len(key) != key_len or len(value) != rec_len - key_len:
            raise CorruptRecordError(path, rec_off, index,
                                     "record body truncated")
        yield key, value
        index += 1


def py_read_records(path: str, max_record_bytes: Optional[int] = None
                    ) -> Iterator[Tuple[bytes, bytes]]:
    """(key, value) byte pairs from an uncompressed SequenceFile.

    ``max_record_bytes`` overrides the module-level ``MAX_RECORD_BYTES``
    corruption cap for files with legitimately huge records.  A corrupt
    mid-file record raises :class:`CorruptRecordError` naming the byte
    offset and record index (see :func:`read_records_resilient` for the
    skip-and-continue reader built on it)."""
    cap = MAX_RECORD_BYTES if max_record_bytes is None else max_record_bytes
    with open(path, "rb") as f:
        sync = _read_header(f, path)
        yield from _py_read_from(f, path, sync, cap, 0)


def find_next_sync(path: str, offset: int,
                   sync: Optional[bytes] = None) -> Optional[int]:
    """Byte offset of the first sync escape (``-1`` length + the file's
    16-byte sync marker) at or after ``offset``, or ``None`` when no
    further marker exists.  The resync primitive: a reader that hit a
    corrupt record at offset ``o`` scans from ``o + 1`` and resumes on a
    known record boundary, losing only the records between the damage
    and the marker (the Hadoop recovery semantic) instead of the whole
    shard."""
    with open(path, "rb") as f:
        if sync is None:
            sync = _read_header(f, path)
        needle = struct.pack(">i", -1) + sync
        pos = max(0, int(offset))
        f.seek(pos)
        chunk_size = 1 << 20
        carry = b""
        while True:
            chunk = f.read(chunk_size)
            if not chunk:
                return None
            buf = carry + chunk
            hit = buf.find(needle)
            if hit != -1:
                return pos - len(carry) + hit
            # keep a needle-sized tail so a marker split across chunk
            # boundaries is still found
            carry = buf[-(len(needle) - 1):]
            pos = f.tell()


def read_records_resilient(path: str, on_skip=None,
                           max_record_bytes: Optional[int] = None
                           ) -> Iterator[Tuple[bytes, bytes]]:
    """(key, value) pairs, skipping past structurally corrupt records.

    Where :func:`py_read_records` raises :class:`CorruptRecordError`,
    this reader calls ``on_skip(err, resume_offset)`` (resume_offset is
    None when no later sync marker exists) and continues from the next
    sync marker — the quarantine path's shard reader.  ``on_skip`` may
    itself raise to convert a skip into a hard failure (budget
    exhaustion).  Without sync markers between the damage and EOF the
    remainder of the file is unrecoverable and iteration ends after the
    ``on_skip`` callback.

    Always the Python implementation: the native reader neither reports
    offsets nor resumes mid-file."""
    cap = MAX_RECORD_BYTES if max_record_bytes is None else max_record_bytes
    with open(path, "rb") as f:
        sync = _read_header(f, path)
        index = 0
        while True:
            gen = _py_read_from(f, path, sync, cap, index)
            try:
                for key, value in gen:
                    index += 1
                    yield key, value
                return
            except CorruptRecordError as e:
                if on_skip is None:
                    raise   # resilience needs an observer: silent loss is
                            # exactly what the quarantine exists to prevent
                resume = find_next_sync(path, e.offset + 1, sync)
                on_skip(e, resume)
                if resume is None:
                    return
                f.seek(resume)
                index = e.record_index  # unknown true count; best effort


def py_write_records(path: str, records, key_class: str = "org.apache.hadoop.io.Text",
                     value_class: str = "org.apache.hadoop.io.BytesWritable"
                     ) -> None:
    with open(path, "wb") as f:
        f.write(b"SEQ")
        f.write(bytes([6]))
        _write_text(f, key_class.encode())
        _write_text(f, value_class.encode())
        f.write(b"\x00\x00")
        f.write(struct.pack(">i", 0))
        f.write(SYNC)
        since = 0
        for key, value in records:
            if since > 2000:
                f.write(struct.pack(">i", -1))
                f.write(SYNC)
                since = 0
            f.write(struct.pack(">i", len(key) + len(value)))
            f.write(struct.pack(">i", len(key)))
            f.write(key)
            f.write(value)
            since += len(key) + len(value) + 8


# ---------------------------------------------------------------------------
# native-preferred public API
# ---------------------------------------------------------------------------

def read_records(path: str, max_record_bytes: Optional[int] = None
                 ) -> Iterator[Tuple[bytes, bytes]]:
    """(key, value) pairs; native reader when available.

    ``max_record_bytes`` (default: module-level ``MAX_RECORD_BYTES``)
    adjusts the corruption cap; any value other than the native reader's
    compiled-in 1 GiB falls back to the Python reader so the cap is
    actually honoured."""
    import ctypes
    cap = MAX_RECORD_BYTES if max_record_bytes is None else max_record_bytes
    lib = load_native()
    if lib is None or cap != _NATIVE_MAX_RECORD_BYTES:
        yield from py_read_records(path, max_record_bytes=cap)
        return
    handle = lib.seqfile_open(path.encode())
    if not handle:
        raise IOError(f"cannot open SequenceFile {path}")
    try:
        key_p = ctypes.c_char_p()
        val_p = ctypes.c_char_p()
        klen = ctypes.c_int()
        vlen = ctypes.c_int()
        while True:
            rc = lib.seqfile_next(handle, ctypes.byref(key_p),
                                  ctypes.byref(klen), ctypes.byref(val_p),
                                  ctypes.byref(vlen))
            if rc == 0:
                return
            if rc < 0:
                # the native reader knows only "corrupt"; replay through
                # the Python reader to name the exact offset and record
                # index (cold path — a corrupt shard aborts the sweep
                # anyway, the second pass costs nothing that matters)
                for _ in py_read_records(path, max_record_bytes=cap):
                    pass
                err = IOError(
                    f"corrupt SequenceFile {path} (native reader failed "
                    "but the Python replay read it clean — native/python "
                    "disagreement, check MAX_RECORD_BYTES)")
                err.fatal = True   # permanent: a transient-IO retry
                raise err          # would just re-read the shard twice
            yield (ctypes.string_at(key_p, klen.value),
                   ctypes.string_at(val_p, vlen.value))
    finally:
        lib.seqfile_close(handle)


def write_records(path: str, records) -> None:
    """Write (key, value) byte pairs; native writer when available."""
    lib = load_native()
    if lib is None:
        py_write_records(path, records)
        return
    handle = lib.seqfile_create(path.encode(),
                                b"org.apache.hadoop.io.Text",
                                b"org.apache.hadoop.io.BytesWritable", SYNC)
    if not handle:
        raise IOError(f"cannot create SequenceFile {path}")
    try:
        for key, value in records:
            lib.seqfile_append(handle, key, len(key), value, len(value))
    finally:
        lib.seqfile_close_writer(handle)


# ---------------------------------------------------------------------------
# image-folder convenience (reference SeqFileFolder protocol)
# ---------------------------------------------------------------------------

def _text_frame(payload: bytes) -> bytes:
    """Hadoop ``Text`` serialization: vint length + utf8 bytes (delegates
    to the module's vint helpers)."""
    import io as _io
    buf = _io.BytesIO()
    _write_text(buf, payload)
    return buf.getvalue()


def _text_unframe(raw: bytes) -> bytes:
    import io as _io
    return _read_text(_io.BytesIO(raw))


def write_image_seqfile(path: str, entries: List[Tuple[str, float, bytes]]
                        ) -> None:
    """entries: (name, label, image bytes).  Key = Text("name label") with
    the vint length prefix Hadoop's ``Text.readFields`` expects; value =
    BytesWritable framing (4-byte BE length + data) — byte-compatible with
    the reference's ImageNet seq-file writer."""
    def gen():
        for name, label, data in entries:
            key = _text_frame(f"{name} {label:g}".encode())
            value = struct.pack(">i", len(data)) + data
            yield key, value
    write_records(path, gen())


def read_image_seqfile(path: str) -> Iterator[Tuple[str, float, bytes]]:
    for key, value in read_records(path):
        text = _text_unframe(key).decode()
        name, _, label = text.rpartition(" ")
        (n,) = struct.unpack(">i", value[:4])
        yield name, float(label), value[4:4 + n]


def read_image_seqfile_resilient(path: str, on_skip=None
                                 ) -> Iterator[Tuple[str, float, bytes]]:
    """:func:`read_image_seqfile` over :func:`read_records_resilient`:
    structurally corrupt records resync to the next marker, and a record
    whose FRAMING survived but whose key/value payload no longer parses
    (a bit flip inside the Text key or the BytesWritable prefix) is
    skipped through the same ``on_skip(err, resume_offset)`` protocol
    instead of killing the shard."""
    for key, value in read_records_resilient(path, on_skip=on_skip):
        try:
            text = _text_unframe(key).decode()
            name, _, label = text.rpartition(" ")
            (n,) = struct.unpack(">i", value[:4])
            payload = value[4:4 + n]
            label_f = float(label)
        except (ValueError, IOError, struct.error,
                UnicodeDecodeError) as e:
            if on_skip is None:
                raise
            on_skip(e, None)
            continue
        yield name, label_f, payload
