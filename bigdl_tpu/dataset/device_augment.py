"""On-device augmentation for the real-data hot path (ISSUE 16).

The host MT path (``mt_batch.assemble_batch_u8``) spends its decode
threads on crop + flip + HWC->CHW transpose — per-pixel work that the
chip does for free inside the fused step.  In device-augment mode the
ingest pipeline packs FULL decoded uint8 frames (one cheap ``np.stack``
memcpy) plus two tiny ride-along tensors — the per-record crop offsets
and flip flags drawn from the SAME clone-and-commit RNG stream as the
host path — and these transforms run on device:

``crop_flip_transpose``
    vmapped ``lax.dynamic_slice`` crop + ``where``-select flip +
    NHWC->NCHW transpose over the uint8 batch.  Operation-for-operation
    identical to the host fallback (``im[oy:oy+ch, ox:ox+cw]``,
    ``patch[:, ::-1]``, ``patch.transpose(2, 0, 1)``) on the same bytes
    with the same draws, so trained-weight bit-parity against the host
    path is provable (test_prefetch_determinism.py asserts it).

``color_jitter``
    optional per-record brightness/contrast/saturation jitter keyed by
    ride-along int32 seeds drawn from the clone-and-commit stream —
    replays reproduce bit-exactly.  OFF by default (the host reference
    path has no jitter, so parity only holds with it disabled).

No function here calls ``jax.jit``: the transforms trace into the
tracked fused step (compile_cache.tracked_jit) like any other module
apply, keeping the one-registered-jit-entry-point invariant.  All
shapes are static per (batch, crop) configuration, so the strict
retrace sentinel stays quiet after warmup.
"""

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["crop_flip_transpose", "color_jitter"]


def crop_flip_transpose(frames, offsets, flips, crop_h, crop_w):
    """Crop + horizontal-flip + NHWC->NCHW transpose on device.

    frames:  (N, H, W, C) uint8 full decoded frames
    offsets: (N, 2) int32 ``(oy, ox)`` crop origins (host-drawn)
    flips:   (N,) uint8 flip flags (host-drawn)
    returns  (N, C, crop_h, crop_w) uint8

    Bit-exact mirror of the host path on the same inputs: dynamic_slice
    with host-validated in-bounds origins never clamps, the flip is the
    same ``[:, ::-1]`` reversal, and uint8 survives every step untouched.
    """
    channels = frames.shape[-1]

    def one(frame, off, flip):
        patch = lax.dynamic_slice(
            frame, (off[0], off[1], jnp.int32(0)),
            (crop_h, crop_w, channels))
        patch = jnp.where(flip.astype(jnp.bool_), patch[:, ::-1, :], patch)
        return jnp.transpose(patch, (2, 0, 1))

    return jax.vmap(one)(frames, offsets.astype(jnp.int32),
                         flips.astype(jnp.uint8))


def color_jitter(images, seeds, brightness=0.0, contrast=0.0,
                 saturation=0.0):
    """Per-record ColorJitter over a uint8 NCHW (BGR) batch.

    images: (N, C, H, W) uint8, BGR channel order (cv2 decode layout)
    seeds:  (N,) int32 per-record keys, drawn from the clone-and-commit
            stream by the packer so a replayed batch jitters identically
    Each factor is sampled uniformly from ``[1 - x, 1 + x]``; zero
    disables that leg at trace time (no dead ops in the HLO).  Output is
    rounded, clipped to [0, 255], and returned as uint8 so the module
    chain (DeviceAugment -> ChannelNormalize) is unchanged.
    """

    def one(img, seed):
        key = jax.random.PRNGKey(seed)
        kb, kc, ks = jax.random.split(key, 3)
        x = img.astype(jnp.float32)
        if brightness:
            x = x * jax.random.uniform(
                kb, (), minval=1.0 - brightness, maxval=1.0 + brightness)
        if contrast:
            factor = jax.random.uniform(
                kc, (), minval=1.0 - contrast, maxval=1.0 + contrast)
            mean = jnp.mean(x, keepdims=True)
            x = mean + (x - mean) * factor
        if saturation:
            factor = jax.random.uniform(
                ks, (), minval=1.0 - saturation, maxval=1.0 + saturation)
            # BGR luma: channel 0 is blue, 2 is red.
            gray = (0.114 * x[0] + 0.587 * x[1] + 0.299 * x[2])[None]
            x = gray + (x - gray) * factor
        return jnp.clip(jnp.round(x), 0, 255).astype(jnp.uint8)

    return jax.vmap(one)(images, seeds.astype(jnp.int32))
