"""Image record types and augmentation transformers.

Reference equivalent: ``dataset/image/`` (24 files) — BGR/Grey decode, scale,
center/random crop, HFlip, channel normalizers, ColorJitter, PCA Lighting,
and the to-batch converters.

Representation: a ``LabeledImage`` holds float32 HWC numpy ``data`` plus a
float label.  The reference keeps BGR channel order for OpenCV compatibility
(``dataset/image/Types.scala:284``); loaders here emit BGR too so the
normalization constants line up.  Augmentation runs host-side on numpy
(the TPU sees only the final batched arrays).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.dataset.sample import MiniBatch, Sample
from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.utils.random_generator import RandomGenerator


class LocalImgPath:
    """Path + label record (reference ``LocalLabeledImagePath``)."""

    __slots__ = ("path", "label")

    def __init__(self, path: str, label: float = -1.0):
        self.path = path
        self.label = label


class LabeledImageBytes:
    """Compressed (JPEG/PNG) bytes + label: the seq-file record form — kept
    compressed in memory, decoded per pass (reference keeps byte records in
    the cached RDD and decodes in the transformer chain)."""

    __slots__ = ("name", "label", "bytes")

    def __init__(self, name: str, label: float, data: bytes):
        self.name = name
        self.label = label
        self.bytes = data


class BytesToBGRImg(Transformer):
    """Decode LabeledImageBytes → BGR LabeledImage (reference
    ``BytesToBGRImg``)."""

    def __call__(self, it):
        import io
        from PIL import Image
        for rec in it:
            rgb = np.asarray(Image.open(io.BytesIO(rec.bytes))
                             .convert("RGB"), dtype=np.float32)
            yield LabeledImage(rgb[..., ::-1], rec.label)


class LabeledImage:
    """Float HWC image + label (reference ``LabeledBGRImage`` /
    ``LabeledGreyImage``, ``dataset/image/Types.scala``)."""

    __slots__ = ("data", "label")

    def __init__(self, data: np.ndarray, label: float = -1.0):
        self.data = np.asarray(data, dtype=np.float32)
        self.label = label

    @property
    def height(self) -> int:
        return self.data.shape[0]

    @property
    def width(self) -> int:
        return self.data.shape[1]

    @property
    def channels(self) -> int:
        return 1 if self.data.ndim == 2 else self.data.shape[2]


# ---------------------------------------------------------------------------
# decode / scale
# ---------------------------------------------------------------------------

def _resize_bilinear(img: np.ndarray, h: int, w: int) -> np.ndarray:
    """Pure-numpy bilinear resize (no PIL/cv2 dependency on the hot path)."""
    ih, iw = img.shape[:2]
    if ih == h and iw == w:
        return img.astype(np.float32)
    ys = (np.arange(h) + 0.5) * ih / h - 0.5
    xs = (np.arange(w) + 0.5) * iw / w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, ih - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, iw - 1)
    y1 = np.clip(y0 + 1, 0, ih - 1)
    x1 = np.clip(x0 + 1, 0, iw - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :]
    if img.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    a = img[y0][:, x0]
    b = img[y0][:, x1]
    c = img[y1][:, x0]
    d = img[y1][:, x1]
    top = a * (1 - wx) + b * wx
    bot = c * (1 - wx) + d * wx
    return (top * (1 - wy) + bot * wy).astype(np.float32)


class LocalImgReader(Transformer):
    """Decode image files to BGR float [0,255], scaling the shorter side to
    ``scale_to`` (reference ``LocalImgReader`` + ``BGRImage.readImage``,
    ``dataset/image/Types.scala:284``)."""

    def __init__(self, scale_to: int = 256):
        self.scale_to = scale_to

    def _decode(self, path: str) -> np.ndarray:
        try:
            from PIL import Image  # optional dependency
            rgb = np.asarray(Image.open(path).convert("RGB"), dtype=np.float32)
        except ImportError as e:  # pragma: no cover - PIL is present in image
            raise RuntimeError(
                "image decoding requires PIL; pre-decode to numpy and use "
                "DataSet.array instead") from e
        return rgb[..., ::-1]  # RGB → BGR, matching reference OpenCV order

    def __call__(self, it: Iterator) -> Iterator[LabeledImage]:
        for rec in it:
            img = _scale_shorter_side(self._decode(rec.path), self.scale_to)
            yield LabeledImage(img, rec.label)


class BGRImgToSample(Transformer):
    """HWC image → CHW Sample (reference ``BGRImgToSample``)."""

    def __init__(self, to_rgb: bool = False):
        self.to_rgb = to_rgb

    def __call__(self, it: Iterator) -> Iterator[Sample]:
        for img in it:
            data = img.data
            if data.ndim == 2:
                data = data[..., None]
            if self.to_rgb:
                data = data[..., ::-1]
            chw = np.ascontiguousarray(np.transpose(data, (2, 0, 1)))
            yield Sample(chw, np.float32(img.label))


class GreyImgToSample(BGRImgToSample):
    pass


# ---------------------------------------------------------------------------
# crops / flips
# ---------------------------------------------------------------------------

def _scale_shorter_side(img: np.ndarray, scale_to: int) -> np.ndarray:
    """Shorter side → ``scale_to``, preserving aspect ratio (the reference
    ``BGRImage.scale`` convention shared by reader and Scale transformer)."""
    h, w = img.shape[:2]
    if h < w:
        nh, nw = scale_to, max(1, round(w * scale_to / h))
    else:
        nh, nw = max(1, round(h * scale_to / w)), scale_to
    return _resize_bilinear(img, nh, nw)


class Scale(Transformer):
    """Scale the shorter side to ``scale_to``, preserving aspect ratio
    (reference ``BGRImage.scale`` resize convention)."""

    def __init__(self, scale_to: int):
        self.scale_to = scale_to

    def __call__(self, it: Iterator[LabeledImage]) -> Iterator[LabeledImage]:
        for img in it:
            yield LabeledImage(_scale_shorter_side(img.data, self.scale_to),
                               img.label)


class CenterCrop(Transformer):
    """(reference ``BGRImgCropper`` with CropCenter)."""

    def __init__(self, crop_width: int, crop_height: int):
        self.cw, self.ch = crop_width, crop_height

    def __call__(self, it: Iterator) -> Iterator[LabeledImage]:
        for img in it:
            y = (img.height - self.ch) // 2
            x = (img.width - self.cw) // 2
            yield LabeledImage(img.data[y:y + self.ch, x:x + self.cw],
                               img.label)


class RandomCrop(Transformer):
    """(reference ``BGRImgCropper`` with CropRandom)."""

    def __init__(self, crop_width: int, crop_height: int,
                 padding: int = 0):
        self.cw, self.ch, self.padding = crop_width, crop_height, padding

    def __call__(self, it: Iterator) -> Iterator[LabeledImage]:
        rng = RandomGenerator.RNG()
        for img in it:
            data = img.data
            if self.padding > 0:
                pads = [(self.padding, self.padding),
                        (self.padding, self.padding)] + \
                       ([(0, 0)] if data.ndim == 3 else [])
                data = np.pad(data, pads)
            h, w = data.shape[:2]
            y = rng.random_int(0, h - self.ch + 1)
            x = rng.random_int(0, w - self.cw + 1)
            yield LabeledImage(data[y:y + self.ch, x:x + self.cw], img.label)


class HFlip(Transformer):
    """Random horizontal flip (reference ``HFlip``)."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold

    def __call__(self, it: Iterator) -> Iterator[LabeledImage]:
        rng = RandomGenerator.RNG()
        for img in it:
            if rng.uniform() < self.threshold:
                yield LabeledImage(img.data[:, ::-1], img.label)
            else:
                yield img


# ---------------------------------------------------------------------------
# normalization / color
# ---------------------------------------------------------------------------

class ChannelNormalize(Transformer):
    """Per-channel (x - mean) / std ON THE HOST (reference
    ``BGRImgNormalizer``).  Means/stds are in the image's channel order
    (BGR for BGR images).

    Namespace note: ``bigdl_tpu.nn.ChannelNormalize`` is the DEVICE-side
    sibling (a Module placed first in the model) — pair it with the
    uint8 ingest layout (``MTLabeledBGRImgToBatch(device_normalize=
    True)``) to ship 4x fewer bytes over the host→device link instead
    of normalizing here."""

    def __init__(self, means: Sequence[float], stds: Sequence[float]):
        self.means = np.asarray(means, dtype=np.float32)
        self.stds = np.asarray(stds, dtype=np.float32)

    def __call__(self, it: Iterator) -> Iterator[LabeledImage]:
        for img in it:
            data = img.data
            m, s = self.means, self.stds
            if data.ndim == 2:
                m, s = float(m[0]), float(s[0])
            yield LabeledImage((data - m) / s, img.label)


GreyImgNormalizer = ChannelNormalize
BGRImgNormalizer = ChannelNormalize


class ColorJitter(Transformer):
    """Random brightness/contrast/saturation in random order
    (reference ``dataset/image/ColorJitter.scala:36``; operates on BGR
    float [0,255])."""

    def __init__(self, brightness: float = 0.4, contrast: float = 0.4,
                 saturation: float = 0.4):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation

    @staticmethod
    def _grayscale(img: np.ndarray) -> np.ndarray:
        # BGR weights (reference uses 0.299R + 0.587G + 0.114B)
        g = (0.114 * img[..., 0] + 0.587 * img[..., 1] + 0.299 * img[..., 2])
        return g[..., None]

    def _blend(self, a, b, alpha):
        return a * alpha + b * (1.0 - alpha)

    def __call__(self, it: Iterator) -> Iterator[LabeledImage]:
        rng = RandomGenerator.RNG()
        for img in it:
            data = img.data
            order = rng.permutation(3)
            for op in order:
                if op == 0 and self.brightness > 0:
                    alpha = 1.0 + rng.uniform(-self.brightness, self.brightness)
                    data = self._blend(data, np.zeros_like(data), alpha)
                elif op == 1 and self.contrast > 0:
                    alpha = 1.0 + rng.uniform(-self.contrast, self.contrast)
                    mean = self._grayscale(data).mean()
                    data = self._blend(data, np.full_like(data, mean), alpha)
                elif op == 2 and self.saturation > 0:
                    alpha = 1.0 + rng.uniform(-self.saturation, self.saturation)
                    data = self._blend(data, self._grayscale(data), alpha)
            yield LabeledImage(np.clip(data, 0.0, 255.0), img.label)


class Lighting(Transformer):
    """AlexNet-style PCA color noise (reference ``Lighting``); eigen
    vectors/values of ImageNet RGB, applied in BGR order."""

    # ImageNet PCA (RGB order as published); rows re-ordered for BGR data.
    _eigval = np.array([0.2175, 0.0188, 0.0045], dtype=np.float32)
    _eigvec_rgb = np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]], dtype=np.float32)

    def __init__(self, alphastd: float = 0.1):
        self.alphastd = alphastd
        self._eigvec_bgr = self._eigvec_rgb[::-1]

    def __call__(self, it: Iterator) -> Iterator[LabeledImage]:
        rng = RandomGenerator.RNG()
        for img in it:
            alpha = rng.np.normal(0.0, self.alphastd, size=3).astype(np.float32)
            noise = (self._eigvec_bgr * alpha * self._eigval).sum(axis=1)
            yield LabeledImage(img.data + noise, img.label)


# ---------------------------------------------------------------------------
# batching
# ---------------------------------------------------------------------------

class BGRImgToBatch(Transformer):
    """Images → CHW MiniBatch (reference ``BGRImgToBatch``)."""

    def __init__(self, batch_size: int, to_rgb: bool = False):
        self.batch_size = batch_size
        self.to_rgb = to_rgb

    def __call__(self, it: Iterator) -> Iterator[MiniBatch]:
        feats: List[np.ndarray] = []
        labels: List[float] = []
        for img in it:
            data = img.data
            if data.ndim == 2:
                data = data[..., None]
            if self.to_rgb:
                data = data[..., ::-1]
            feats.append(np.transpose(data, (2, 0, 1)))
            labels.append(img.label)
            if len(feats) == self.batch_size:
                yield MiniBatch(np.stack(feats),
                                np.asarray(labels, dtype=np.float32))
                feats, labels = [], []
        if feats:
            yield MiniBatch(np.stack(feats),
                            np.asarray(labels, dtype=np.float32))


GreyImgToBatch = BGRImgToBatch
