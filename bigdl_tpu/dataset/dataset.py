"""DataSet: the training-data abstraction.

Reference equivalents: ``dataset/DataSet.scala:46`` (``AbstractDataSet``:
``data(train)`` returns a looped-infinite (train) or finite (eval) stream;
``shuffle()``; ``transform/->`` composition), ``:110`` (``LocalDataSet``),
``:164`` (``DistributedDataSet``), ``:240-314`` (``CachedDistriDataSet``:
in-memory records + a separately shuffled index array).

TPU-native notes: records stay host-side numpy until the jit boundary.  The
epoch/shuffle protocol is reproduced exactly (shuffled index array over a
cached record array; infinite looping iterator for training) because the
north-star metric is epoch-to-accuracy parity (SURVEY §7 hard parts).

``ShardedDataSet`` is the DistributedDataSet analog: it splits records into
``partition_num`` shards (one per data-parallel device/host) and hands each
shard its own looped iterator — the reference's "one Spark partition = one
model replica group" tier, minus Spark (which orchestrates ingest in the
full deployment; the in-process sharded form is what feeds pjit).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.utils.random_generator import RandomGenerator

logger = logging.getLogger("bigdl_tpu")


class AbstractDataSet:
    """(reference ``AbstractDataSet``, ``dataset/DataSet.scala:46``)."""

    def data(self, train: bool) -> Iterator:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def shuffle(self) -> None:
        raise NotImplementedError

    def transform(self, transformer: Transformer) -> "AbstractDataSet":
        raise NotImplementedError

    def __rshift__(self, transformer: Transformer) -> "AbstractDataSet":
        return self.transform(transformer)


class LocalDataSet(AbstractDataSet):
    """In-memory record array + shuffled index (reference ``LocalArrayDataSet``
    + the CachedDistriDataSet index-shuffle protocol,
    ``dataset/DataSet.scala:251-299``)."""

    def __init__(self, records: Sequence[Any],
                 transformers: Optional[List[Transformer]] = None):
        self.records = list(records)
        self.index = np.arange(len(self.records))
        self.transformers: List[Transformer] = list(transformers or [])

    def size(self) -> int:
        return len(self.records)

    def shuffle(self, rng=None) -> None:
        (rng if rng is not None else RandomGenerator.RNG()).shuffle(
            self.index)

    def transform(self, transformer: Transformer) -> "LocalDataSet":
        ds = LocalDataSet.__new__(LocalDataSet)
        ds.records = self.records
        ds.index = self.index      # shared: shuffle() visible through views
        ds.transformers = self.transformers + [transformer]
        return ds

    def _raw(self, train: bool) -> Iterator:
        if train:
            # looped-infinite, re-reading the (possibly re-shuffled) index
            def gen():
                while True:
                    for i in self.index:
                        yield self.records[i]
            return gen()
        return (self.records[i] for i in self.index)

    def data(self, train: bool) -> Iterator:
        it = self._raw(train)
        for t in self.transformers:
            it = t(it)
        return it


class _ShardView(LocalDataSet):
    """One partition's window onto the parent :class:`ShardedDataSet`:
    the FULL record list (a shared reference, never a copy) plus a numpy
    slice VIEW of the parent's global shuffle index.  The parent permutes
    that index in place, so every shard sees each epoch's new order
    without any per-shard reshuffle."""

    def __init__(self, records: Sequence[Any], index_view: np.ndarray,
                 transformers: Optional[List[Transformer]] = None):
        self.records = records
        self.index = index_view
        self.transformers: List[Transformer] = list(transformers or [])

    def size(self) -> int:
        return len(self.index)

    def transform(self, transformer: Transformer) -> "_ShardView":
        return _ShardView(self.records, self.index,
                          self.transformers + [transformer])


class ShardedDataSet(AbstractDataSet):
    """Partition-sharded dataset — the DistributedDataSet analog
    (reference ``CachedDistriDataSet``, ``dataset/DataSet.scala:240-314``:
    in-memory records + a separately shuffled index, coalesced to exactly
    nodeNumber partitions).

    ``data(train=True)`` yields per-shard iterators via :meth:`shard_data`;
    the distributed optimizer zips shard streams into one global step.

    **Partition-count-invariant order.**  One GLOBAL index permutation is
    shuffled per epoch (seeded by ``(global seed, round)`` only — never by
    partition id or count) and partition ``p`` streams the contiguous
    slice ``index[p*per:(p+1)*per]`` of it.  Under the full-epoch-batch
    protocol the assembled global batch is therefore the SAME record
    sequence whatever ``partition_num`` is — the property elastic
    training leans on: a run checkpointed on N devices and resumed on M
    replays the identical batch stream, so trajectory parity across the
    topology change is decided by arithmetic alone.  (With several
    batches per epoch the per-batch *composition* still follows the
    partition slicing — only the epoch-level order is invariant.)
    CAVEAT: the permutation runs over the TRUNCATED count
    ``per * partition_num``, so the invariance needs ``len(records)``
    divisible by both partition counts — a remainder is dropped (warned
    at construction) and makes the epoch order depend on the count.  The
    per-shard split-with-its-own-RNG protocol this replaces made the
    batch sequence a function of the partition count, which also made
    any per-shard-group statistic — the MoE load-balancing loss — differ
    between topologies.

    Multi-host: pass ``local_partitions`` (the data-axis partition ids this
    process's devices own — :func:`bigdl_tpu.parallel.distri_optimizer.
    local_data_partitions` computes them from the mesh) and only those
    shard views are constructed; every process builds the SAME logical
    dataset (same ``records`` order, same ``partition_num``, same global
    shuffle seed) so all processes derive the same epoch order.

    **Memory.**  The global permutation can route ANY record to any
    partition each epoch, so under it every process retains the full
    record list for the dataset's lifetime — ``P`` hosts hold ``P`` x the
    records a partition-local scheme would.  Jobs sized against per-host
    memory can opt out with ``bigdl.elastic.globalShuffle=false``
    (or ``global_shuffle=False``): shards then copy ONLY their own
    contiguous record block (the caller's full list is droppable after
    construction — the pre-elastic footprint) and shuffle within it,
    pure in ``(seed, round, partition)``.  Same-topology resume parity
    is preserved; what is given up is the partition-count-invariant
    batch stream, i.e. an elastic N->M restore continues from exact
    weights but not the identical batch sequence.
    """

    def __init__(self, records: Sequence[Any], partition_num: int,
                 transformers: Optional[List[Transformer]] = None,
                 local_partitions: Optional[Sequence[int]] = None,
                 global_shuffle: Optional[bool] = None):
        if global_shuffle is None:
            from bigdl_tpu.utils import config
            global_shuffle = config.get_bool(
                "bigdl.elastic.globalShuffle", True)
        self.global_shuffle = bool(global_shuffle)
        self.partition_num = partition_num
        n = len(records)
        if n < partition_num:
            raise ValueError(f"{n} records < {partition_num} partitions")
        if local_partitions is None:
            local_partitions = range(partition_num)
        self.local_partitions = sorted(set(local_partitions))
        if not self.local_partitions or not all(
                0 <= p < partition_num for p in self.local_partitions):
            raise ValueError(
                f"local_partitions {self.local_partitions} must be a "
                f"non-empty subset of range({partition_num})")
        # truncate to equal shard size (static shapes for XLA); the
        # remainder count is recorded so evaluation paths can surface it
        self._per = n // partition_num
        self.dropped_records = n - self._per * partition_num
        if self.global_shuffle and self.dropped_records:
            # the permutation runs over per*partition_num records, so a
            # truncated remainder makes the epoch order (and size) a
            # function of the partition count after all — elastic N->M
            # replay parity needs len(records) divisible by BOTH counts
            logger.warning(
                "ShardedDataSet drops %d remainder record(s) at "
                "partition_num=%d: the epoch permutation is over the "
                "truncated count, so the batch stream is NOT "
                "partition-count-invariant across an elastic topology "
                "change (weights still restore exactly; the replayed "
                "batch sequence differs)", self.dropped_records,
                partition_num)
        self._shuffle_round = [0]      # shared across transform() views
        self.shards: dict = {}
        if self.global_shuffle:
            self._records = list(records)
            #: the ONE global epoch permutation; shards hold slice views
            self.index = np.arange(self._per * partition_num)
            for p in self.local_partitions:
                view = self.index[p * self._per:(p + 1) * self._per]
                self.shards[p] = _ShardView(self._records, view,
                                            transformers)
        else:
            # partition-local: shard p copies records[p*per:(p+1)*per]
            # only — non-local records are not retained on this process
            self._records = None
            self.index = None
            for p in self.local_partitions:
                block = list(records[p * self._per:(p + 1) * self._per])
                self.shards[p] = _ShardView(block, np.arange(self._per),
                                            transformers)

    def size(self) -> int:
        """GLOBAL record count (all partitions, held locally or not) — the
        trainer's epoch accounting must agree across processes."""
        return self._per * self.partition_num

    def shuffle(self) -> None:
        """Permute the GLOBAL index in place, as a PURE function of
        ``(global seed, round)`` — each round's permutation regenerates
        from the identity order, never by composing onto the previous
        round's.  Three consumers lean on that purity: partition count
        independence (any topology derives the same epoch order),
        multi-host alignment (every process derives it — the reference
        keeps aligned per-partition RNGs for this,
        ``dataset/DataSet.scala:262``), and elastic resume
        (:meth:`set_shuffle_round` fast-forwards a fresh dataset to the
        interrupted run's round, replaying the exact epoch orders an
        uninterrupted run would have drawn)."""
        base = RandomGenerator.RNG().get_seed()
        self._shuffle_round[0] += 1
        rnd = self._shuffle_round[0]
        if not self.global_shuffle:
            # partition-local mode: each shard permutes its own block,
            # pure in (seed, round, partition) — same-topology replay
            # still works, the cross-topology invariance does not apply
            for p, shard in self.shards.items():
                seed = (base + 0x9E3779B1 * rnd +
                        0x85EBCA77 * (p + 1)) % (2 ** 32)
                idx = np.arange(len(shard.index))
                np.random.RandomState(seed).shuffle(idx)
                shard.index[:] = idx
            return
        seed = (base + 0x9E3779B1 * rnd) % (2 ** 32)
        idx = np.arange(len(self.index))
        np.random.RandomState(seed).shuffle(idx)
        # in-place assignment: shard slice views track the same buffer
        self.index[:] = idx

    def set_shuffle_round(self, round_: int) -> None:
        """Fast-forward (or rewind) the shuffle round counter: a resumed
        run sets ``epoch - 1`` before its first ``shuffle()`` so epoch E
        trains on the SAME permutation the interrupted run drew for
        epoch E — the last piece of cross-restart batch-stream parity
        (shuffles are pure in ``(seed, round)``, see :meth:`shuffle`)."""
        self._shuffle_round[0] = int(round_)

    def transform(self, transformer: Transformer) -> "ShardedDataSet":
        ds = ShardedDataSet.__new__(ShardedDataSet)
        ds.global_shuffle = self.global_shuffle
        ds.partition_num = self.partition_num
        ds.local_partitions = self.local_partitions
        ds._per = self._per
        ds.dropped_records = self.dropped_records
        ds._shuffle_round = self._shuffle_round
        ds._records = self._records
        ds.index = self.index
        ds.shards = {p: s.transform(transformer)
                     for p, s in self.shards.items()}
        return ds

    def shard_data(self, shard: int, train: bool) -> Iterator:
        if shard not in self.shards:
            raise ValueError(
                f"partition {shard} is not local to this process "
                f"(local_partitions={self.local_partitions})")
        return self.shards[shard].data(train)

    def data(self, train: bool) -> Iterator:
        """Interleaved stream over the LOCAL partitions (eval convenience)."""
        its = [self.shards[p].data(train) for p in self.local_partitions]
        if train:
            while True:
                for it in its:
                    yield next(it)
        else:
            exhausted = [False] * len(its)
            while not all(exhausted):
                for i, it in enumerate(its):
                    if exhausted[i]:
                        continue
                    try:
                        yield next(it)
                    except StopIteration:
                        exhausted[i] = True


class DataSet:
    """Factory namespace (reference ``object DataSet``,
    ``dataset/DataSet.scala:319-558``)."""

    @staticmethod
    def array(records: Sequence[Any],
              partition_num: Optional[int] = None) -> AbstractDataSet:
        if partition_num is None or partition_num <= 1:
            return LocalDataSet(records)
        return ShardedDataSet(records, partition_num)

    @staticmethod
    def seq_file_folder(path: str,
                        shards: Optional[int] = None) -> "LocalDataSet":
        """Hadoop SequenceFile tree of JPEG records (reference
        ``SeqFileFolder.files``, ``dataset/DataSet.scala:500-558``): every
        ``*.seq`` under ``path``.  Records hold the COMPRESSED bytes
        (ImageNet scale must not decode up-front); a built-in transformer
        decodes to BGR :class:`~bigdl_tpu.dataset.image.LabeledImage`
        per epoch pass.

        Loading streams through
        :class:`~bigdl_tpu.dataset.ingest.ShardedSeqFileReader`
        (``shards`` reader threads, default ``bigdl.ingest.shards``) — IO
        and record parsing of the files overlap, while the record ORDER
        stays exactly the sorted-walk sequence a sequential sweep yields
        (the sharded reader's merge contract)."""
        from bigdl_tpu.dataset.image import BytesToBGRImg
        from bigdl_tpu.dataset.ingest import ShardedSeqFileReader

        records = list(ShardedSeqFileReader(path, shards=shards))
        return LocalDataSet(records, [BytesToBGRImg()])

    @staticmethod
    def image_folder(path: str, scale_to: int = 256) -> "LocalDataSet":
        """Label-per-subdirectory image tree (reference
        ``ImageFolder.paths``, ``dataset/DataSet.scala:419``).  Labels are
        1-based float32 in subdirectory sort order, like the reference."""
        import os
        from bigdl_tpu.dataset.image import LocalImgPath
        classes = sorted(d for d in os.listdir(path)
                         if os.path.isdir(os.path.join(path, d)))
        records = []
        for label, cls in enumerate(classes, start=1):
            d = os.path.join(path, cls)
            for f in sorted(os.listdir(d)):
                if f.lower().endswith((".jpg", ".jpeg", ".png", ".bmp")):
                    records.append(LocalImgPath(os.path.join(d, f),
                                                float(label)))
        return LocalDataSet(records)
