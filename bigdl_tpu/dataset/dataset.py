"""DataSet: the training-data abstraction.

Reference equivalents: ``dataset/DataSet.scala:46`` (``AbstractDataSet``:
``data(train)`` returns a looped-infinite (train) or finite (eval) stream;
``shuffle()``; ``transform/->`` composition), ``:110`` (``LocalDataSet``),
``:164`` (``DistributedDataSet``), ``:240-314`` (``CachedDistriDataSet``:
in-memory records + a separately shuffled index array).

TPU-native notes: records stay host-side numpy until the jit boundary.  The
epoch/shuffle protocol is reproduced exactly (shuffled index array over a
cached record array; infinite looping iterator for training) because the
north-star metric is epoch-to-accuracy parity (SURVEY §7 hard parts).

``ShardedDataSet`` is the DistributedDataSet analog: it splits records into
``partition_num`` shards (one per data-parallel device/host) and hands each
shard its own looped iterator — the reference's "one Spark partition = one
model replica group" tier, minus Spark (which orchestrates ingest in the
full deployment; the in-process sharded form is what feeds pjit).
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.utils.random_generator import RandomGenerator


class AbstractDataSet:
    """(reference ``AbstractDataSet``, ``dataset/DataSet.scala:46``)."""

    def data(self, train: bool) -> Iterator:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def shuffle(self) -> None:
        raise NotImplementedError

    def transform(self, transformer: Transformer) -> "AbstractDataSet":
        raise NotImplementedError

    def __rshift__(self, transformer: Transformer) -> "AbstractDataSet":
        return self.transform(transformer)


class LocalDataSet(AbstractDataSet):
    """In-memory record array + shuffled index (reference ``LocalArrayDataSet``
    + the CachedDistriDataSet index-shuffle protocol,
    ``dataset/DataSet.scala:251-299``)."""

    def __init__(self, records: Sequence[Any],
                 transformers: Optional[List[Transformer]] = None):
        self.records = list(records)
        self.index = np.arange(len(self.records))
        self.transformers: List[Transformer] = list(transformers or [])

    def size(self) -> int:
        return len(self.records)

    def shuffle(self, rng=None) -> None:
        (rng if rng is not None else RandomGenerator.RNG()).shuffle(
            self.index)

    def transform(self, transformer: Transformer) -> "LocalDataSet":
        ds = LocalDataSet.__new__(LocalDataSet)
        ds.records = self.records
        ds.index = self.index      # shared: shuffle() visible through views
        ds.transformers = self.transformers + [transformer]
        return ds

    def _raw(self, train: bool) -> Iterator:
        if train:
            # looped-infinite, re-reading the (possibly re-shuffled) index
            def gen():
                while True:
                    for i in self.index:
                        yield self.records[i]
            return gen()
        return (self.records[i] for i in self.index)

    def data(self, train: bool) -> Iterator:
        it = self._raw(train)
        for t in self.transformers:
            it = t(it)
        return it


class ShardedDataSet(AbstractDataSet):
    """Partition-sharded dataset — the DistributedDataSet analog
    (reference ``CachedDistriDataSet``, ``dataset/DataSet.scala:240-314``:
    per-partition record arrays, per-partition shuffled indexes, coalesced to
    exactly nodeNumber partitions).

    ``data(train=True)`` yields per-shard iterators via :meth:`shard_data`;
    the distributed optimizer zips shard streams into one global step.

    Multi-host: pass ``local_partitions`` (the data-axis partition ids this
    process's devices own — :func:`bigdl_tpu.parallel.distri_optimizer.
    local_data_partitions` computes them from the mesh) and only those
    shards are materialized; every process constructs the SAME logical
    dataset (same ``records`` order, same ``partition_num``) but holds just
    its slice — the reference keeps per-partition records on the executor
    that owns the partition (``dataset/DataSet.scala:240-314``), never the
    whole set on one node.  ``size()``/``shuffle()`` stay globally
    consistent (size counts all partitions; the shared shuffle seed keeps
    shard index permutations aligned across processes).
    """

    def __init__(self, records: Sequence[Any], partition_num: int,
                 transformers: Optional[List[Transformer]] = None,
                 local_partitions: Optional[Sequence[int]] = None):
        self.partition_num = partition_num
        n = len(records)
        if n < partition_num:
            raise ValueError(f"{n} records < {partition_num} partitions")
        if local_partitions is None:
            local_partitions = range(partition_num)
        self.local_partitions = sorted(set(local_partitions))
        if not self.local_partitions or not all(
                0 <= p < partition_num for p in self.local_partitions):
            raise ValueError(
                f"local_partitions {self.local_partitions} must be a "
                f"non-empty subset of range({partition_num})")
        # round-robin assignment keeps shard sizes within 1 of each other,
        # then truncate to equal size (static shapes for XLA); the
        # remainder count is recorded so evaluation paths can surface it
        self._per = n // partition_num
        self.dropped_records = n - self._per * partition_num
        self._shuffle_round = [0]      # shared across transform() views
        self.shards: dict = {}
        for p in self.local_partitions:
            recs = [records[i] for i in range(p, self._per * partition_num,
                                              partition_num)]
            self.shards[p] = LocalDataSet(recs, transformers)

    def size(self) -> int:
        """GLOBAL record count (all partitions, held locally or not) — the
        trainer's epoch accounting must agree across processes."""
        return self._per * self.partition_num

    def shuffle(self) -> None:
        """Per-shard permutations seeded by (global seed, round, partition
        id) — independent of which process holds the shard or how many
        shards are local, so every multi-host process derives the SAME
        epoch order (the reference keeps per-partition RNGs on the
        executors for the same reason, ``dataset/DataSet.scala:262``)."""
        base = RandomGenerator.RNG().get_seed()
        self._shuffle_round[0] += 1
        rnd = self._shuffle_round[0]
        for p, s in self.shards.items():
            seed = (base + 0x9E3779B1 * rnd + 7919 * p) % (2 ** 32)
            s.shuffle(np.random.RandomState(seed))

    def transform(self, transformer: Transformer) -> "ShardedDataSet":
        ds = ShardedDataSet.__new__(ShardedDataSet)
        ds.partition_num = self.partition_num
        ds.local_partitions = self.local_partitions
        ds._per = self._per
        ds.dropped_records = self.dropped_records
        ds._shuffle_round = self._shuffle_round
        ds.shards = {p: s.transform(transformer)
                     for p, s in self.shards.items()}
        return ds

    def shard_data(self, shard: int, train: bool) -> Iterator:
        if shard not in self.shards:
            raise ValueError(
                f"partition {shard} is not local to this process "
                f"(local_partitions={self.local_partitions})")
        return self.shards[shard].data(train)

    def data(self, train: bool) -> Iterator:
        """Interleaved stream over the LOCAL partitions (eval convenience)."""
        its = [self.shards[p].data(train) for p in self.local_partitions]
        if train:
            while True:
                for it in its:
                    yield next(it)
        else:
            exhausted = [False] * len(its)
            while not all(exhausted):
                for i, it in enumerate(its):
                    if exhausted[i]:
                        continue
                    try:
                        yield next(it)
                    except StopIteration:
                        exhausted[i] = True


class DataSet:
    """Factory namespace (reference ``object DataSet``,
    ``dataset/DataSet.scala:319-558``)."""

    @staticmethod
    def array(records: Sequence[Any],
              partition_num: Optional[int] = None) -> AbstractDataSet:
        if partition_num is None or partition_num <= 1:
            return LocalDataSet(records)
        return ShardedDataSet(records, partition_num)

    @staticmethod
    def seq_file_folder(path: str,
                        shards: Optional[int] = None) -> "LocalDataSet":
        """Hadoop SequenceFile tree of JPEG records (reference
        ``SeqFileFolder.files``, ``dataset/DataSet.scala:500-558``): every
        ``*.seq`` under ``path``.  Records hold the COMPRESSED bytes
        (ImageNet scale must not decode up-front); a built-in transformer
        decodes to BGR :class:`~bigdl_tpu.dataset.image.LabeledImage`
        per epoch pass.

        Loading streams through
        :class:`~bigdl_tpu.dataset.ingest.ShardedSeqFileReader`
        (``shards`` reader threads, default ``bigdl.ingest.shards``) — IO
        and record parsing of the files overlap, while the record ORDER
        stays exactly the sorted-walk sequence a sequential sweep yields
        (the sharded reader's merge contract)."""
        from bigdl_tpu.dataset.image import BytesToBGRImg
        from bigdl_tpu.dataset.ingest import ShardedSeqFileReader

        records = list(ShardedSeqFileReader(path, shards=shards))
        return LocalDataSet(records, [BytesToBGRImg()])

    @staticmethod
    def image_folder(path: str, scale_to: int = 256) -> "LocalDataSet":
        """Label-per-subdirectory image tree (reference
        ``ImageFolder.paths``, ``dataset/DataSet.scala:419``).  Labels are
        1-based float32 in subdirectory sort order, like the reference."""
        import os
        from bigdl_tpu.dataset.image import LocalImgPath
        classes = sorted(d for d in os.listdir(path)
                         if os.path.isdir(os.path.join(path, d)))
        records = []
        for label, cls in enumerate(classes, start=1):
            d = os.path.join(path, cls)
            for f in sorted(os.listdir(d)):
                if f.lower().endswith((".jpg", ".jpeg", ".png", ".bmp")):
                    records.append(LocalImgPath(os.path.join(d, f),
                                                float(label)))
        return LocalDataSet(records)
