"""Transformer: composable iterator-to-iterator data transforms.

Reference equivalent: ``dataset/Transformer.scala:44`` — a serializable
``Iterator[A] → Iterator[B]`` function with ``->`` chaining, cloned per Spark
partition.  Here transformers are picklable Python callables over iterators;
chaining composes with ``>>`` (or ``chain``).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.sample import MiniBatch, PaddingParam, Sample


class Transformer:
    """Base: subclasses implement ``__call__(iterator) -> iterator``."""

    def __call__(self, it: Iterator) -> Iterator:
        raise NotImplementedError(type(self).__name__)

    def __rshift__(self, other: "Transformer") -> "ChainedTransformer":
        return ChainedTransformer(self, other)

    # reference spelling: ``prev -> next``
    def chain(self, other: "Transformer") -> "ChainedTransformer":
        return self >> other

    def apply_single(self, item):
        """Convenience: run on one element."""
        return next(iter(self([item])))


class ChainedTransformer(Transformer):
    """(reference ``ChainedTransformer``, ``dataset/Transformer.scala:86``)."""

    def __init__(self, *stages: Transformer):
        flat: List[Transformer] = []
        for s in stages:
            if isinstance(s, ChainedTransformer):
                flat.extend(s.stages)
            else:
                flat.append(s)
        self.stages = flat

    def __call__(self, it: Iterator) -> Iterator:
        for s in self.stages:
            it = s(it)
        return it


class Identity(Transformer):
    def __call__(self, it: Iterator) -> Iterator:
        return iter(it)


class FuncTransformer(Transformer):
    """Wrap a per-element function."""

    def __init__(self, fn: Callable[[Any], Any]):
        self.fn = fn

    def __call__(self, it: Iterator) -> Iterator:
        return (self.fn(x) for x in it)


class SampleToMiniBatch(Transformer):
    """Group a Sample stream into MiniBatches
    (reference ``SampleToMiniBatch``, ``dataset/Transformer.scala:309``).

    ``total_batch`` is the GLOBAL batch size; the per-iterator batch is
    ``total_batch / partition_num`` exactly as the reference divides per
    partition (``dataset/Utils.scala:25``).  Incomplete trailing batches are
    emitted (the looped-infinite training iterator never produces one).
    """

    def __init__(self, total_batch: int, partition_num: int = 1,
                 feature_padding: Optional[PaddingParam] = None,
                 label_padding: Optional[PaddingParam] = None):
        if total_batch % partition_num != 0:
            raise ValueError(
                f"total batch size {total_batch} must be divisible by "
                f"partition number {partition_num} (reference dataset/Utils.scala:25)")
        self.batch_per_partition = total_batch // partition_num
        self.feature_padding = feature_padding
        self.label_padding = label_padding

    def __call__(self, it: Iterator) -> Iterator[MiniBatch]:
        buf: List[Sample] = []
        for s in it:
            buf.append(s)
            if len(buf) == self.batch_per_partition:
                yield MiniBatch.from_samples(buf, self.feature_padding,
                                             self.label_padding)
                buf = []
        if buf:
            yield MiniBatch.from_samples(buf, self.feature_padding,
                                         self.label_padding)


# Alias for the older reference name (``SampleToBatch``).
SampleToBatch = SampleToMiniBatch
