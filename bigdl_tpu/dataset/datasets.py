"""Dataset file-format loaders: MNIST idx, CIFAR-10 binary, GloVe, + synthetic.

Reference equivalents: ``pyspark/bigdl/dataset/mnist.py`` (idx parsing),
``models/vgg/Utils.scala`` (CIFAR-10 binary), ``pyspark/bigdl/dataset/glove``.
Downloads are out of scope (egress-free environment): loaders read local
files; ``synthetic_*`` generators provide deterministic stand-ins for tests
and perf harnesses (the reference's DistriOptimizerPerf does the same,
``models/utils/DistriOptimizerPerf.scala:82``).
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Dict, List, Tuple

import numpy as np

from bigdl_tpu.dataset.image import LabeledImage


def _open_maybe_gz(path: str):
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


def load_mnist_images(path: str) -> np.ndarray:
    """Parse an MNIST idx3 image file → (N, 28, 28) float32
    (reference ``pyspark/bigdl/dataset/mnist.py`` extract_images)."""
    with _open_maybe_gz(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise ValueError(f"bad idx3 magic {magic} in {path}")
        data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
    return data.reshape(n, rows, cols).astype(np.float32)


def load_mnist_labels(path: str) -> np.ndarray:
    """Parse an MNIST idx1 label file → (N,) float32, 1-based classes
    (BigDL labels are 1-based: reference models/lenet/Train pipeline)."""
    with _open_maybe_gz(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise ValueError(f"bad idx1 magic {magic} in {path}")
        labels = np.frombuffer(f.read(n), dtype=np.uint8)
    return labels.astype(np.float32) + 1.0


# Reference normalization constants (models/lenet/Utils.scala)
MNIST_TRAIN_MEAN = 0.13066047740239506 * 255
MNIST_TRAIN_STD = 0.3081078 * 255


def load_mnist(folder: str, split: str = "train") -> List[LabeledImage]:
    prefix = "train" if split == "train" else "t10k"
    imgs = labels = None
    for suffix in ("-images-idx3-ubyte", "-images.idx3-ubyte"):
        for ext in ("", ".gz"):
            p = os.path.join(folder, prefix + suffix + ext)
            if os.path.exists(p):
                imgs = load_mnist_images(p)
                labels = load_mnist_labels(
                    p.replace("images", "labels").replace("idx3", "idx1"))
                break
        if imgs is not None:
            break
    if imgs is None:
        raise FileNotFoundError(f"no MNIST idx files under {folder}")
    return [LabeledImage(im, lb) for im, lb in zip(imgs, labels)]


# CIFAR-10 BGR means/stds over [0,255] (reference models/vgg/Utils pipeline)
CIFAR_MEAN_BGR = (113.8653, 122.95, 125.307)
CIFAR_STD_BGR = (66.705, 62.089, 62.993)


def load_cifar10(folder: str, split: str = "train") -> List[LabeledImage]:
    """Parse CIFAR-10 binary batches → BGR HWC LabeledImages, 1-based labels."""
    files = ([f"data_batch_{i}.bin" for i in range(1, 6)]
             if split == "train" else ["test_batch.bin"])
    out: List[LabeledImage] = []
    for fname in files:
        path = os.path.join(folder, fname)
        if not os.path.exists(path):
            path = os.path.join(folder, "cifar-10-batches-bin", fname)
        raw = np.fromfile(path, dtype=np.uint8).reshape(-1, 3073)
        labels = raw[:, 0].astype(np.float32) + 1.0
        rgb = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        bgr = rgb[..., ::-1].astype(np.float32)
        out.extend(LabeledImage(im, lb) for im, lb in zip(bgr, labels))
    return out


def load_glove(path: str, dim: int = 100) -> Dict[str, np.ndarray]:
    """Parse a GloVe .txt embedding file (reference
    ``pyspark/bigdl/dataset/news20.py`` get_glove_w2v)."""
    out: Dict[str, np.ndarray] = {}
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip().split(" ")
            if len(parts) != dim + 1:
                continue
            out[parts[0]] = np.asarray(parts[1:], dtype=np.float32)
    return out


def load_news20(news_dir: str) -> List[Tuple[str, int]]:
    """Parse an extracted 20-newsgroups tree → [(text, label_id)], labels
    1-based in sorted-subdirectory order (reference
    ``pyspark/bigdl/dataset/news20.py`` get_news20; downloads out of scope —
    the caller points at the extracted ``20_newsgroups`` directory)."""
    texts: List[Tuple[str, int]] = []
    label_id = 0
    for name in sorted(os.listdir(news_dir)):
        path = os.path.join(news_dir, name)
        if not os.path.isdir(path):
            continue
        label_id += 1
        for fname in sorted(os.listdir(path)):
            if not fname.isdigit():
                continue
            with open(os.path.join(path, fname), encoding="latin-1") as f:
                texts.append((f.read(), label_id))
    return texts


def load_movielens(data_dir: str) -> np.ndarray:
    """Parse MovieLens ``ratings.dat`` (``::``-separated) → int array of
    (user, item, rating, timestamp) rows (reference
    ``pyspark/bigdl/dataset/movielens.py`` read_data_sets)."""
    path = os.path.join(data_dir, "ratings.dat")
    if not os.path.exists(path):
        path = os.path.join(data_dir, "ml-1m", "ratings.dat")
    with open(path, "r") as f:
        rows = [line.strip().split("::") for line in f if line.strip()]
    return np.asarray(rows).astype(np.int64)


def movielens_id_pairs(data_dir: str) -> np.ndarray:
    """(user, item) columns (reference get_id_pairs)."""
    return load_movielens(data_dir)[:, 0:2]


def movielens_id_ratings(data_dir: str) -> np.ndarray:
    """(user, item, rating) columns (reference get_id_ratings)."""
    return load_movielens(data_dir)[:, 0:3]


# ---------------------------------------------------------------------------
# synthetic data (tests + perf harnesses)
# ---------------------------------------------------------------------------

def synthetic_images(n: int, channels: int, height: int, width: int,
                     n_classes: int, seed: int = 1) -> List[LabeledImage]:
    rng = np.random.RandomState(seed)
    data = rng.uniform(0, 255, size=(n, height, width, channels)).astype(np.float32)
    labels = rng.randint(1, n_classes + 1, size=n).astype(np.float32)
    return [LabeledImage(d.squeeze() if channels == 1 else d, l)
            for d, l in zip(data, labels)]


def synthetic_separable(n: int, dim: int, n_classes: int = 2,
                        seed: int = 1):
    """Linearly separable clusters (the reference optimizer specs train tiny
    MLPs on such data, ``optim/DistriOptimizerSpec``)."""
    from bigdl_tpu.dataset.sample import Sample
    rng = np.random.RandomState(seed)
    centers = rng.uniform(-4, 4, size=(n_classes, dim)).astype(np.float32)
    labels = rng.randint(0, n_classes, size=n)
    feats = centers[labels] + rng.normal(0, 0.5, size=(n, dim)).astype(np.float32)
    return [Sample(f, np.float32(l + 1)) for f, l in zip(feats, labels)]
