"""Streaming stage-pipelined ingest engine: the real-data hot path.

Reference equivalent: ``dataset/image/MTLabeledBGRImgToBatch.scala:46`` —
the production ImageNet lesson that host-side batch prep must overlap both
itself (decode vs assemble) and device compute.  The synchronous
:class:`~bigdl_tpu.dataset.mt_batch.MTLabeledBGRImgToBatch` executes
read → decode → assemble serially *per batch* (``pool.map`` is a batch
barrier, assemble runs while the pool sits idle); BENCH_r05 measured that
structure at 0.56x of the decode-alone ceiling.  This module removes the
barriers:

    sharded seqfile readers ──► record ring ──► decode pool ──► ordered
    decoded window ──► assembler (native pack, GIL-released) ──► batch
    ring ──► consumer (── engine.BatchPrefetcher keeps N device uploads
    in flight beyond this point)

Every stage is decoupled by a bounded ring (backpressure, never unbounded
memory) and instrumented: items, busy seconds, stall seconds split into
*starve* (waiting for the upstream stage) and *backpressure* (blocked on a
full downstream ring), plus mean ring occupancy.  ``stats()`` snapshots
feed ``bench.py --ingest-only`` (``bench_ingest.json``) and the training
summary layer — the stage with high busy and low stall is the bottleneck.

Determinism contract (the part that makes this usable for training, not
just benchmarks): crop offsets / flips draw from a CLONE of the caller's
``RandomGenerator`` stream in strict record order, and each batch carries
the post-draw RNG state; the clone's position is committed back to the
caller's stream only when the batch is CONSUMED.  Pipeline read-ahead that
gets discarded (an epoch rollover replacing the chain) therefore never
advances the user-visible stream — the pipelined engine reproduces the
synchronous path's batch sequence bit for bit at every depth setting, and
epoch rollover / reshuffle stays producer-owned exactly as before
(``engine.BatchPrefetcher``'s single-drawer contract).  With MULTIPLE
engines forked from one stream (a multi-shard ``ShardedDataSet``), only
the first fork commits; the others draw decorrelated deterministic
per-shard streams (the reference's per-partition RNG model) — sync-path
bit-parity is a single-engine contract, multi-shard runs are run-to-run
deterministic.

Configuration (``bigdl.ingest.*``, see ``utils/config.py``):

===============================  =============================================
``bigdl.ingest.shards``          parallel seqfile reader threads
``bigdl.ingest.decodeWorkers``   decode pool size (default: host cores)
``bigdl.ingest.recordRingDepth`` reader → decode record ring depth
``bigdl.ingest.decodedRingDepth``in-flight decode window (default 2x batch)
``bigdl.ingest.batchRingDepth``  assembled batches buffered ahead
``bigdl.ingest.batchesInFlight`` device uploads in flight (BatchPrefetcher)
===============================  =============================================
"""

from __future__ import annotations

import os
import queue
import threading
import time
import weakref
from collections import deque
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu import telemetry
from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.utils import config

#: live engines, for the summary layer (weak: an abandoned engine must not
#: be pinned by the diagnostics that observe it)
_LIVE: "weakref.WeakSet" = weakref.WeakSet()

_END = object()          # upstream exhausted
_NO_ITEM = object()      # try_get on an empty ring

_NAME_LOCK = threading.Lock()
_NAME_SEQ = [0]          # per-process engine naming (ingest0, ingest1, …)


class StageStats:
    """Counters for one pipeline stage.

    ``items``/``busy_s`` measure the stage's own work; ``starve_s`` is time
    blocked waiting for its upstream ring, ``backpressure_s`` time blocked
    on a full downstream ring.  A stage whose starve dominates is fed too
    slowly (look upstream); one whose backpressure dominates is faster than
    its consumer (look downstream); the bottleneck stage shows near-zero
    stall and the highest busy fraction."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self.items = 0
        self.busy_s = 0.0
        self.starve_s = 0.0
        self.backpressure_s = 0.0
        self._occ_sum = 0
        self._occ_n = 0
        self._t0 = time.monotonic()

    def add(self, items: int = 0, busy_s: float = 0.0,
            starve_s: float = 0.0, backpressure_s: float = 0.0) -> None:
        with self._lock:
            self.items += items
            self.busy_s += busy_s
            self.starve_s += starve_s
            self.backpressure_s += backpressure_s

    def sample_occupancy(self, depth: int) -> None:
        with self._lock:
            self._occ_sum += depth
            self._occ_n += 1

    def snapshot(self) -> dict:
        with self._lock:
            wall = max(time.monotonic() - self._t0, 1e-9)
            return {
                "items": self.items,
                "throughput_per_sec": round(self.items / wall, 1),
                "busy_s": round(self.busy_s, 3),
                "starve_s": round(self.starve_s, 3),
                "backpressure_s": round(self.backpressure_s, 3),
                "stall_frac": round(
                    (self.starve_s + self.backpressure_s) / wall, 3),
                "mean_queue_depth": round(self._occ_sum / self._occ_n, 2)
                if self._occ_n else 0.0,
            }


class _Ring:
    """Bounded stage-coupling queue with stall accounting.

    ``put`` charges blocked time to the producing stage's ``backpressure_s``
    (a full ring means the downstream stage is the bottleneck); ``get``
    charges the consuming stage's ``starve_s``.  Both poll a stop event so
    teardown can never deadlock a stage thread."""

    def __init__(self, depth: int, producer: Optional[StageStats] = None,
                 consumer: Optional[StageStats] = None):
        self.q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
        self._producer = producer
        self._consumer = consumer

    def put(self, item, stop: Optional[threading.Event]) -> bool:
        t0 = None
        while stop is None or not stop.is_set():
            try:
                self.q.put(item, timeout=0.05)
                if t0 is not None and self._producer is not None:
                    self._producer.add(backpressure_s=time.monotonic() - t0)
                if self._producer is not None:
                    self._producer.sample_occupancy(self.q.qsize())
                return True
            except queue.Full:
                if t0 is None:
                    t0 = time.monotonic()
        if t0 is not None and self._producer is not None:
            self._producer.add(backpressure_s=time.monotonic() - t0)
        return False

    def get(self, stop: Optional[threading.Event]):
        t0 = None
        while stop is None or not stop.is_set():
            try:
                item = self.q.get(timeout=0.05)
                if t0 is not None and self._consumer is not None:
                    self._consumer.add(starve_s=time.monotonic() - t0)
                return item
            except queue.Empty:
                if t0 is None:
                    t0 = time.monotonic()
        if t0 is not None and self._consumer is not None:
            self._consumer.add(starve_s=time.monotonic() - t0)
        return _NO_ITEM

    def try_get(self):
        try:
            return self.q.get_nowait()
        except queue.Empty:
            return _NO_ITEM

    def drain(self) -> None:
        try:
            while True:
                self.q.get_nowait()
        except queue.Empty:
            pass


class ShardedSeqFileReader:
    """Parallel SequenceFile record source preserving the global order.

    ``shards`` reader threads (``bigdl.ingest.shards``) own the ``*.seq``
    files round-robin and stream records into per-shard rings; the merge
    side drains one file at a time in sorted-walk order, so the yielded
    sequence is byte-identical to a sequential
    :func:`~bigdl_tpu.dataset.seqfile.read_image_seqfile` sweep — sharding
    is a latency detail, not an ordering change.  IO and vint/frame parsing
    for file k+1..k+shards overlap the consumer's handling of file k."""

    def __init__(self, path: str, shards: Optional[int] = None,
                 ring_depth: Optional[int] = None):
        if os.path.isdir(path):
            self.files: List[str] = []
            for root, _, files in sorted(os.walk(path)):
                for fname in sorted(files):
                    if fname.endswith(".seq"):
                        self.files.append(os.path.join(root, fname))
        else:
            self.files = [path]
        self.shards = max(1, shards if shards is not None
                          else config.get_int("bigdl.ingest.shards", 2))
        self.ring_depth = (ring_depth if ring_depth is not None
                           else config.get_int("bigdl.ingest.recordRingDepth", 256))
        self.stats = StageStats("seqfile_read")

    def __iter__(self) -> Iterator:
        from bigdl_tpu.dataset.image import LabeledImageBytes
        from bigdl_tpu.dataset.seqfile import read_image_seqfile

        if not self.files:
            return
        n = min(self.shards, len(self.files))
        stop = threading.Event()
        rings = [_Ring(max(1, self.ring_depth // n), producer=self.stats)
                 for _ in range(n)]
        file_end = object()

        def reader(si: int) -> None:
            try:
                for fi in range(si, len(self.files), n):
                    t0 = time.monotonic()
                    for name, label, data in read_image_seqfile(
                            self.files[fi]):
                        t1 = time.monotonic()
                        self.stats.add(items=1, busy_s=t1 - t0)
                        telemetry.add_span_s("ingest/seqfile_read", t0, t1)
                        if not rings[si].put(
                                LabeledImageBytes(name, label, data), stop):
                            return
                        t0 = time.monotonic()
                    if not rings[si].put(file_end, stop):
                        return
            except BaseException as e:  # surfaced on the merge side
                rings[si].put(e, stop)

        threads = [threading.Thread(target=reader, args=(si,), daemon=True,
                                    name=f"ingest-seqread{si}")
                   for si in range(n)]
        for t in threads:
            t.start()
        try:
            for fi in range(len(self.files)):
                ring = rings[fi % n]
                while True:
                    item = ring.get(None)
                    if item is file_end:
                        break
                    if isinstance(item, BaseException):
                        raise item
                    yield item
        finally:
            stop.set()
            for ring in rings:
                ring.drain()
            for t in threads:
                t.join(timeout=5)
            for ring in rings:
                ring.drain()


class StreamingIngest(Transformer):
    """Compressed byte records → MiniBatches, stage-pipelined.

    Drop-in pipelined replacement for
    :class:`~bigdl_tpu.dataset.mt_batch.MTLabeledBGRImgToBatch` (same
    constructor surface, same output semantics — asserted bit-identical by
    ``tests/test_prefetch_determinism.py``), with the per-batch barriers
    removed:

    - a *reader* thread pulls upstream records into a bounded record ring;
    - a *decode pool* (``decode_workers`` threads; cv2/PIL JPEG decode
      releases the GIL) holds a sliding window of in-flight decodes that
      spans batch boundaries — decode of batch k+1 proceeds while batch k
      is being packed;
    - an *assembler* thread consumes decoded images in strict record
      order, draws crop/flip from the (cloned) RNG stream, and packs full
      batches with the native std::thread assembler (ctypes releases the
      GIL for the call, so packing overlaps the pool);
    - assembled MiniBatches buffer in a bounded *batch ring* the consumer
      drains, each carrying the RNG state to commit on consumption.

    Ring depths and pool width default from ``bigdl.ingest.*``; constructor
    arguments override per instance.
    """

    def __init__(self, batch_size: int, crop: Tuple[int, int] = (224, 224),
                 mean: Sequence[float] = (104.0, 117.0, 123.0),
                 std: Sequence[float] = (1.0, 1.0, 1.0),
                 random_crop: bool = True, hflip: bool = True,
                 device_normalize: bool = False,
                 decode_workers: Optional[int] = None,
                 record_ring_depth: Optional[int] = None,
                 decoded_ring_depth: Optional[int] = None,
                 batch_ring_depth: Optional[int] = None,
                 assemble_threads: Optional[int] = None,
                 name: Optional[str] = None):
        if name is None:
            with _NAME_LOCK:
                name = f"ingest{_NAME_SEQ[0]}"
                _NAME_SEQ[0] += 1
        # distinguishes this engine's summary tags / log lines when more
        # than one engine is alive (train + validation pipelines, …)
        self.name = name
        self.batch_size = batch_size
        self.crop = crop
        self.mean, self.std = mean, std
        self.random_crop, self.hflip = random_crop, hflip
        self.device_normalize = device_normalize
        cores = max(1, os.cpu_count() or 1)
        self.decode_workers = (decode_workers if decode_workers is not None
                               else config.get_int("bigdl.ingest.decodeWorkers",
                                                   cores))
        self.record_ring_depth = (
            record_ring_depth if record_ring_depth is not None
            else config.get_int("bigdl.ingest.recordRingDepth", 256))
        self.decoded_ring_depth = (
            decoded_ring_depth if decoded_ring_depth is not None
            else config.get_int("bigdl.ingest.decodedRingDepth",
                                 2 * batch_size))
        self.batch_ring_depth = (
            batch_ring_depth if batch_ring_depth is not None
            else config.get_int("bigdl.ingest.batchRingDepth", 2))
        self.assemble_threads = assemble_threads or cores
        # per-run stage stats: a ShardedDataSet applies ONE transformer
        # instance to every shard, so several runs can be live at once —
        # each run appends its own dict and stats() merges them
        self._active_stats: List[dict] = []
        self._last_stats: Optional[dict] = None

    # ---- diagnostics ----------------------------------------------------

    def has_active_run(self) -> bool:
        """True while at least one pipeline run of this engine is live."""
        return bool(self._active_stats)

    def stats(self) -> dict:
        """Per-stage snapshots: the merge of every ACTIVE run (multi-shard
        pipelines sum their counters), else the last finished run."""
        runs = list(self._active_stats)
        if not runs and self._last_stats is not None:
            runs = [self._last_stats]
        if not runs:
            return {}
        if len(runs) == 1:
            return {name: s.snapshot() for name, s in runs[0].items()}
        out = {}
        for name in ("read", "decode", "assemble", "consume"):
            snaps = [r[name].snapshot() for r in runs if name in r]
            if not snaps:
                continue
            n = len(snaps)
            out[name] = {
                "items": sum(s["items"] for s in snaps),
                "throughput_per_sec": round(
                    sum(s["throughput_per_sec"] for s in snaps), 1),
                "busy_s": round(sum(s["busy_s"] for s in snaps), 3),
                "starve_s": round(sum(s["starve_s"] for s in snaps), 3),
                "backpressure_s": round(
                    sum(s["backpressure_s"] for s in snaps), 3),
                "stall_frac": round(
                    sum(s["stall_frac"] for s in snaps) / n, 3),
                "mean_queue_depth": round(
                    sum(s["mean_queue_depth"] for s in snaps) / n, 2),
            }
        return out

    # ---- the pipeline ---------------------------------------------------

    def __call__(self, it: Iterator) -> Iterator:
        from concurrent.futures import ThreadPoolExecutor
        from bigdl_tpu.dataset.mt_batch import (MTLabeledBGRImgToBatch,
                                                _check_crop_fits,
                                                assemble_batch,
                                                assemble_batch_u8)
        from bigdl_tpu.dataset.sample import MiniBatch
        from bigdl_tpu.utils.random_generator import RandomGenerator

        stats = {name: StageStats(name)
                 for name in ("read", "decode", "assemble", "consume")}
        self._active_stats.append(stats)
        _LIVE.add(self)

        # the caller's stream is CLONED, not handed off: the assembler
        # draws from the clone in record order, and each batch carries the
        # clone's post-draw state — committed to the shared instance only
        # when the consumer takes the batch.  Read-ahead discarded at an
        # epoch rollover never advances the user-visible stream, so the
        # pipelined sequence stays bit-identical to the synchronous path
        # regardless of ring depths or how far ahead the engine ran.
        #
        # Multiple engines on ONE stream (a ShardedDataSet applies the
        # transformer per shard and the driver pulls the shard iterators
        # alternately): only the FIRST active fork is the stream's
        # committer — secondaries draw from a deterministically reseeded
        # fork (decorrelated per-shard augmentation, the reference's
        # per-partition RNG model, ``dataset/DataSet.scala:262``) and
        # never commit, so alternating consumption cannot interleave
        # incoherent positions onto the caller's stream.  Synchronous-path
        # bit-parity is therefore a SINGLE-engine contract; multi-shard
        # runs are run-to-run deterministic instead.
        shared_rng = RandomGenerator.RNG()
        active_forks = shared_rng.__dict__.setdefault("_ingest_forks", set())
        # secondary forks are numbered by how many forks are already
        # active — NOT a global counter, so re-running the same pipeline
        # derives the identical per-shard seeds
        fork_rank = len(active_forks)
        fork_token = object()
        primary = fork_rank == 0
        active_forks.add(fork_token)
        drawer = RandomGenerator(0)
        drawer.np.set_state(shared_rng.np.get_state())
        if not primary:
            # decorrelate the secondary fork: seed from the fork point +
            # the fork rank, so each shard's stream is distinct but every
            # run derives the identical sequence
            mix = int(np.asarray(shared_rng.np.get_state()[1],
                                 np.uint64).sum())
            drawer.set_seed((mix ^ (0x9E3779B1 * fork_rank)) % (2 ** 31))

        stop = threading.Event()
        record_ring = _Ring(self.record_ring_depth,
                            producer=stats["read"],
                            consumer=stats["assemble"])
        batch_ring = _Ring(self.batch_ring_depth,
                           producer=stats["assemble"],
                           consumer=stats["consume"])
        pool = ThreadPoolExecutor(self.decode_workers,
                                  thread_name_prefix="ingest-decode")
        ch, cw = self.crop

        def reader() -> None:
            """Pull upstream records into the record ring.  The upstream
            iterator draws no host RNG (crop/flip belongs to the assembler;
            reshuffles to the training driver's producer), so running it on
            its own thread keeps the single-drawer contract intact."""
            try:
                t0 = time.monotonic()
                for rec in it:
                    t1 = time.monotonic()
                    stats["read"].add(items=1, busy_s=t1 - t0)
                    telemetry.add_span_s("ingest/read", t0, t1)
                    if not record_ring.put(rec, stop):
                        return
                    t0 = time.monotonic()
                record_ring.put(_END, stop)
            except BaseException as e:  # surface downstream
                record_ring.put(e, stop)

        def timed_decode(data: bytes) -> np.ndarray:
            t0 = time.monotonic()
            img = MTLabeledBGRImgToBatch._decode(data)
            t1 = time.monotonic()
            stats["decode"].add(items=1, busy_s=t1 - t0)
            telemetry.add_span_s("ingest/decode", t0, t1)
            return img

        def assembler() -> None:
            pending: "deque" = deque()   # (record, decode future), in order
            done = [False]

            def fill(block: bool) -> None:
                """Top up the in-flight decode window.  Blocking only when
                the window is empty keeps the assembler from stalling on a
                slow upstream while it still has decoded work to pack."""
                while not done[0] and len(pending) < self.decoded_ring_depth:
                    rec = (record_ring.get(stop) if block and not pending
                           else record_ring.try_get())
                    if rec is _NO_ITEM:
                        if block and not pending:
                            done[0] = True    # stop was set mid-get
                        return
                    if rec is _END:
                        done[0] = True
                        return
                    if isinstance(rec, BaseException):
                        done[0] = True
                        pending.append((None, rec))
                        return
                    pending.append((rec, pool.submit(timed_decode,
                                                     rec.bytes)))

            imgs: List[np.ndarray] = []
            recs: List = []
            offsets: List[Tuple[int, int]] = []
            flips: List[int] = []

            def emit() -> bool:
                t0 = time.monotonic()
                offs = np.asarray(offsets, np.int32).reshape(len(imgs), 2)
                fl = np.asarray(flips, np.uint8)
                if self.device_normalize:
                    x = assemble_batch_u8(imgs, self.crop, offs, fl,
                                          n_threads=self.assemble_threads)
                else:
                    x = assemble_batch(imgs, self.crop, offs, fl,
                                       self.mean, self.std,
                                       n_threads=self.assemble_threads)
                y = np.asarray([r.label for r in recs], np.float32)
                t1 = time.monotonic()
                stats["assemble"].add(items=len(imgs), busy_s=t1 - t0)
                telemetry.add_span_s("ingest/assemble", t0, t1,
                                     {"batch": len(imgs)})
                ok = batch_ring.put(
                    (MiniBatch(x, y), drawer.np.get_state()), stop)
                imgs.clear(), recs.clear(), offsets.clear(), flips.clear()
                return ok

            try:
                while True:
                    fill(block=True)
                    if not pending:
                        break
                    rec, fut = pending.popleft()
                    if rec is None:      # upstream error, in order
                        raise fut
                    if fut.done():
                        img = fut.result()
                    else:                # wait-on-decode = assemble starve
                        t0 = time.monotonic()
                        img = fut.result()
                        stats["assemble"].add(
                            starve_s=time.monotonic() - t0)
                    fill(block=False)    # decode of the NEXT batch proceeds
                    _check_crop_fits(
                        [img], self.crop,
                        describe=lambda _i: (
                            f"StreamingIngest: record {len(imgs)} of the "
                            f"current batch (label {rec.label})"))
                    # crop/flip draws in strict record order — the same
                    # draw sequence MTLabeledBGRImgToBatch makes, just
                    # without the batch barrier
                    h, w = img.shape[:2]
                    if self.random_crop:
                        oy = drawer.random_int(0, h - ch + 1)
                        ox = drawer.random_int(0, w - cw + 1)
                    else:
                        oy, ox = (h - ch) // 2, (w - cw) // 2
                    fl = int(drawer.uniform() < 0.5) if self.hflip else 0
                    imgs.append(img if img.ndim == 3 else img[:, :, None])
                    recs.append(rec)
                    offsets.append((oy, ox))
                    flips.append(fl)
                    if len(imgs) == self.batch_size:
                        if not emit():
                            return
                if imgs:
                    if not emit():
                        return
                batch_ring.put(_END, stop)
            except BaseException as e:  # surface at the consumer
                batch_ring.put(e, stop)

        reader_t = threading.Thread(target=reader, daemon=True,
                                    name="ingest-reader")
        asm_t = threading.Thread(target=assembler, daemon=True,
                                 name="ingest-assembler")
        reader_t.start()
        asm_t.start()
        try:
            while True:
                # blocked time inside get() is charged to consume.starve_s
                # by the ring itself
                item = batch_ring.get(None)
                if item is _END:
                    return
                if isinstance(item, BaseException):
                    raise item
                batch, rng_state = item
                if primary:
                    # commit the drawn-through position: the caller's
                    # stream advances exactly as far as the batches it
                    # actually took
                    shared_rng.np.set_state(rng_state)
                stats["consume"].add(items=1)
                yield batch
        finally:
            active_forks.discard(fork_token)
            for i, run in enumerate(self._active_stats):
                if run is stats:
                    del self._active_stats[i]
                    break
            self._last_stats = stats
            stop.set()
            # cancel queued decodes so teardown never waits on work whose
            # output nobody will read (mirrors the MT transformer fix)
            pool.shutdown(wait=False, cancel_futures=True)
            for ring in (record_ring, batch_ring):
                ring.drain()
            reader_t.join(timeout=5)
            asm_t.join(timeout=5)
            # a final put can land between the first drain and the join —
            # drain again so no full batch stays pinned in the ring
            for ring in (record_ring, batch_ring):
                ring.drain()


def summary_scalars():
    """(tag, value) pairs for the training summary: per-stage throughput,
    stall fraction, and ring occupancy of every engine with an ACTIVE run
    (idle engines from finished pipelines are excluded — their stale final
    counters must not pollute a later run's series).  Tags always include
    the engine's ``name`` so the series stays stable when a second engine
    (a validation pipeline) goes live mid-run."""
    out = []
    for eng in sorted((e for e in _LIVE if e.has_active_run()),
                      key=lambda e: e.name):
        prefix = f"Ingest/{eng.name}"
        for stage, snap in eng.stats().items():
            out.append((f"{prefix}/{stage}/throughput",
                        snap["throughput_per_sec"]))
            out.append((f"{prefix}/{stage}/stall_frac", snap["stall_frac"]))
            if snap["mean_queue_depth"]:
                out.append((f"{prefix}/{stage}/queue_depth",
                            snap["mean_queue_depth"]))
    return out


# the engine's scalars flow through the telemetry registry's single flush
# path: the driver's one emission loop pulls this provider instead of
# special-casing the ingest module (tags unchanged — Ingest/<name>/...)
telemetry.REGISTRY.register_provider("ingest", summary_scalars)
